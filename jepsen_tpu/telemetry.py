"""Pipeline telemetry spine: span tracing + the unified metrics registry.

The r05 roofline said the checker is dispatch/latency-bound (`hbm_util`
0.0018) — yet nothing could *show* where wall-clock goes between XLA
calls: timing lived in ad-hoc per-subsystem stats dicts
(``BucketScheduler.stats``, ``AOT_STATS``, WAL flush deques, run
resilience counters) that never composed into one picture. Following
the trace-level-observability argument of OmniLink (arXiv:2601.11836)
— live validation of an unmodified system needs its traces — this
module is the one spine every stage reports through:

  * **span tracer** — a process-wide, thread-local span stack. A span
    is an interval with a name, a category (``"device"`` for dispatch
    launches and device waits, ``"host"`` for everything else), and
    attributes (W class, rows, chunk ordinal, fuse-group id,
    provenance...). Completed spans land in a monotonic-clock
    ring-buffer flight recorder (bounded; the newest ``ring`` spans
    survive) and, when a sink path is configured, append to a JSONL
    trace file. ``export_chrome`` writes the standard Chrome-trace /
    Perfetto ``trace.json`` (load it at chrome://tracing or
    ui.perfetto.dev). Instant ``event``s record point occurrences
    (retries, bisections, quarantines, campaign resumes).

  * **metrics registry** — counters / gauges / histograms with labels,
    lock-protected, snapshot-to-dict (``REGISTRY``). The registry is
    ALWAYS on (increments are a dict bump under a lock — the
    scattered per-subsystem counters it replaces cost the same without
    the thread safety); only the span tracer is gated.

  * **dispatch-gap analyzer** (``gaps``) — the direct diagnostic for
    the 1.9k/s plateau: over a window of recorded spans, the union of
    ``"device"``-category intervals is the device-active time; the
    complement is host gap, and each gap is attributed to the host
    spans overlapping it. The bench's ``telemetry`` section reports
    the fractions and the top gap causes.

Enabling: ``JT_TRACE=1`` turns the tracer on (flight recorder only);
``JT_TRACE=<path>`` additionally streams every record to ``<path>`` as
JSONL (the ``jepsen-tpu trace`` subcommand summarizes/exports such a
file). Unset or ``0``: every ``span()``/``event()`` call is a no-op
returning a shared singleton — no Span object, no record, nothing
retained — so the instrumented hot paths cost one predicate each.
``JT_TRACE_RING`` sizes the flight recorder (default 65536 spans).

Metric naming scheme (doc/observability.md): dotted
``subsystem.metric`` names plus sorted ``{label=value}`` suffixes —
``scheduler.retries{family=wgl}``, ``aot.hits``, ``wal.flush_ms``
(histogram), ``run.barrier_timeouts``, ``journal.rows``. Snapshots are
deterministic: keys sort, floats round, and two snapshots of the same
state compare equal — ``store.save_results`` merges one canonical
``telemetry`` block into ``results.json`` from it.

The cluster observability plane (doc/observability.md "The cluster
plane") builds three layers on this spine:

  * **correlation ids** — every span/event record carries the active
    correlation id (``corr``): the campaign id for fleet units, the
    tenant key + writer incarnation for online/service tenants, the
    run dir for plain runs. A process-wide default
    (``set_correlation`` / $JT_CORR, inherited by spawned workers)
    plus a thread-local override (``correlation_scope``) mean a child
    worker's spans inherit the id that names the cluster-level unit
    of work, so ``merge_traces`` can lay N workers' traces on one
    timeline and draw flow arrows between the spans that belong to
    the same tenant/campaign.
  * **OpenMetrics export** — ``openmetrics(snapshot)`` renders any
    registry snapshot (live or series-merged) in the Prometheus text
    exposition format; ``web.py /metrics`` and ``jepsen-tpu metrics``
    serve it. Histograms carry real cumulative ``le`` buckets
    (maintained incrementally in ``observe`` — the reservoir only
    feeds p50/p99), so a scrape is a first-class histogram, not a
    summary impostor.
  * **series / alerts** — ``telemetry.series`` (durable per-worker
    snapshot frames under ``store/telemetry/``) and
    ``telemetry.alerts`` (the SLO burn-rate evaluator over them) are
    sibling modules re-exported here.
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

# ------------------------------------------------------------- config

_CONF_LOCK = threading.Lock()
_ENABLED = False
_SINK_PATH: Optional[str] = None
_SINK = None                    # open file handle (lazy)
_RING: deque = deque(maxlen=65536)
_CONFIGURED = False

# Trace epoch: all timestamps are monotonic ns relative to this, so
# records from one process compose and export without clock skew.
_EPOCH_NS = time.monotonic_ns()

_TLS = threading.local()
_IDS = iter(range(1, 1 << 62)).__next__
_ID_LOCK = threading.Lock()

# Correlation id: the cluster-level unit of work this process's (or
# this thread's) spans belong to. Process default inherits $JT_CORR so
# spawned fleet/service workers carry their parent's campaign id
# without any code in between; a thread-local stack overrides it for
# per-tenant scopes inside one multi-tenant daemon.
_CORR: Optional[str] = os.environ.get("JT_CORR") or None

# Per-sink wall-clock anchor: the first record appended to a JSONL
# sink additionally carries ``wall_s`` (time.time() at emit), so
# merge_traces can align N processes' monotonic-relative timestamps
# onto one wall-clock axis. Reset whenever the sink is reconfigured.
_SINK_ANCHORED = False


def set_correlation(cid: Optional[str]) -> Optional[str]:
    """Set the PROCESS-default correlation id (None clears). Returns
    the previous value so callers can restore it (runtime.run does:
    the run-dir id must not leak past the run, and must not clobber a
    campaign id a fleet worker already installed)."""
    global _CORR
    prev = _CORR
    _CORR = cid
    return prev


def correlation() -> Optional[str]:
    """The active correlation id: the innermost ``correlation_scope``
    on THIS thread, else the process default."""
    stack = getattr(_TLS, "corr", None)
    if stack:
        return stack[-1]
    return _CORR


@contextmanager
def correlation_scope(cid: Optional[str]):
    """Thread-local correlation override — the per-tenant scope a
    multi-tenant daemon wraps around one tenant's check/finalize so
    every span underneath (encode, dispatch, decode...) inherits the
    tenant's id while a sibling tenant's spans carry its own."""
    stack = getattr(_TLS, "corr", None)
    if stack is None:
        stack = _TLS.corr = []
    stack.append(cid)
    try:
        yield
    finally:
        stack.pop()


def _next_id() -> int:
    with _ID_LOCK:
        return _IDS()


def _ring_size() -> int:
    try:
        return max(16, int(os.environ.get("JT_TRACE_RING", "65536")))
    except ValueError:
        return 65536


def configure(trace=None, ring: Optional[int] = None) -> None:
    """(Re)configure the tracer. ``trace``: True (recorder only), a
    path (recorder + JSONL sink), False/None/"0" (off), or "env" to
    re-read $JT_TRACE. Reconfiguring swaps in a fresh ring buffer and
    closes any open sink — the test/bench seam."""
    global _ENABLED, _SINK_PATH, _SINK, _RING, _CONFIGURED, \
        _SINK_ANCHORED
    with _CONF_LOCK:
        _SINK_ANCHORED = False
        if trace == "env":
            trace = os.environ.get("JT_TRACE")
            if trace in (None, "", "0"):
                trace = False
            elif trace == "1":
                trace = True
        if _SINK is not None:
            try:
                _SINK.close()
            except Exception:
                pass
            _SINK = None
        _SINK_PATH = None
        if trace in (None, False, "", "0"):
            _ENABLED = False
        elif trace is True or trace == "1":
            _ENABLED = True
        else:
            _ENABLED = True
            _SINK_PATH = str(trace)
        _RING = deque(maxlen=_ring_size() if ring is None else max(16,
                                                                   ring))
        _CONFIGURED = True


def _ensure_config() -> None:
    if not _CONFIGURED:
        configure("env")


def enabled() -> bool:
    """Is the span tracer on? The one predicate the instrumented hot
    paths pay when tracing is off."""
    _ensure_config()
    return _ENABLED


def _emit(rec: dict) -> None:
    """Record one completed span/event: ring buffer always, sink when
    configured. Sink writes are whole-line appends under the config
    lock — records from retire/prewarm threads never interleave. The
    first record a sink sees is additionally stamped with ``wall_s``
    (the merge_traces cross-process clock anchor)."""
    global _SINK, _SINK_ANCHORED
    _RING.append(rec)
    if _SINK_PATH is None:
        return
    with _CONF_LOCK:
        try:
            if _SINK is None:
                _SINK = open(_SINK_PATH, "a")
            if not _SINK_ANCHORED:
                # wall_s names the wall-clock instant whose trace-
                # relative coordinate is wall_ts (NOT this record's
                # ts, which is its span's start): both are sampled at
                # the same emit instant, so the pair is skew-free.
                rec = {**rec, "wall_s": round(time.time(), 6),
                       "wall_ts": (time.monotonic_ns() - _EPOCH_NS)
                       / 1e3,
                       "pid": os.getpid()}
                _SINK_ANCHORED = True
            _SINK.write(json.dumps(rec, default=str) + "\n")
            _SINK.flush()
        except Exception:
            pass                 # tracing is diagnostics, never a fault


def flush() -> None:
    """Flush/close the JSONL sink (idempotent; reopens on next emit)."""
    global _SINK
    with _CONF_LOCK:
        if _SINK is not None:
            try:
                _SINK.close()
            except Exception:
                pass
            _SINK = None


# --------------------------------------------------------------- spans

class Span:
    """One in-flight interval. Created by ``begin``/``span``; ``end``
    completes it and emits the record. Attribute updates before end
    ride ``set(**attrs)`` (e.g. a count only known at the end)."""

    __slots__ = ("name", "cat", "t0", "attrs", "sid", "parent", "corr",
                 "_done")

    def __init__(self, name: str, cat: str, attrs: Optional[dict],
                 parent: Optional[int]):
        self.name = name
        self.cat = cat
        self.t0 = time.monotonic_ns()
        self.attrs = attrs
        self.sid = _next_id()
        self.parent = parent
        # Captured at creation: end() may run after the enclosing
        # correlation_scope already popped.
        self.corr = correlation()
        self._done = False

    def set(self, **attrs) -> "Span":
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        t1 = time.monotonic_ns()
        stack = getattr(_TLS, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        t = threading.current_thread()
        rec = {"ph": "X", "name": self.name, "cat": self.cat,
               "ts": (self.t0 - _EPOCH_NS) / 1e3,
               "dur": (t1 - self.t0) / 1e3,
               "tid": t.ident, "tname": t.name,
               "id": self.sid}
        if self.parent is not None:
            rec["parent"] = self.parent
        if self.corr is not None:
            rec["corr"] = self.corr
        if self.attrs:
            rec["args"] = self.attrs
        _emit(rec)

    # context-manager protocol
    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.end()


class _NopSpan:
    """The disabled-tracer singleton: every operation is a no-op, and
    ``span()``/``begin()`` return THIS object — no allocation, no
    record, no state. ``set`` discards its kwargs."""

    __slots__ = ()

    def set(self, **attrs) -> "_NopSpan":
        return self

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOP = _NopSpan()


def begin(name: str, /, cat: str = "host", **attrs):
    """Open a span (pushes the thread-local stack); caller must
    ``end()`` it. Use for intervals that outlive a lexical scope (a
    generator's whole drive); ``span`` is the with-statement form."""
    if not enabled():
        return NOP
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    parent = stack[-1].sid if stack else None
    sp = Span(name, cat, attrs or None, parent)
    stack.append(sp)
    return sp


def span(name: str, /, cat: str = "host", **attrs):
    """Context manager: ``with telemetry.span("encode", W=9): ...``.
    Nested spans record their parent (the enclosing span on THIS
    thread). When tracing is off, returns the shared no-op singleton."""
    if not enabled():
        return NOP
    return begin(name, cat, **attrs)


def event(name: str, /, cat: str = "event", **attrs) -> None:
    """Instant occurrence (retry, bisection, quarantine, resume...)."""
    if not enabled():
        return
    t = threading.current_thread()
    rec = {"ph": "i", "name": name, "cat": cat,
           "ts": (time.monotonic_ns() - _EPOCH_NS) / 1e3,
           "tid": t.ident, "tname": t.name}
    corr = correlation()
    if corr is not None:
        rec["corr"] = corr
    if attrs:
        rec["args"] = attrs
    _emit(rec)


def spans() -> List[dict]:
    """The flight recorder's current contents (oldest first)."""
    _ensure_config()
    return list(_RING)


def reset() -> None:
    """Drop recorded spans (keeps the enabled/sink configuration)."""
    _ensure_config()
    _RING.clear()


# ------------------------------------------------------------- export

def export_chrome(path, records: Optional[Sequence[dict]] = None) -> int:
    """Write records (default: the flight recorder) as a Chrome-trace /
    Perfetto ``trace.json``. Returns the number of trace events.

    Accepts both raw single-process records and ``merge_traces``
    output: records may carry their own ``pid`` (per-worker process
    lanes), ``"M"`` metadata records (process/thread names) pass
    through, and flow records (``ph`` s/t/f — the correlation-id
    arrows) keep their binding id. Malformed records — an unclosed
    span a ring wrap orphaned, a torn line's partial dict — degrade to
    defaults; an export must never crash on its input."""
    recs = list(records) if records is not None else spans()
    pid = os.getpid()
    evs = []
    tnames: Dict[tuple, str] = {}
    for r in recs:
        if not isinstance(r, dict):
            continue
        ph = r.get("ph", "X")
        rpid = r.get("pid", pid)
        if ph == "M":
            evs.append({"name": r.get("name", "?"), "ph": "M",
                        "pid": rpid, "tid": r.get("tid", 0),
                        "args": r.get("args") or {}})
            continue
        args = dict(r.get("args") or {})
        if r.get("corr") is not None:
            args.setdefault("corr", r["corr"])
        ev = {"name": r.get("name", "?"), "cat": r.get("cat", "host"),
              "ph": ph, "ts": r.get("ts", 0.0),
              "pid": rpid, "tid": r.get("tid", 0),
              "args": args}
        if ph == "X":
            ev["dur"] = r.get("dur", 0.0)
        elif ph in ("s", "t", "f"):
            ev["id"] = r.get("id", 0)
            if ph == "f":
                ev["bp"] = "e"         # bind to the enclosing slice
        else:
            ev["s"] = "t"              # thread-scoped instant
        evs.append(ev)
        key = (rpid, r.get("tid"))
        if r.get("tname") and key not in tnames:
            tnames[key] = r["tname"]
    for (rpid, tid), tname in tnames.items():
        evs.append({"name": "thread_name", "ph": "M", "pid": rpid,
                    "tid": tid, "args": {"name": tname}})
    with open(path, "w") as f:
        # default=str matches the JSONL sink's _emit: attrs may carry
        # numpy scalars or other non-JSON-native values, and an export
        # must degrade them to strings, never crash.
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f,
                  default=str)
    return len(evs)


def read_trace(path) -> List[dict]:
    """Load a JSONL trace file (the sink format), tolerating a torn
    final line the way every other log reader here does."""
    out: List[dict] = []
    with open(path, "rb") as f:
        for line in f:
            if not line.endswith(b"\n"):
                break
            try:
                out.append(json.loads(line))
            except Exception:
                break
    return out


def merge_traces(paths: Sequence) -> List[dict]:
    """Fuse N per-worker JSONL traces into ONE record list on a
    common timeline — the cross-worker correlation view
    (``jepsen-tpu trace --merge DIR``).

    Each worker's timestamps are monotonic-relative to its own
    process epoch; the sink's first record carries a (wall_s,
    wall_ts) anchor pair sampled at one instant, so every file's
    records shift onto a shared wall-clock axis (a file with no
    anchor — an old-format sink — keeps its relative times). Every
    record gets the worker's ``pid`` lane (the sink-stamped pid when
    present, else a per-file ordinal) plus a ``process_name``
    metadata record naming the source file, so Chrome/Perfetto
    renders one lane per worker. Records sharing a correlation id
    across DIFFERENT workers additionally grow flow events (ph
    s/t/f, one chain per corr id): the takeover arrows — a killed
    worker's tenant spans connect to the survivor's.
    """
    def _anchor(r):
        """(origin-µs, pid) of an anchor record, or None. A sink can
        hold SEVERAL anchors: a restarted worker reusing the same
        JT_TRACE path appends a fresh anchor (configure resets
        _SINK_ANCHORED) with a new monotonic epoch and pid — each
        incarnation's records must shift by ITS anchor, not the first
        boot's, or they render hours off in a dead pid's lane."""
        if "wall_s" not in r or "wall_ts" not in r:
            return None
        try:
            return (float(r["wall_s"]) * 1e6 - float(r["wall_ts"]),
                    r.get("pid"))
        except (TypeError, ValueError):
            return None

    per_file: List[List[dict]] = [read_trace(p) for p in paths]
    # Re-base onto the earliest anchored origin so merged timestamps
    # start near zero (Chrome renders huge absolute µs poorly).
    known = [a[0] for recs in per_file
             for a in (_anchor(r) for r in recs) if a is not None]
    base = min(known) if known else 0.0
    merged: List[dict] = []
    by_corr: Dict[str, List[dict]] = {}
    for i, (p, recs) in enumerate(zip(paths, per_file)):
        first = next((a for a in (_anchor(r) for r in recs)
                      if a is not None), None)
        # Segment state: records before the first anchor inherit it
        # (the anchor is the file's first record by construction, but
        # stay tolerant of hand-edited sinks).
        origin = first[0] if first else None
        pid = first[1] if first and isinstance(first[1], int) \
            else i + 1
        named: set = set()
        for r in recs:
            a = _anchor(r)
            if a is not None:
                origin = a[0]
                pid = a[1] if isinstance(a[1], int) else pid
            if pid not in named:
                named.add(pid)
                merged.append({"ph": "M", "name": "process_name",
                               "pid": pid, "tid": 0,
                               "args": {"name": Path(p).stem}})
            r = dict(r)
            r["pid"] = pid
            shift = (origin - base) if origin is not None else 0.0
            try:
                r["ts"] = float(r.get("ts", 0.0)) + shift
            except (TypeError, ValueError):
                r["ts"] = shift
            merged.append(r)
            corr = r.get("corr")
            if corr is not None and r.get("ph", "X") in ("X", "i"):
                by_corr.setdefault(str(corr), []).append(r)
    # Flow arrows only where a corr id actually crosses workers — an
    # id confined to one process is already one lane.
    for corr, recs in sorted(by_corr.items()):
        if len({r["pid"] for r in recs}) < 2:
            continue
        recs.sort(key=lambda r: r.get("ts", 0.0))
        fid = _flow_id(corr)
        for j, r in enumerate(recs):
            ph = "s" if j == 0 else ("f" if j == len(recs) - 1
                                     else "t")
            merged.append({"ph": ph, "name": f"corr:{corr}",
                           "cat": "flow", "id": fid,
                           # Nudge inside the slice so the enclosing-
                           # slice binding holds for zero-offset spans.
                           "ts": float(r.get("ts", 0.0)) + 0.01,
                           "pid": r["pid"], "tid": r.get("tid", 0)})
    merged.sort(key=lambda r: (r.get("ph") != "M",
                               r.get("ts", 0.0)))
    return merged


def _flow_id(corr: str) -> int:
    import hashlib
    return int.from_bytes(
        hashlib.sha256(corr.encode()).digest()[:4], "big")


def summarize(records: Optional[Sequence[dict]] = None) -> dict:
    """Per-name span totals over a record set (default: the flight
    recorder) — the ``jepsen-tpu trace`` summary body."""
    recs = list(records) if records is not None else spans()
    by: Dict[str, dict] = {}
    n_spans = n_events = 0
    for r in recs:
        if not isinstance(r, dict):
            continue
        ph = r.get("ph", "X")
        if ph == "i":
            n_events += 1
            continue
        if ph != "X":
            continue           # metadata / flow records: not spans
        n_spans += 1
        d = by.setdefault(r.get("name", "?"),
                          {"count": 0, "total_us": 0.0, "max_us": 0.0})
        d["count"] += 1
        dur = float(r.get("dur", 0.0))
        d["total_us"] += dur
        if dur > d["max_us"]:
            d["max_us"] = dur
    for d in by.values():
        d["total_s"] = round(d.pop("total_us") / 1e6, 6)
        d["max_us"] = round(d["max_us"], 1)
        d["mean_us"] = round(d["total_s"] * 1e6 / max(d["count"], 1), 1)
    return {"spans": n_spans, "events": n_events,
            "by_name": {k: by[k] for k in sorted(by)}}


# ------------------------------------------------- dispatch-gap report

def gaps(records: Optional[Sequence[dict]] = None, *,
         top: int = 8) -> dict:
    """Device-busy vs host-gap breakdown — the plateau diagnostic.

    Over the window spanned by ``"device"``-category spans (dispatch
    launches + device waits — the honest proxy for device activity
    this side of a hardware profiler), the union of those intervals is
    device-busy time; the complement is host gap. Each gap interval is
    attributed to the LEAF host spans overlapping it: wrapper spans
    that fully contain a device interval (``scheduler.run``,
    ``campaign.seed``, ``run.case``...) are excluded — they enclose
    every gap by construction and would always top the ranking while
    naming nothing actionable. Time no leaf span covers is
    ``(untraced)``. Returns fractions, gap count, the top causes by
    attributed seconds, and ``device_busy_by_family`` — the busy union
    broken down per backend family (the ``family=`` span attribute:
    ``wgl`` for the lax.scan kernels, ``wgl-pallas`` for the Pallas
    megakernel, ``graph`` for the MXU closure). Over a merge_traces
    record set (records carrying per-worker ``pid`` lanes) the report
    additionally attributes cluster-wide device-busy per worker AND
    per family: ``device_busy_by_worker`` is {worker: {family:
    seconds}} — which worker's device did the cluster's work, and
    through which backend."""
    recs = list(records) if records is not None else spans()
    dev = []
    host = []
    for r in recs:
        if r.get("ph") != "X":
            continue
        t0 = float(r.get("ts", 0.0))
        t1 = t0 + float(r.get("dur", 0.0))
        if r.get("cat") == "device":
            fam = (r.get("args") or {}).get("family") or "(untagged)"
            dev.append((t0, t1, fam, r.get("pid")))
        else:
            host.append((t0, t1, r.get("name", "?")))
    if not dev:
        return {"window_s": 0.0, "device_busy_s": 0.0, "host_gap_s": 0.0,
                "device_busy_frac": None, "host_gap_frac": None,
                "n_gaps": 0, "top_gap_causes": [],
                "device_busy_by_family": {},
                "device_busy_by_worker": {}}

    def _merge(ivs):
        ivs = sorted(ivs)
        out = [list(ivs[0])]
        for t0, t1 in ivs[1:]:
            if t0 <= out[-1][1]:
                out[-1][1] = max(out[-1][1], t1)
            else:
                out.append([t0, t1])
        return out

    by_fam_ivs: Dict[str, list] = {}
    by_worker_ivs: Dict[str, Dict[str, list]] = {}
    for t0, t1, fam, pid in dev:
        by_fam_ivs.setdefault(fam, []).append((t0, t1))
        if pid is not None:
            by_worker_ivs.setdefault(str(pid), {}) \
                .setdefault(fam, []).append((t0, t1))
    by_family = {
        fam: round(sum(b - a for a, b in _merge(ivs)) / 1e6, 6)
        for fam, ivs in sorted(by_fam_ivs.items())}
    by_worker = {
        w: {fam: round(sum(b - a for a, b in _merge(ivs)) / 1e6, 6)
            for fam, ivs in sorted(fams.items())}
        for w, fams in sorted(by_worker_ivs.items())}
    merged = _merge([(t0, t1) for t0, t1, _, _ in dev])
    # Leaf filter by bisect against the merged device intervals (a
    # full pairwise scan is O(hosts x devices) — minutes of CPU on a
    # default-size ring): a host span is a wrapper iff the first
    # merged interval starting at/after it also ends inside it.
    starts = [a for a, _ in merged]

    def _wrapper(h0, h1):
        i = bisect.bisect_left(starts, h0)
        return i < len(merged) and merged[i][1] <= h1

    host = [(h0, h1, name) for h0, h1, name in host
            if not _wrapper(h0, h1)]
    w0, w1 = merged[0][0], merged[-1][1]
    window = w1 - w0
    busy = sum(b - a for a, b in merged)
    gap_ivs = [(merged[i][1], merged[i + 1][0])
               for i in range(len(merged) - 1)]
    # Attribution by one event sweep (near-linear): walk gap and host
    # interval edges in time order; inside a gap, each time slice is
    # charged once to every distinct active leaf-span name, or to
    # ``(untraced)`` when none is active.
    evs: List[tuple] = []
    for a, b in gap_ivs:
        evs.append((a, 1, "\x00gap"))
        evs.append((b, 0, "\x00gap"))
    for h0, h1, name in host:
        evs.append((h0, 1, name))
        evs.append((h1, 0, name))
    evs.sort(key=lambda e: (e[0], e[1]))      # ends before starts
    causes: Dict[str, float] = {}
    active: Dict[str, int] = {}
    in_gap = 0
    gap_total = 0.0
    last_t = evs[0][0] if evs else 0.0
    for t, kind, name in evs:
        dt = t - last_t
        if dt > 0 and in_gap:
            gap_total += dt
            if active:
                for n in active:
                    causes[n] = causes.get(n, 0.0) + dt
            else:
                causes["(untraced)"] = \
                    causes.get("(untraced)", 0.0) + dt
        last_t = t
        if name == "\x00gap":
            in_gap += 1 if kind else -1
        elif kind:
            active[name] = active.get(name, 0) + 1
        else:
            if active.get(name, 0) <= 1:
                active.pop(name, None)
            else:
                active[name] -= 1
    order = sorted(causes.items(), key=lambda kv: -kv[1])[:top]
    return {
        "window_s": round(window / 1e6, 6),
        "device_busy_s": round(busy / 1e6, 6),
        "host_gap_s": round(gap_total / 1e6, 6),
        "device_busy_frac": round(busy / window, 4) if window else None,
        "host_gap_frac": round(gap_total / window, 4) if window else None,
        "n_gaps": len(gap_ivs),
        "top_gap_causes": [[name, round(s / 1e6, 6)]
                           for name, s in order],
        "device_busy_by_family": by_family,
        "device_busy_by_worker": by_worker,
    }


# ---------------------------------------------------- metrics registry

def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class _Counter:
    __slots__ = ("_reg", "_k")

    def __init__(self, reg, k):
        self._reg, self._k = reg, k

    def inc(self, n=1) -> None:
        with self._reg._lock:
            self._reg._counters[self._k] = \
                self._reg._counters.get(self._k, 0) + n


class _Gauge:
    __slots__ = ("_reg", "_k")

    def __init__(self, reg, k):
        self._reg, self._k = reg, k

    def set(self, v) -> None:
        with self._reg._lock:
            self._reg._gauges[self._k] = v


#: Fixed histogram bucket upper bounds (seconds-or-ms scale agnostic —
#: log-spaced over the latency range every recorded histogram spans).
#: Maintained incrementally in observe() so a snapshot carries REAL
#: cumulative ``le`` buckets for the Prometheus exposition; the
#: reservoir keeps feeding p50/p99 (exact over the recent window).
HIST_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0)


class _Histogram:
    __slots__ = ("_reg", "_k")

    RESERVOIR = 4096

    def __init__(self, reg, k):
        self._reg, self._k = reg, k

    def observe(self, v) -> None:
        v = float(v)
        with self._reg._lock:
            h = self._reg._hists.get(self._k)
            if h is None:
                h = self._reg._hists[self._k] = {
                    "count": 0, "sum": 0.0, "min": v, "max": v,
                    "_res": deque(maxlen=self.RESERVOIR),
                    "_b": [0] * (len(HIST_BUCKETS) + 1)}
            h["count"] += 1
            h["sum"] += v
            if v < h["min"]:
                h["min"] = v
            if v > h["max"]:
                h["max"] = v
            h["_res"].append(v)
            h["_b"][bisect.bisect_left(HIST_BUCKETS, v)] += 1


class Registry:
    """Lock-protected metrics store. Handles are cheap stateless views;
    every mutation takes the one registry lock, so concurrent bucket
    executor threads can't drop counts (the BucketScheduler.stats race
    this replaces). ``snapshot()`` is deterministic: sorted keys,
    rounded floats, plain JSON types."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, object] = {}
        self._hists: Dict[str, dict] = {}

    def counter(self, name: str, **labels) -> _Counter:
        return _Counter(self, _key(name, labels))

    def gauge(self, name: str, **labels) -> _Gauge:
        return _Gauge(self, _key(name, labels))

    def histogram(self, name: str, **labels) -> _Histogram:
        return _Histogram(self, _key(name, labels))

    def get(self, name: str, **labels):
        k = _key(name, labels)
        with self._lock:
            if k in self._counters:
                return self._counters[k]
            if k in self._gauges:
                return self._gauges[k]
            h = self._hists.get(k)
            return dict(h, _res=None) if h is not None else None

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()

    def snapshot(self) -> dict:
        """JSON-friendly deterministic state: {} when nothing was ever
        recorded (the save_results merge-only-when-non-empty rule)."""
        def _pct(xs: List[float], p: float):
            if not xs:
                return None
            i = min(len(xs) - 1,
                    max(0, int(round(p / 100.0 * len(xs) + 0.5)) - 1))
            return round(xs[i], 6)

        with self._lock:
            out: dict = {}
            if self._counters:
                out["counters"] = {k: self._counters[k]
                                   for k in sorted(self._counters)}
            if self._gauges:
                out["gauges"] = {k: self._gauges[k]
                                 for k in sorted(self._gauges)}
            if self._hists:
                hs = {}
                for k in sorted(self._hists):
                    h = self._hists[k]
                    xs = sorted(h["_res"])
                    hs[k] = {"count": h["count"],
                             "sum": round(h["sum"], 6),
                             "min": round(h["min"], 6),
                             "max": round(h["max"], 6),
                             "p50": _pct(xs, 50), "p99": _pct(xs, 99)}
                    raw = h.get("_b")
                    if raw:
                        # Cumulative le counts (Prometheus histogram
                        # semantics); "+Inf" always equals count.
                        cum, buckets = 0, {}
                        for le, n in zip(HIST_BUCKETS, raw):
                            cum += n
                            buckets[repr(le)] = cum
                        buckets["+Inf"] = h["count"]
                        hs[k]["buckets"] = buckets
                out["histograms"] = hs
            return out


REGISTRY = Registry()


def snapshot() -> dict:
    """The process-wide registry snapshot (store.save_results' source
    for the results.json ``telemetry`` block)."""
    return REGISTRY.snapshot()


def preregister(counters=()) -> None:
    """Create counter series at zero so /metrics exposes them before
    the first event — a scraper watching ``ingest.shed`` must see an
    explicit 0, not an absent series, to tell "no sheds" apart from
    "no ingest plane". (inc(0) materializes the entry; histograms are
    deliberately NOT pre-created — openmetrics skips count==0.)"""
    for name in counters:
        REGISTRY.counter(name).inc(0)


def metrics_prefixed(prefix: str) -> dict:
    """Flat {metric: value} slice of the registry under a name prefix
    — counters/gauges verbatim, histograms as their summary dicts
    (count/sum/min/max/p50/p99). The online daemon's status line and
    the web /live view read their ``online.*`` SLO histograms and
    queue gauges through this instead of re-walking the full
    snapshot."""
    snap = snapshot()
    out: dict = {}
    for kind in ("counters", "gauges", "histograms"):
        for k, v in (snap.get(kind) or {}).items():
            if k.startswith(prefix):
                out[k] = v
    return out


def counters_delta(base: Optional[dict], now: dict) -> dict:
    """``now`` with its counters re-expressed as deltas over ``base``
    (zero deltas dropped). The registry is process-cumulative; a
    per-RUN results.json block must not re-report the previous runs'
    traffic as this run's — StoreHandle captures ``base`` at create
    time and save_results diffs against it. Gauges stay current-value;
    histograms stay process-cumulative distributions (documented as
    such — their p50/p99 describe latency, which doesn't double-count).
    Returns {} when nothing beyond stale counters remains."""
    out = dict(now)
    b = (base or {}).get("counters") or {}
    if "counters" in out:
        c = {k: v - b.get(k, 0) for k, v in out["counters"].items()
             if v - b.get(k, 0)}
        if c:
            out["counters"] = c
        else:
            del out["counters"]
    return out


def merge_histogram_snapshots(snaps) -> dict:
    """Fold several processes' histogram SUMMARIES (the snapshot()
    shape: count/sum/min/max/p50/p99 + optional cumulative buckets)
    into one cluster-wide view — the checking service's cross-worker
    SLO aggregation. count/sum/min/max merge exactly, and bucket
    counts sum per ``le`` bound (identical bound sets — one code base
    emits them — otherwise buckets drop rather than lie); percentiles
    cannot be recombined from summaries, so the merged p50/p99 are the
    WORST (max) per-worker values — a conservative upper bound, which
    is the right direction for an SLO breach signal (doc/service.md).

    Tolerant by contract: empty input, None members, snapshots with no
    ``histograms`` block, empty-summary members, and members whose
    metric keys are disjoint (each worker's labels differ) all merge
    without a KeyError — a cluster view must survive whatever a
    half-written registry file serves it."""
    out: dict = {}
    for s in snaps:
        for k, h in ((s or {}).get("histograms") or {}).items():
            if not isinstance(h, dict) or not h.get("count"):
                continue
            m = out.get(k)
            if m is None:
                out[k] = dict(h)
                continue
            m["count"] = m.get("count", 0) + h["count"]
            m["sum"] = round(m.get("sum", 0.0)
                             + (h.get("sum") or 0.0), 6)
            for f, pick in (("min", min), ("max", max)):
                vals = [v for v in (m.get(f), h.get(f))
                        if v is not None]
                m[f] = pick(vals) if vals else None
            for p in ("p50", "p99"):
                vals = [v for v in (m.get(p), h.get(p))
                        if v is not None]
                m[p] = max(vals) if vals else None
            mb, hb = m.get("buckets"), h.get("buckets")
            if isinstance(mb, dict) and isinstance(hb, dict) and \
                    set(mb) == set(hb):
                m["buckets"] = {le: mb[le] + hb[le] for le in mb}
            else:
                m.pop("buckets", None)
    return out


def merge_counter_snapshots(snaps) -> dict:
    """Sum the ``counters`` blocks of several processes' snapshots (or
    counters_delta outputs) into one — the fleet orchestrator's
    cross-worker aggregation: each worker persists its own per-process
    counter deltas, and the campaign-level telemetry block must report
    the FLEET's total traffic, which no single registry ever saw.
    Tolerant like its histogram sibling: empty input, None members,
    counter-less snapshots, and disjoint key sets all sum cleanly."""
    out: dict = {}
    for s in snaps:
        for k, v in ((s or {}).get("counters") or {}).items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0) + v
    return out


def merge_gauge_snapshots(snaps) -> dict:
    """Sum the numeric ``gauges`` across workers — cluster totals for
    additive gauges (pending ops, tenant counts: the only gauges the
    registry records). Non-numeric values are skipped, not summed."""
    out: dict = {}
    for s in snaps:
        for k, v in ((s or {}).get("gauges") or {}).items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[k] = out.get(k, 0) + v
    return out


# ------------------------------------------------ OpenMetrics export

def parse_key(key: str):
    """Split a registry key back into (name, labels):
    ``"scheduler.retries{family=wgl}"`` → ("scheduler.retries",
    {"family": "wgl"}) — the inverse of ``_key``."""
    if "{" not in key or not key.endswith("}"):
        return key, {}
    name, _, inner = key.partition("{")
    labels = {}
    for part in inner[:-1].split(","):
        if "=" in part:
            lk, _, lv = part.partition("=")
            labels[lk] = lv
    return name, labels


def _prom_name(name: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_"
                  for c in name)
    if out and out[0].isdigit():
        out = "_" + out
    return f"jt_{out}"


def _prom_labels(labels: dict) -> str:
    if not labels:
        return ""

    def esc(v):
        return str(v).replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n")

    def lname(k):
        return "".join(c if c.isalnum() or c == "_" else "_"
                       for c in str(k)) or "_"

    inner = ",".join(f'{lname(k)}="{esc(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def openmetrics(snap: dict, *, labels: Optional[dict] = None) -> str:
    """Render a registry snapshot (live ``snapshot()``, a series
    frame's ``snap``, or a series-merged view) as Prometheus text
    exposition (format 0.0.4 — what every standard scraper parses).

    Metric names are sanitized under a ``jt_`` prefix with the
    registry's ``{label=value}`` suffixes decoded into real label
    sets; ``labels`` adds constant labels to every sample (the
    per-worker exposition stamps ``worker=<host>-<pid>``). Counters
    gain the conventional ``_total`` suffix; histograms expose their
    cumulative ``le`` buckets plus ``_sum``/``_count`` (p50/p99/
    min/max ride along as ``_p50``-style gauges — summaries a scraper
    can alert on without bucket math). Served by ``web.py /metrics``
    and printed offline by ``jepsen-tpu metrics``."""
    extra = dict(labels or {})
    lines: List[str] = []
    seen_types: set = set()

    def type_line(pname: str, kind: str) -> None:
        if pname not in seen_types:
            seen_types.add(pname)
            lines.append(f"# TYPE {pname} {kind}")

    for key, v in sorted((snap.get("counters") or {}).items()):
        if not isinstance(v, (int, float)):
            continue
        name, lbl = parse_key(key)
        pname = _prom_name(name) + "_total"
        type_line(pname, "counter")
        lines.append(f"{pname}{_prom_labels({**lbl, **extra})} "
                     f"{_prom_num(v)}")
    for key, v in sorted((snap.get("gauges") or {}).items()):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        name, lbl = parse_key(key)
        pname = _prom_name(name)
        type_line(pname, "gauge")
        lines.append(f"{pname}{_prom_labels({**lbl, **extra})} "
                     f"{_prom_num(v)}")
    for key, h in sorted((snap.get("histograms") or {}).items()):
        if not isinstance(h, dict) or not h.get("count"):
            continue
        name, lbl = parse_key(key)
        pname = _prom_name(name)
        type_line(pname, "histogram")
        base = {**lbl, **extra}
        buckets = h.get("buckets")
        if isinstance(buckets, dict):
            for le, n in buckets.items():
                lines.append(
                    f"{pname}_bucket"
                    f"{_prom_labels({**base, 'le': le})} "
                    f"{_prom_num(n)}")
        else:
            # Summary-only member (merged across mismatched bounds):
            # the +Inf bucket alone still makes it a valid histogram.
            lines.append(f"{pname}_bucket"
                         f"{_prom_labels({**base, 'le': '+Inf'})} "
                         f"{_prom_num(h['count'])}")
        lines.append(f"{pname}_sum{_prom_labels(base)} "
                     f"{_prom_num(h.get('sum') or 0.0)}")
        lines.append(f"{pname}_count{_prom_labels(base)} "
                     f"{_prom_num(h['count'])}")
        for stat in ("min", "max", "p50", "p99"):
            sv = h.get(stat)
            if sv is None:
                continue
            sname = f"{pname}_{stat}"
            type_line(sname, "gauge")
            lines.append(f"{sname}{_prom_labels(base)} "
                         f"{_prom_num(sv)}")
    return "\n".join(lines) + ("\n" if lines else "")


# Sibling modules of the cluster observability plane, re-exported so
# callers write ``telemetry.series`` / ``telemetry.alerts`` (the
# doc/observability.md names). Imported last: both consume the names
# defined above.
from . import series, alerts  # noqa: E402,F401  (re-export)
