"""Functional sequential models of datatype behavior.

A model consumes one operation at a time via ``step`` and returns the next
model state, or an ``Inconsistent`` marker when the op is impossible from
the current state. Semantics mirror the reference's model records
(jepsen/src/jepsen/model.clj:21-105) and knossos' Model protocol; these are
the specs both the host linearizability oracle and the TPU kernels are
tested against.

Models are immutable; ``step`` never mutates.
"""
from __future__ import annotations

from collections import Counter
from dataclasses import dataclass
from typing import Any, Tuple


class Model:
    def step(self, op) -> "Model":
        raise NotImplementedError


@dataclass(frozen=True)
class Inconsistent(Model):
    msg: str

    def step(self, op) -> "Model":
        return self


def inconsistent(msg: str) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(m) -> bool:
    return isinstance(m, Inconsistent)


@dataclass(frozen=True)
class NoOp(Model):
    def step(self, op) -> "Model":
        return self


noop = NoOp()


@dataclass(frozen=True)
class CASRegister(Model):
    """A compare-and-set register over :read/:write/:cas.

    A read with value None always succeeds (the test recorded no
    observation); cas takes a (from, to) pair.
    """

    value: Any = None

    def step(self, op) -> "Model":
        f = op.f
        if f == "write":
            return CASRegister(op.value)
        if f == "cas":
            cur, new = op.value[0], op.value[1]
            if cur == self.value:
                return CASRegister(new)
            return inconsistent(
                f"can't CAS {self.value!r} from {cur!r} to {new!r}")
        if f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(
                f"can't read {op.value!r} from register {self.value!r}")
        return inconsistent(f"unknown op {f!r} for CASRegister")


def cas_register(value=None) -> CASRegister:
    return CASRegister(value)


@dataclass(frozen=True)
class Mutex(Model):
    locked: bool = False

    def step(self, op) -> "Model":
        if op.f == "acquire":
            if self.locked:
                return inconsistent("already held")
            return Mutex(True)
        if op.f == "release":
            if self.locked:
                return Mutex(False)
            return inconsistent("not held")
        return inconsistent(f"unknown op {op.f!r} for Mutex")


def mutex() -> Mutex:
    return Mutex(False)


@dataclass(frozen=True)
class SetModel(Model):
    s: frozenset = frozenset()

    def step(self, op) -> "Model":
        if op.f == "add":
            return SetModel(self.s | {op.value})
        if op.f == "read":
            # A read with no recorded observation constrains nothing
            # (same convention as CASRegister's None read).
            if op.value is None or set(op.value) == set(self.s):
                return self
            return inconsistent(
                f"can't read {op.value!r} from {set(self.s)!r}")
        return inconsistent(f"unknown op {op.f!r} for Set")


def set_model() -> SetModel:
    return SetModel()


class UnorderedQueue(Model):
    """A queue whose pending elements are an unordered multiset."""

    __slots__ = ("pending",)

    def __init__(self, pending: Counter = None):
        self.pending = pending if pending is not None else Counter()

    def step(self, op) -> "Model":
        if op.f == "enqueue":
            p = self.pending.copy()
            p[op.value] += 1
            return UnorderedQueue(p)
        if op.f == "dequeue":
            if self.pending.get(op.value, 0) > 0:
                p = self.pending.copy()
                p[op.value] -= 1
                if p[op.value] == 0:
                    del p[op.value]
                return UnorderedQueue(p)
            return inconsistent(f"can't dequeue {op.value!r}")
        return inconsistent(f"unknown op {op.f!r} for UnorderedQueue")

    def __eq__(self, other):
        return (isinstance(other, UnorderedQueue)
                and self.pending == other.pending)

    def __hash__(self):
        return hash(frozenset(self.pending.items()))

    def __repr__(self):
        # Value-based and order-stable (Counter iteration order is
        # insertion order, which differs between equal states reached
        # by different paths): counterexample configs embed this
        # string, and equal states must render identically.
        items = sorted(self.pending.items(), key=lambda kv: repr(kv[0]))
        return f"UnorderedQueue(pending={dict(items)!r})"


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


class FIFOQueue(Model):
    __slots__ = ("pending",)

    def __init__(self, pending: Tuple = ()):
        self.pending = tuple(pending)

    def step(self, op) -> "Model":
        if op.f == "enqueue":
            return FIFOQueue(self.pending + (op.value,))
        if op.f == "dequeue":
            if not self.pending:
                return inconsistent(
                    f"can't dequeue {op.value!r} from empty queue")
            if self.pending[0] == op.value:
                return FIFOQueue(self.pending[1:])
            return inconsistent(f"can't dequeue {op.value!r}")
        return inconsistent(f"unknown op {op.f!r} for FIFOQueue")

    def __eq__(self, other):
        return isinstance(other, FIFOQueue) and self.pending == other.pending

    def __hash__(self):
        return hash(self.pending)

    def __repr__(self):
        return f"FIFOQueue(pending={list(self.pending)!r})"


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()
