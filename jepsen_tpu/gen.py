"""Generator DSL: composable, stateful op sources for test workloads.

Semantics mirror the reference's generator library
(jepsen/src/jepsen/generator.clj) — "a big ol box of monads":

  * a generator yields op dicts until exhausted, then yields None;
  * *everything* may act as a generator: None yields nothing, a dict
    yields itself forever, a callable is invoked per op, an object with
    an ``op`` method delegates;
  * generators may sleep inside ``op`` to pace the test;
  * thread-scoped combinators (`on`, `reserve`, `nemesis`, `clients`)
    narrow the set of threads a sub-generator sees — here via an explicit
    immutable :class:`Context` rather than the reference's dynamic
    ``*threads*`` var (generator.clj:40-46);
  * barrier combinators (`synchronize`, `phases`, `then`) block until
    every thread in scope arrives (generator.clj:402-424).

Stateful combinators are thread-safe: the runtime's workers poll a shared
generator tree concurrently, as the reference's JVM futures do.

Ops are plain dicts with at least ``{"f": ...}``; workers fill in
``process``/``time``/``type`` (invoke) — generator.clj:7-9. ``ctx.rng``
is a seeded Random so single-threaded drains (and the batch-seeded north
star mode) are deterministic.
"""
from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field, replace
from random import Random
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

NEMESIS = "nemesis"


@dataclass(frozen=True)
class Context:
    """Generator-side execution context.

    threads:     ordered tuple of thread ids in scope (ints + "nemesis").
    concurrency: worker count; process -> thread is process % concurrency.
    rng:         seeded Random shared across the generator tree.
    time_nanos:  callable yielding monotonic nanos (injectable for tests).
    """

    threads: tuple = ()
    concurrency: int = 0
    rng: Random = field(default_factory=Random)
    time_nanos: Callable[[], int] = _time.monotonic_ns

    def with_threads(self, threads) -> "Context":
        return replace(self, threads=tuple(threads))

    def thread_of(self, process):
        """process → thread id (generator.clj:58-63)."""
        if isinstance(process, int) and self.concurrency:
            return process % self.concurrency
        return process


def _accepts_3_args(f) -> bool:
    """Can f be called as f(test, process, ctx)? Decided from the
    signature, NOT by catching TypeError from a call — a TypeError
    raised *inside* the body must propagate, not trigger a re-call
    (which would duplicate side effects and mask the real error)."""
    import inspect
    try:
        inspect.signature(f).bind(None, None, None)
        return True
    except TypeError:
        return False
    except ValueError:   # no signature available (builtins): assume 0-ary
        return False


def op(gen, test: dict, process, ctx: Context) -> Optional[dict]:
    """Yield the next op from anything generator-like (generator.clj:25-38)."""
    if gen is None:
        return None
    if isinstance(gen, dict):
        return dict(gen)
    if isinstance(gen, Generator):
        return gen.op(test, process, ctx)
    if callable(gen):
        cached = getattr(gen, "__jt_gen_arity3__", None)
        if cached is None:
            cached = _accepts_3_args(gen)
            try:
                gen.__jt_gen_arity3__ = cached
            except (AttributeError, TypeError):
                pass
        return gen(test, process, ctx) if cached else gen()
    raise TypeError(f"not a generator: {gen!r}")


class Generator:
    """Base class; subclasses implement ``op`` returning a dict or None."""

    def op(self, test: dict, process, ctx: Context) -> Optional[dict]:
        raise NotImplementedError


class _Fn(Generator):
    def __init__(self, f):
        self.f = f

    def op(self, test, process, ctx):
        return self.f(test, process, ctx)


def void() -> Generator:
    """Terminates immediately (generator.clj:74-77)."""
    return _Fn(lambda test, process, ctx: None)


class _Once(Generator):
    """Invokes the source exactly once (generator.clj:148-156)."""

    def __init__(self, source):
        self.source = source
        self._lock = threading.Lock()
        self._emitted = False

    def op(self, test, process, ctx):
        with self._lock:
            if self._emitted:
                return None
            self._emitted = True
        return op(self.source, test, process, ctx)


def once(source) -> Generator:
    return _Once(source)


class _Log(Generator):
    def __init__(self, msg, logger=None):
        import logging
        self.msg = msg
        self.logger = logger or logging.getLogger("jepsen.gen")

    def op(self, test, process, ctx):
        self.logger.info(self.msg)
        return None


def log_every(msg) -> Generator:
    """Logs every time invoked; yields None (generator.clj:158-164)."""
    return _Log(msg)


def log(msg) -> Generator:
    """Logs once; yields None (generator.clj:166-169)."""
    return once(_Log(msg))


class _Each(Generator):
    """An independent copy of the underlying generator per process
    (generator.clj:171-193)."""

    def __init__(self, gen_fn):
        self.gen_fn = gen_fn
        self._gens: Dict[Any, Any] = {}
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        with self._lock:
            g = self._gens.get(process)
            if g is None:
                g = self._gens[process] = self.gen_fn()
        return op(g, test, process, ctx)


def each(gen_fn: Callable[[], Any]) -> Generator:
    return _Each(gen_fn)


class _Seq(Generator):
    """One op per call from the current generator; on None advance to the
    next (generator.clj:195-206). Accepts any iterable, incl. infinite."""

    def __init__(self, coll: Iterable):
        self._it = iter(coll)
        self._cur = None
        self._live = True
        self._lock = threading.RLock()

    def op(self, test, process, ctx):
        with self._lock:
            while self._live:
                if self._cur is None:
                    try:
                        self._cur = next(self._it)
                    except StopIteration:
                        self._live = False
                        return None
                o = op(self._cur, test, process, ctx)
                if o is not None:
                    # A bare dict/constant yields itself forever; in a seq
                    # each such element contributes one op then retires.
                    if not isinstance(self._cur, Generator) \
                            and not callable(self._cur):
                        self._cur = None
                    return o
                self._cur = None
        return None


def seq(coll: Iterable) -> Generator:
    return _Seq(coll)


def start_stop(t1: float, t2: float) -> Generator:
    """sleep t1, :start, sleep t2, :stop, forever (generator.clj:208-215)."""
    import itertools
    return seq(itertools.cycle([sleep(t1), {"type": "info", "f": "start"},
                                sleep(t2), {"type": "info", "f": "stop"}]))


class _Mix(Generator):
    """Uniform random choice per op (generator.clj:217-224)."""

    def __init__(self, gens: Sequence):
        self.gens = list(gens)

    def op(self, test, process, ctx):
        return op(self.gens[ctx.rng.randrange(len(self.gens))],
                  test, process, ctx)


def mix(gens: Sequence) -> Generator:
    return _Mix(gens)


def cas_gen(n_values: int = 5) -> Generator:
    """Random read/write/cas invocations over a small int field
    (generator.clj:226-239)."""

    def g(test, process, ctx):
        r = ctx.rng.random()
        if r > 0.66:
            return {"type": "invoke", "f": "read", "value": None}
        if r > 0.33:
            return {"type": "invoke", "f": "write",
                    "value": ctx.rng.randrange(n_values)}
        return {"type": "invoke", "f": "cas",
                "value": [ctx.rng.randrange(n_values),
                          ctx.rng.randrange(n_values)]}

    return _Fn(g)


class _QueueGen(Generator):
    """Random enqueue (consecutive ints) / dequeue mix
    (generator.clj:241-252)."""

    def __init__(self):
        self._i = -1
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        if ctx.rng.random() < 0.5:
            with self._lock:
                self._i += 1
                v = self._i
            return {"type": "invoke", "f": "enqueue", "value": v}
        return {"type": "invoke", "f": "dequeue", "value": None}


def queue_gen() -> Generator:
    return _QueueGen()


class _DrainQueue(Generator):
    """After the source is exhausted, emits one dequeue per attempted
    enqueue that passed through (generator.clj:254-269)."""

    def __init__(self, source):
        self.source = source
        self._outstanding = 0
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        o = op(self.source, test, process, ctx)
        if o is not None:
            if o.get("f") == "enqueue":
                with self._lock:
                    self._outstanding += 1
            return o
        with self._lock:
            self._outstanding -= 1
            remaining = self._outstanding
        if remaining >= 0:
            return {"type": "invoke", "f": "dequeue", "value": None}
        return None


def drain_queue(source) -> Generator:
    return _DrainQueue(source)


class _Limit(Generator):
    """At most n ops (generator.clj:271-279)."""

    def __init__(self, n: int, source):
        self.source = source
        self._life = n + 1
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        with self._lock:
            self._life -= 1
            alive = self._life > 0
        if alive:
            return op(self.source, test, process, ctx)
        return None


def limit(n: int, source) -> Generator:
    return _Limit(n, source)


class _TimeLimit(Generator):
    """Ops until dt seconds after first use (generator.clj:281-291)."""

    def __init__(self, dt: float, source):
        self.source = source
        self.dt_nanos = int(dt * 1e9)
        self._deadline = None
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        now = ctx.time_nanos()
        with self._lock:
            if self._deadline is None:
                self._deadline = now + self.dt_nanos
            deadline = self._deadline
        if now <= deadline:
            return op(self.source, test, process, ctx)
        return None


def time_limit(dt: float, source) -> Generator:
    return _TimeLimit(dt, source)


class _Filter(Generator):
    """Only ops satisfying f (generator.clj:293-303)."""

    def __init__(self, f, source):
        self.f = f
        self.source = source

    def op(self, test, process, ctx):
        while True:
            o = op(self.source, test, process, ctx)
            if o is None:
                return None
            if self.f(o):
                return o


def filter_gen(f, source) -> Generator:
    return _Filter(f, source)


# ------------------------------------------------- timing combinators

def sleep_til_nanos(ctx: Context, t: int) -> None:
    while True:
        dt = t - ctx.time_nanos()
        if dt <= 10_000:
            return
        _time.sleep(dt / 1e9)


class _DelayFn(Generator):
    """Each op takes (f) extra seconds (generator.clj:88-101)."""

    def __init__(self, f, source):
        self.f = f
        self.source = source

    def op(self, test, process, ctx):
        _time.sleep(self.f(ctx))
        return op(self.source, test, process, ctx)


def delay(dt: float, source) -> Generator:
    return _DelayFn(lambda ctx: dt, source)


def stagger(dt: float, source) -> Generator:
    """Uniform random delay in [0, 2dt) — mean dt (generator.clj:137-141)."""
    return _DelayFn(lambda ctx: ctx.rng.uniform(0, 2 * dt), source)


def sleep(dt: float) -> Generator:
    """Takes dt seconds and yields None (generator.clj:143-146)."""
    return delay(dt, void())


class _DelayTil(Generator):
    """Emit invocations as close as possible to shared multiples of dt
    from an anchor — aligned invocations trigger races
    (generator.clj:112-135)."""

    def __init__(self, dt: float, source, precache: bool = True):
        self.dt_nanos = int(dt * 1e9)
        self.source = source
        self.precache = precache
        self._anchor = None
        self._lock = threading.Lock()

    def _next_tick(self, ctx):
        now = ctx.time_nanos()
        with self._lock:
            if self._anchor is None:
                self._anchor = now
            anchor = self._anchor
        return now + (self.dt_nanos - (now - anchor) % self.dt_nanos)

    def op(self, test, process, ctx):
        if self.precache:
            o = op(self.source, test, process, ctx)
            sleep_til_nanos(ctx, self._next_tick(ctx))
            return o
        sleep_til_nanos(ctx, self._next_tick(ctx))
        return op(self.source, test, process, ctx)


def delay_til(dt: float, source, precache: bool = True) -> Generator:
    return _DelayTil(dt, source, precache)


# ------------------------------------------- thread-scoped combinators

class _On(Generator):
    """Forward ops iff f(thread); narrows ctx.threads
    (generator.clj:305-312)."""

    def __init__(self, f, source):
        self.f = f
        self.source = source

    def op(self, test, process, ctx):
        if not self.f(ctx.thread_of(process)):
            return None
        sub = ctx.with_threads(t for t in ctx.threads if self.f(t))
        return op(self.source, test, process, sub)


def on(f, source) -> Generator:
    return _On(f, source)


def nemesis(nemesis_gen, client_gen=None) -> Generator:
    """Route the nemesis thread to one generator, clients to another
    (generator.clj:372-380)."""
    if client_gen is None:
        return on(lambda t: t == NEMESIS, nemesis_gen)
    return concat(on(lambda t: t == NEMESIS, nemesis_gen),
                  on(lambda t: t != NEMESIS, client_gen))


def clients(client_gen) -> Generator:
    """Executes only on client threads (generator.clj:382-385)."""
    return on(lambda t: t != NEMESIS, client_gen)


class _Reserve(Generator):
    """(reserve 5 writes 10 cas reads): thread-range partitioning
    (generator.clj:314-358)."""

    def __init__(self, *args):
        assert args and len(args) % 2 == 1, \
            "reserve takes count/gen pairs + a default generator"
        pairs, self.default = args[:-1], args[-1]
        self.ranges = []
        lower = 0
        for n, g in zip(pairs[::2], pairs[1::2]):
            self.ranges.append((lower, lower + n, g))
            lower += n

    def op(self, test, process, ctx):
        threads = list(ctx.threads)
        thread = ctx.thread_of(process)
        # Thread ids in scope, ordered; find our index range.
        for lower, upper, g in self.ranges:
            if upper <= len(threads) and thread in threads[lower:upper]:
                return op(g, test, process, ctx.with_threads(
                    threads[lower:upper]))
        tail = self.ranges[-1][1] if self.ranges else 0
        if thread in threads[tail:]:
            return op(self.default, test, process,
                      ctx.with_threads(threads[tail:]))
        return None


def reserve(*args) -> Generator:
    return _Reserve(*args)


# ------------------------------------------------ barrier combinators

class _Concat(Generator):
    """First non-None op across sources, in order (generator.clj:360-370)."""

    def __init__(self, *sources):
        self.sources = list(sources)

    def op(self, test, process, ctx):
        for s in self.sources:
            o = op(s, test, process, ctx)
            if o is not None:
                return o
        return None


def concat(*sources) -> Generator:
    return _Concat(*sources)


class _Await(Generator):
    """Blocks until f returns (invoked once), then delegates
    (generator.clj:387-400)."""

    def __init__(self, f, source=None):
        self.f = f
        self.source = source
        self._state = "waiting"
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        if self._state == "waiting":
            with self._lock:
                if self._state == "waiting":
                    self.f()
                    self._state = "ready"
        return op(self.source, test, process, ctx)


def await_fn(f, source=None) -> Generator:
    return _Await(f, source)


class _Synchronize(Generator):
    """Block until every thread in scope is waiting here, once; then
    pass through (generator.clj:402-419)."""

    def __init__(self, source):
        self.source = source
        self._barrier = None
        self._cleared = False
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        if not self._cleared:
            with self._lock:
                if self._barrier is None and not self._cleared:
                    def clear():
                        self._cleared = True
                    self._barrier = threading.Barrier(
                        len(ctx.threads), action=clear)
                b = self._barrier
            if not self._cleared and b is not None:
                b.wait()
        return op(self.source, test, process, ctx)


def synchronize(source) -> Generator:
    return _Synchronize(source)


def phases(*generators) -> Generator:
    """All threads finish phase k before any starts k+1
    (generator.clj:421-424)."""
    return concat(*[synchronize(g) for g in generators])


def then(a, b) -> Generator:
    """b, synchronize, then a — backwards for pipeline composition
    (generator.clj:426-430)."""
    return concat(b, synchronize(a))


def barrier(source) -> Generator:
    """When source completes, synchronize, then None (generator.clj:441-444)."""
    return then(void(), source)


class _SingleThreaded(Generator):
    """Exclusive lock around the underlying generator
    (generator.clj:432-439)."""

    def __init__(self, source):
        self.source = source
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        with self._lock:
            return op(self.source, test, process, ctx)


def singlethreaded(source) -> Generator:
    return _SingleThreaded(source)
