"""Always-on online checker: tail live WALs, check prefixes as they run.

ROADMAP item 2's production story (the OmniLink trace-validation-of-
live-systems argument, arXiv 2601.11836): histories should be checked
*while they are being written*, flagging the first violating op seconds
after it happens instead of post-mortem. The pieces already exist —
the live WAL (history/wal.py) streams every op to disk with phase
stamps, decrease-and-conquer monitoring (arXiv 2410.04581) says a
completed prefix is independently checkable, and the scheduler ladder
is the ready-made overload behavior. This module is the long-running
service that ties them together and stays correct under writer
crashes, torn tails, log rotation, slow consumers, and its own faults.

Model
-----
One ``OnlineDaemon`` watches a store. Every incomplete run (live WAL,
no results.json) becomes a *tenant*: an incremental tail cursor
(``history.wal.TailState``; whole lines only, so the writer's torn
in-flight group commit is completed by a later poll, and rotation is
an inode change that resets the cursor), a buffered op prefix, and a
per-tenant ``store.ChunkJournal`` whose rows are decided prefix
lengths. Rolling checks encode the current prefix into the columnar
layout and dispatch through the standard device pipeline with a
``schedule.ResidentState`` (learned OOM-safe chunk sizes and awaited
kernel shapes persist across checks) and a grow-only resident kind
vocabulary — the daemon's encode side stays warm the way the kernel
registry/AOT shipping keeps the device side warm.

Prefix semantics reuse salvage's checkability argument with one
refinement: dangling invocations are HELD BACK — included in the
checked prefix as open (never-completing) invocations, which the WGL
treats exactly like salvage's ``:info`` completion (pending forever) —
but never durably *decided* as ``:info``, because the live tail may
still confirm them. Interim verdicts are therefore monotone
(linearizability is prefix-closed: an invalid prefix never becomes
valid) and the first invalid interim check persists a durable
``first-violation.json``.

Finalization is parity-exact by construction: when the writer stamps
``analyzed`` the daemon re-checks the stored history (falling back to
the tailed ops, which test_durability pins byte-equal); when the
writer DIES (pid liveness + quiescence) it applies
``salvage_history`` — the same transform ``Store.salvage`` runs — and
dispatches the same engine call ``Store.recheck`` uses
(``details="invalid"``, ``min_device_batch=64``). The acceptance
contract: the daemon's final verdict, witness, and bad-op index are
field-for-field identical to a post-mortem recheck, fault-free and
under every single-fault daemon schedule.

Robustness core
---------------
Admission and overload are explicit, not emergent:

  * admission — tenant count bound; per-tenant W-class bound (a prefix
    whose peak pending window exceeds ``max_w`` rides the host oracle
    — wide windows are exponential device cost); per-tenant check rate
    bound; a bounded ingest buffer with counted backpressure (the tail
    simply stops reading ahead of the checker).
  * degradation ladder — by total undecided backlog:
    L0 fresh-prefix-first service order → L1 widen the check interval
    (``widen_factor``) → L2 shed interim checks to the host oracle →
    L3 pause the stalest tenant with a durable ``online-deferred.json``
    mark (its buffer is released; the journal keeps its decided
    prefixes). Every transition is counted; no level drops a tenant's
    eventual verdict.
  * fault plan — ``DaemonFaultPlan`` stage hooks on tail/encode/
    dispatch ($JT_WATCH_FAULT_PLAN): a ``fail`` skips that tenant's
    stage for the tick (retried next tick — the daemon loop is the
    retry), a ``stall`` sleeps through the hook. Writer-crash,
    rotation, and tail-stall nemeses are driven by the tests
    (subprocess SIGKILL via $JT_RUN_FAULT, inode swaps, withheld
    appends).
  * restart — a killed daemon resumes from the per-tenant journals
    (decided prefixes never re-dispatch; ``ChunkJournal.record``
    structurally refuses a double-decide) and from durable
    ``online-verdict.json`` files (finalized tenants rehydrate with
    zero work).

SLOs land on the telemetry registry: ``online.ttfv_s`` histograms
(time to first verdict, global and per test name), ``online.pending_
ops``/``online.tenants`` gauges, and counters for every ladder
transition — surfaced by ``jepsen-tpu watch``, the web ``/live`` view
(via the persisted store registry), and the bench ``online`` section.
"""
from __future__ import annotations

import json
import logging
import os
import random
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from . import alerts as alerts_mod
from . import series as series_mod
from . import telemetry
from .history.core import index
from .history.ops import FAIL, INVOKE, OK, Op
from .history.wal import (TailState, WAL_FILE, salvage_history, tail_wal,
                          writer_alive)
from .store import (FIRST_VIOLATION, ONLINE_DEFERRED, ONLINE_ISO,
                    ONLINE_JOURNAL, ONLINE_VERDICT, ChunkJournal,
                    DEFAULT, Store, atomic_write_json)

log = logging.getLogger("jepsen.online")

# Daemon-level fault stages: the three loop boundaries a tick crosses
# per tenant. (The checker pipeline's own encode/dispatch/decode
# nemesis — ops.faults — still applies INSIDE a dispatched check; these
# are the stages above it.)
DAEMON_STAGES = ("tail", "encode", "dispatch")
DAEMON_KINDS = ("fail", "stall")


class DaemonFault(RuntimeError):
    """An injected daemon-stage failure. The service loop absorbs it —
    the tenant's tick is skipped and retried on the next poll — which
    is exactly the property the parity tests pin: no single daemon
    fault changes any final verdict."""

    def __init__(self, stage: str, ordinal: int):
        self.stage, self.ordinal = stage, ordinal
        super().__init__(f"injected daemon fault at {stage} "
                         f"ordinal {ordinal}")


@dataclass(frozen=True)
class DaemonFaultSpec:
    """``kind`` at ``stage``, firing on that stage's Nth crossing
    (``tick`` None = sticky)."""

    stage: str
    kind: str
    tick: Optional[int] = 0

    def __post_init__(self):
        assert self.stage in DAEMON_STAGES, self.stage
        assert self.kind in DAEMON_KINDS, self.kind

    def matches(self, stage: str, ordinal: int) -> bool:
        return self.stage == stage and (self.tick is None
                                        or self.tick == ordinal)


class DaemonFaultPlan:
    """Deterministic daemon fault schedule — the ops.faults.FaultPlan
    idiom lifted to the service loop's stages. ``stall_s`` is what a
    ``stall`` fault sleeps (test-scale by default)."""

    def __init__(self, specs: List[DaemonFaultSpec], *,
                 stall_s: float = 0.05):
        self.specs = list(specs)
        self.stall_s = stall_s

    @classmethod
    def single(cls, stage: str, kind: str, tick: int = 0,
               **kw) -> "DaemonFaultPlan":
        return cls([DaemonFaultSpec(stage, kind, tick)], **kw)

    @classmethod
    def parse(cls, text: str, **kw) -> "DaemonFaultPlan":
        """``"stage:kind[:tick]"`` comma/semicolon-separated; tick
        ``*`` = sticky (the $JT_WATCH_FAULT_PLAN syntax)."""
        specs = []
        for part in text.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            tick: Optional[int] = 0
            if len(bits) > 2:
                tick = None if bits[2] == "*" else int(bits[2])
            specs.append(DaemonFaultSpec(bits[0], bits[1], tick))
        return cls(specs, **kw)

    def match(self, stage: str, ordinal: int) -> Optional[DaemonFaultSpec]:
        for s in self.specs:
            if s.matches(stage, ordinal):
                return s
        return None


def daemon_fault_schedules() -> List[Tuple[str, DaemonFaultPlan]]:
    """The canonical single-fault matrix the online parity tests sweep:
    one transient failure at each stage boundary plus a tail stall and
    a dispatch stall — each fired exactly once, on the first crossing
    of its stage."""
    out = [(f"fail@{s}", DaemonFaultPlan.single(s, "fail"))
           for s in DAEMON_STAGES]
    out.append(("stall@tail", DaemonFaultPlan.single("tail", "stall")))
    out.append(("stall@dispatch",
                DaemonFaultPlan.single("dispatch", "stall")))
    return out


class DaemonFaultInjector:
    """Executes a DaemonFaultPlan at the daemon's stage crossings.
    ``fire(stage)`` raises DaemonFault for ``fail`` and sleeps through
    ``stall``; ``log`` records every firing so tests can assert the
    schedule actually engaged."""

    def __init__(self, plan: DaemonFaultPlan):
        self.plan = plan
        self.log: List[Tuple[str, int, str]] = []
        self._ordinal: Dict[str, int] = {s: 0 for s in DAEMON_STAGES}

    def fire(self, stage: str) -> None:
        n = self._ordinal[stage]
        self._ordinal[stage] = n + 1
        spec = self.plan.match(stage, n)
        if spec is None:
            return
        self.log.append((stage, n, spec.kind))
        if spec.kind == "fail":
            raise DaemonFault(stage, n)
        time.sleep(self.plan.stall_s)

    @classmethod
    def from_env(cls) -> Optional["DaemonFaultInjector"]:
        text = os.environ.get("JT_WATCH_FAULT_PLAN")
        if not text:
            return None
        return cls(DaemonFaultPlan.parse(text))


# --------------------------------------------------------------- prefix

def checkable_prefix(ops: List[Op]) -> List[Op]:
    """An indexed copy of the raw tailed prefix, dangling invocations
    left OPEN. The WGL treats a never-completed invocation exactly like
    salvage's ``:info`` completion — pending forever, possibly taking
    effect at any point — so the prefix verdict is sound without
    durably deciding the dangling ops, which the live tail may yet
    confirm. (Excluding them would be UNSOUND: a completed read in the
    prefix may observe a dangling write's effect.)"""
    return index([op.with_() for op in ops])


def _is_txn(history: List[Op]) -> bool:
    """Transactional vocabulary sniff (fleet.classify_history's rule):
    any ``txn`` client op routes the history to the isolation plane —
    the register/WGL engines have no semantics for multi-key micro-op
    vectors."""
    return any(getattr(op, "f", None) == "txn" for op in history)


def _bad_index(r: dict) -> Optional[int]:
    """The first-impossible-op index out of a result dict, from either
    engine's shape (device details decode an op dict; the host engine
    an Op)."""
    if r.get("valid") is True:
        return None
    op = r.get("op")
    if op is None:
        return None
    if isinstance(op, dict):
        return op.get("index")
    return getattr(op, "index", None)


# --------------------------------------------------------------- config

@dataclass
class OnlineConfig:
    """The daemon's admission/overload policy. Thresholds are in
    buffered-undecided ops (the unit backpressure actually acts on);
    the defaults suit a real store — tests shrink them to force the
    ladder."""

    model: object = None
    poll_s: float = 0.5             # tail poll interval (jittered)
    jitter: float = 0.25            # fraction of poll_s
    check_interval_ops: int = 64    # interim check every N new ops
    min_check_ops: int = 1
    # -- admission
    max_tenants: int = 64
    max_w: int = 14                 # W-class admission bound (device)
    rate_checks_per_s: float = 0.0  # per-tenant; 0 = unlimited
    max_buffered_ops: int = 262144  # ingest bound per tenant
    # -- degradation ladder (total undecided backlog across tenants)
    overload_pending_ops: int = 8192     # L1: widen check interval
    widen_factor: int = 4
    shed_pending_ops: int = 32768        # L2: shed to the host oracle
    defer_pending_ops: int = 131072      # L3: pause stalest tenant
    # Hard re-admission deadline for deferred tenants ($JT_DEFER_MAX_S,
    # default 300 s; 0 = disabled, the convention every sibling knob
    # uses): past it the stalest deferred tenant is force-admitted
    # ahead of fresh ones even while the fleet stays busy — no live
    # stream waits unboundedly behind a persistently overloaded
    # daemon (``deferred_starvation_rescues``).
    defer_max_s: Optional[float] = None
    # -- incremental prefix checking ($JT_ONLINE_INCREMENTAL, default
    # on; 0 = the restore switch, every interim check re-walks the
    # full prefix — the pre-frontier behavior, bit-for-bit). When on,
    # non-shed interim checks resume a per-tenant resident device
    # frontier (ops.schedule.ResidentFrontier) so per-tick cost is
    # O(new ops); any fault, rotation, or non-monotone vocabulary
    # growth invalidates the carried frontier and that tick falls back
    # to the full-prefix check. Finalization ALWAYS runs the exact
    # full Store.recheck engine call — the parity contract is
    # structurally untouched by this switch.
    incremental: Optional[bool] = None
    # -- live isolation monitoring ($JT_ONLINE_ISO, default on;
    # 0 = the restore switch). Transactional tenants (txn vocabulary)
    # feed an isolation.IncrementalIsolation monitor per tail tick:
    # the per-tick "strongest level still holding" verdict is monotone
    # non-increasing, and every downgrade persists durably as
    # ``online-iso.json`` (the first-violation pattern). Interim and
    # final CHECKS are unaffected — they ride the isolation certifier
    # either way; this switch only governs the per-tick monitor.
    iso: Optional[bool] = None
    # -- finalization
    crash_quiet_s: float = 1.0      # writer dead AND quiet this long
    min_device_batch: int = 64      # Store.recheck's value (parity)
    host_engine: object = None      # default: the exact host engine

    def __post_init__(self):
        if self.model is None:
            from .models.core import cas_register
            self.model = cas_register()
        if self.incremental is None:
            self.incremental = os.environ.get(
                "JT_ONLINE_INCREMENTAL", "1") != "0"
        if self.iso is None:
            self.iso = os.environ.get("JT_ONLINE_ISO", "1") != "0"
        if self.defer_max_s is None:
            try:
                self.defer_max_s = max(
                    0.0, float(os.environ.get("JT_DEFER_MAX_S", "300")))
            except ValueError:
                self.defer_max_s = 300.0


# --------------------------------------------------------------- engine

class OnlineCheckEngine:
    """The daemon's resident check engine. Rolling (interim) checks
    ride the device pipeline with persistent state: a grow-only kind
    vocabulary seeds every conversion (stable bucketing across
    checks), and one ``schedule.ResidentState`` carries learned
    OOM-safe chunk sizes and awaited kernel shapes across the
    per-check scheduler instances — together with the process-wide
    kernel registry/AOT cache these are the "persistent resident
    buffers" that make check k+1 cheaper than check k. Final checks
    deliberately run the UNSEEDED vanilla ``check_batch_columnar``
    call with ``Store.recheck``'s exact arguments: parity with the
    post-mortem path outranks warm-start economics exactly once per
    run. Shed checks (overload L2, W-class overflow, state-space
    explosion) run the same exact host engine quarantine falls back
    to."""

    def __init__(self, cfg: OnlineConfig):
        from .checkers.linearizable import wgl_check
        from .ops.schedule import ResidentState
        self.cfg = cfg
        self.kinds: Optional[list] = None
        self.resident = ResidentState()
        self.host = cfg.host_engine or wgl_check
        # $JT_ONLINE_DC: per-tenant decrease-and-conquer carries
        # (ops.dc_monitor.IncrementalDC), keyed like the resident
        # frontiers. Certify-only fast path — a tick it cannot serve
        # falls through to the frontier with verdicts unchanged.
        self._dc_inc: Dict[Tuple, object] = {}

    def check(self, history: List[Op], *, shed: bool = False,
              final: bool = False) -> Tuple[dict, str]:
        """(result dict, provenance). ``final`` = the parity-exact
        post-mortem call; ``shed`` = the host oracle."""
        from .ops.linearize import check_batch_columnar, check_columnar
        from .ops.statespace import StateSpaceExplosion

        cfg = self.cfg
        if _is_txn(history):
            # Transactional tenant: certification rides the isolation
            # plane (jepsen_tpu.isolation), not the register engines.
            # ``shed`` maps to the host oracle twin exactly like the
            # WGL route; final/interim ride certify_batch, which is
            # also what Store.recheck_isolation dispatches — the
            # parity contract carries over unchanged.
            from .isolation import certify_batch, certify_host
            if shed:
                return certify_host([history])[0], "online-iso-host"
            r = certify_batch([history])[0]
            return r, ("online-iso-final" if final else "online-iso")
        if final:
            r = check_batch_columnar(
                cfg.model, [history], details="invalid",
                min_device_batch=cfg.min_device_batch)[0]
            return r, "online-final"
        if shed:
            return self.host(cfg.model, history), "online-host"
        try:
            from .history.columnar import ops_to_columnar
            cols = ops_to_columnar(cfg.model, [history],
                                   kinds=self.kinds)
            self.kinds = list(cols.kinds)
            r = check_columnar(
                cfg.model, cols, details="invalid",
                min_device_batch=cfg.min_device_batch,
                scheduler_opts={"resident": self.resident})[0]
            return r, "online"
        except StateSpaceExplosion:
            # Vocabulary too rich for the packed table: this tenant's
            # interim checks ride the host engine (recheck's own
            # degradation route).
            return self.host(cfg.model, history), "online-host"

    def check_delta(self, tenant) -> Optional[Tuple[dict, str]]:
        """Incremental interim check: resume the tenant's resident
        device frontier over the ops that arrived since the last tick
        — O(new ops) — rebuilding from op 0 on any invalidation
        (vocabulary renumbering, window overflow, a poisoned carry).
        Returns None when the incremental path cannot serve this
        tenant (state space exploded, window beyond the single-device
        mask axis, a deferred tenant still re-buffering): the caller
        falls back to the full-prefix engine, verdicts unchanged.

        Soundness guard: ANY exception mid-advance drops the carried
        frontier before propagating — a half-updated carry never
        survives into the next tick."""
        from .ops.linearize import DATA_MAX_SLOTS
        from .ops.schedule import FrontierInvalid, ResidentFrontier
        from .ops.statespace import StateSpaceExplosion

        if tenant.is_txn:
            # The WGL frontier has no transactional semantics; txn
            # tenants' interim checks ride the isolation certifier
            # (their O(new ops) path is the per-tick monitor).
            return None
        d = tenant.daemon
        # $JT_ONLINE_DC: the decrease-and-conquer incremental monitor
        # sits BEFORE the frontier's width guard — its carry is flat
        # in W, so it serves the wide tenants (peak_w beyond the
        # device mask axis) the frontier must decline. Certify-only:
        # a tick it cannot serve (residue, non-register ops, a read of
        # a pending write) falls through with verdicts unchanged, and
        # the same soundness guard applies — any mid-advance fault
        # drops the carried peel state before propagating.
        from .ops.dc_monitor import online_dc_enabled
        if online_dc_enabled():
            from .ops.dc_monitor import IncrementalDC
            dkey = (tenant.key, tenant.state.ino)
            inc = self._dc_inc.get(dkey)
            if inc is None:
                inc = self._dc_inc.setdefault(dkey, IncrementalDC())
            try:
                served = inc.advance(tenant.ops)
            except Exception:
                self._dc_inc.pop(dkey, None)
                raise
            if served:
                if inc.last_delta_ops:
                    d._count("delta_ops", inc.last_delta_ops)
                    telemetry.REGISTRY.counter(
                        "online.dc_delta_ops", tenant=tenant.name).inc(
                        inc.last_delta_ops)
                tenant.stats["dc_delta_checks"] = \
                    tenant.stats.get("dc_delta_checks", 0) + 1
                return {"valid": True}, "online-dc"
        if getattr(tenant, "_no_frontier", False) \
                or tenant.peak_w > DATA_MAX_SLOTS:
            return None
        key = (tenant.key, tenant.state.ino)
        frontiers = self.resident.frontiers
        fr = frontiers.get(key)
        if fr is None and tenant.frontier_ckpt is not None:
            # Restore ONCE and adopt immediately — even when the
            # re-tailing buffer hasn't caught up to the carry yet (the
            # guard below just skips those ticks); re-restoring every
            # lagging tick would re-pay the enumeration + bitset
            # decompression for nothing.
            fr = ResidentFrontier.restore(self.cfg.model,
                                          tenant.frontier_ckpt)
            tenant.frontier_ckpt = None
            if fr is not None:
                frontiers[key] = fr
                tenant.stats["frontier_restored"] = \
                    tenant.stats.get("frontier_restored", 0) + 1
        resumed = fr is not None
        if resumed and fr.pos > len(tenant.ops):
            # A deferred/rebuffering tenant hasn't re-tailed past the
            # carry's consumed prefix yet: not an invalidation, just
            # not servable incrementally this tick (a multi-GB WAL
            # re-tails over several polls; the adopted frontier waits).
            return None
        prov = "online-delta" if resumed else "online-rebuild"
        try:
            try:
                if fr is None:
                    fr = ResidentFrontier(self.cfg.model)
                valid, bad = fr.advance(tenant.ops)
            except FrontierInvalid:
                frontiers.pop(key, None)
                if not resumed:
                    # A FRESH build already failed (window beyond the
                    # device axis): rebuilding identically would fail
                    # identically — the full-prefix engine owns this
                    # tick, no second full-cost walk.
                    return None
                tenant._count_frontier(d, "frontier_invalidations")
                prov = "online-rebuild"
                try:
                    fr = ResidentFrontier(self.cfg.model)
                    valid, bad = fr.advance(tenant.ops)
                except FrontierInvalid:
                    return None
        except StateSpaceExplosion:
            tenant._no_frontier = True
            frontiers.pop(key, None)
            return None
        except Exception:
            frontiers.pop(key, None)
            if resumed:
                tenant._count_frontier(d, "frontier_invalidations")
            raise
        frontiers[key] = fr
        if resumed:
            tenant._count_frontier(d, "frontier_resumes")
        if fr.last_delta_ops:
            d._count("delta_ops", fr.last_delta_ops)
            telemetry.REGISTRY.counter(
                "online.delta_ops", tenant=tenant.name).inc(
                fr.last_delta_ops)
        tenant.stats["delta_checks"] = \
            tenant.stats.get("delta_checks", 0) + 1
        tenant.stats["delta_events_last"] = fr.last_events
        # Stalled-frontier visibility: a single never-completing
        # invocation pins the stable point, so the volatile tail — and
        # with it per-tick cost — grows with the prefix again (sound,
        # same cost class as the full path, but no longer O(new ops)).
        # Soundness forbids freezing past an open invocation; what we
        # CAN do is make the degradation loud instead of letting the
        # `inc` badge claim flat cost that isn't. A LATCHED-invalid
        # tenant is exempt: its ticks are O(1) served from the latch —
        # the un-advancing pos is the short-circuit, not a stall.
        tail = len(tenant.ops) - fr.pos
        tenant.stats["delta_tail_last"] = tail
        if fr.latched_bad is None and \
                tail > max(1024, 8 * self.cfg.check_interval_ops):
            if not tenant.stats.get("frontier_stalled"):
                log.warning(
                    "%s: open invocation at op %d pins the frontier's "
                    "stable point; the %d-op volatile tail re-checks "
                    "every tick (O(prefix) again) until it completes "
                    "or the run finalizes", tenant.key, fr.pos, tail)
            tenant.stats["frontier_stalled"] = \
                tenant.stats.get("frontier_stalled", 0) + 1
            telemetry.REGISTRY.counter(
                "online.frontier_stalls", tenant=tenant.name).inc()
        else:
            tenant.stats["frontier_stalled"] = 0
        # Checkpoint the carry whenever it advanced (or latched): a
        # restart or a PR-11 takeover replays only the undecided
        # suffix, with zero re-dispatched decided events.
        if tenant.journal is not None and \
                (fr.pos != tenant._frontier_ckpt_pos
                 or (not valid and not tenant._frontier_ckpt_bad)):
            try:
                tenant.journal.record_frontier(fr.export())
                tenant._frontier_ckpt_pos = fr.pos
                tenant._frontier_ckpt_bad = not valid
            except Exception:
                log.debug("frontier checkpoint failed", exc_info=True)
        if valid:
            return {"valid": True}, prov
        return {"valid": False, "op": {"index": bad}}, prov


# --------------------------------------------------------------- tenant

class OnlineTenant:
    """One tailed run: cursor + buffered prefix + decided-prefix
    journal + verdict-so-far."""

    def __init__(self, daemon: "OnlineDaemon", name: str, ts: str,
                 run_dir):
        self.daemon = daemon
        self.name, self.ts = name, ts
        self.key = f"{name}/{ts}"
        self.run_dir = Path(run_dir)
        self.wal_path = self.run_dir / WAL_FILE
        self.state = TailState()
        self.ops: List[Op] = []
        self.status = "tailing"         # tailing | deferred | done
        self.deferred_at: Optional[float] = None  # wall time (durable)
        self.result: Optional[dict] = None
        self.salvaged: Optional[bool] = None
        self.valid_so_far: Optional[bool] = None
        self.first_violation: Optional[dict] = None
        self.checked_ops = 0            # newest decided prefix length
        self.last_growth = time.monotonic()
        self.last_check_t = 0.0
        self.t_admitted = time.monotonic()
        self.t_first_verdict: Optional[float] = None
        self.rotations = 0
        self._widen_counted = False
        self.stats = {"checks": 0, "device_checks": 0, "host_checks": 0,
                      "resumed_prefixes": 0}
        self._open: set = set()
        self.peak_w = 0
        self.journal: Optional[ChunkJournal] = None
        self._decided: Dict[int, tuple] = {}
        # Incremental prefix checking (doc/online.md "The resident
        # frontier"): the journal's latest frontier-checkpoint row,
        # consumed once by the engine's first delta check; the
        # explosion latch (a vocabulary past the packed table never
        # shrinks); and the checkpoint watermark.
        self.frontier_ckpt: Optional[dict] = None
        self._no_frontier = False
        self._frontier_ckpt_pos = -1
        self._frontier_ckpt_bad = False
        # Live isolation monitoring (doc/isolation.md): the txn-
        # vocabulary latch, the lazy IncrementalIsolation monitor, its
        # fed-ops watermark, and the durable downgrade record.
        self.is_txn = False
        self._iso = None
        self._iso_cursor = 0
        self.iso_record: Optional[dict] = None
        # Restart rehydration, cheapest gate first: a durable final
        # verdict means ZERO work; a decided-prefix journal means zero
        # re-dispatch of decided prefixes; a deferred mark means the
        # overload pause survives the daemon. The verdict is bound to
        # its segment incarnation (inode): a WAL rotated/rewritten
        # AFTER finalization must be re-checked, not served a stale
        # verdict about content that no longer exists.
        v = daemon.store.online_verdict(name, ts)
        if v is not None and not self._verdict_stale(v):
            self.result = v.get("result")
            val = v.get("valid")
            # Tri-state, preserved: a finalized "unknown" must not
            # latch False across restarts (same data, same exit code).
            self.valid_so_far = (True if val is True
                                 else False if val is False else None)
            self.salvaged = v.get("salvaged")
            self.status = "done"
        elif (self.run_dir / ONLINE_DEFERRED).exists():
            self.status = "deferred"
            # The overload pause survives the daemon — and so must its
            # starvation deadline: the mark's own stamp, not this
            # incarnation's admit time, ages the deferral.
            try:
                self.deferred_at = float(json.loads(
                    (self.run_dir / ONLINE_DEFERRED).read_text()
                ).get("deferred_at") or time.time())
            except Exception:
                self.deferred_at = time.time()
        fv = daemon.store.first_violation(name, ts)
        if fv is not None:
            self.first_violation = fv
        iso = daemon.store.online_iso(name, ts)
        if iso is not None:
            self.iso_record = iso

    def corr_id(self) -> str:
        """This tenant's correlation id: run key + writer INCARNATION
        (the WAL segment's inode — the same identity the decided-
        prefix journal and verdict staleness checks key on). Every
        worker that ever serves this tenant — the original owner, a
        takeover survivor — derives the same id from the shared file,
        which is exactly what lets ``telemetry.merge_traces`` connect
        their spans across processes (doc/observability.md)."""
        if self.state.ino >= 0:
            return f"{self.key}#{self.state.ino}"
        return self.key

    def _verdict_stale(self, v: dict) -> bool:
        """A stored final verdict is stale when the WAL at this path
        is a different segment (inode) than the one it was computed
        over. Verdicts from before inode stamping (no ``ino`` key) and
        verdicts whose WAL has since vanished stay trusted — there is
        nothing newer to check."""
        ino = v.get("ino")
        if ino is None:
            return False
        try:
            return os.stat(self.wal_path).st_ino != ino
        except OSError:
            return False

    # ------------------------------------------------------------ state
    @property
    def pending(self) -> int:
        """Buffered ops not yet covered by a decided prefix — the unit
        every ladder threshold is expressed in."""
        return max(0, len(self.ops) - self.checked_ops)

    def _alive(self) -> bool:
        """Writer liveness for finalization. A WAL written by THIS
        process (in-process campaign + daemon) is live by definition —
        writer_alive() excludes our own pid for the salvage sweep's
        sake, the opposite of what a tailer wants."""
        h = self.state.header
        if (h or {}).get("pid") == os.getpid():
            return True
        return writer_alive(h)

    def _open_journal(self) -> None:
        """Create the decided-prefix journal once the header is known:
        the key binds it to this SEGMENT incarnation — writer pid +
        seed from the header plus the segment's inode — so a WAL
        rotated while the daemon was down (or truncated-and-rewritten
        by the same writer) auto-invalidates the old journal
        (ChunkJournal discards on key mismatch) instead of poisoning
        the new content with stale prefix verdicts, while a plain
        daemon restart over the unrotated segment keys identically and
        resumes every decided prefix."""
        h = self.state.header or {}
        self.journal = ChunkJournal(
            self.run_dir / ONLINE_JOURNAL,
            {"online": 1, "model": repr(self.daemon.cfg.model),
             "run": self.key, "wal": {"pid": h.get("pid"),
                                      "seed": h.get("seed")},
             "ino": self.state.ino},
            resume=True)
        self._decided = self.journal.decided()
        self.frontier_ckpt = self.journal.frontier()
        if self._decided:
            self.stats["resumed_prefixes"] = len(self._decided)
            self.daemon._count("resumed_prefixes",
                               len(self._decided))
            k = max(self._decided)
            valid, bad, _prov = self._decided[k]
            self.checked_ops = k
            self.valid_so_far = bool(valid)

    def _track_w(self, op: Op) -> None:
        # :info completions do NOT close the slot — the op pends
        # forever, which is exactly what the encoder's window must
        # hold; the admission estimate has to agree with it.
        if op.f == "txn":
            self.is_txn = True
        if op.type == INVOKE:
            self._open.add(op.process)
            if len(self._open) > self.peak_w:
                self.peak_w = len(self._open)
        elif op.type in (OK, FAIL):
            self._open.discard(op.process)

    def _count_frontier(self, d, key: str) -> None:
        """One frontier lifecycle event: daemon stats + the per-tenant
        labeled registry counter (the ISSUE-14 telemetry surface)."""
        d._count(key)
        telemetry.REGISTRY.counter(f"online.{key}",
                                   tenant=self.name).inc()

    def _drop_frontier(self, *, invalidated: bool) -> None:
        """Void every carried frontier for this tenant (any
        incarnation) — rotation and finalization both end the carry's
        life; rotation counts as an invalidation."""
        frontiers = self.daemon.engine.resident.frontiers
        mine = [k for k in frontiers if k[0] == self.key]
        for k in mine:
            del frontiers[k]
        if invalidated and mine:
            self._count_frontier(self.daemon, "frontier_invalidations")
        self.frontier_ckpt = None
        self._frontier_ckpt_pos = -1
        self._frontier_ckpt_bad = False

    def _reset_segment(self) -> None:
        """The path names different content now (rotation): everything
        derived from the old segment is void — including the durable
        first-violation record, which described ops that no longer
        exist (and would otherwise both badge the clean new segment
        invalid and block the NEW segment's first violation from ever
        persisting)."""
        self.ops = []
        self.checked_ops = 0
        self.valid_so_far = None
        self._open = set()
        self.peak_w = 0
        self._decided = {}
        self._drop_frontier(invalidated=True)
        if self.journal is not None:
            self.journal.finish()       # old-content rows: delete
            self.journal = None
        if self.first_violation is not None:
            self.first_violation = None
            fv = self.run_dir / FIRST_VIOLATION
            if fv.exists():
                fv.unlink()
        self._iso = None
        self._iso_cursor = 0
        if self.iso_record is not None:
            self.iso_record = None
            rec = self.run_dir / ONLINE_ISO
            if rec.exists():
                rec.unlink()

    # ------------------------------------------------------------- tail
    def tail(self) -> bool:
        """One poll: consume whatever whole lines the writer has made
        durable. Returns True when the prefix grew. The ingest buffer
        is bounded: past ``max_buffered_ops`` undecided ops the tail
        stops reading ahead of the checker (counted backpressure) —
        the WAL itself is the overflow queue."""
        d = self.daemon
        if self.pending >= d.cfg.max_buffered_ops:
            d._count("backpressure")
            return False
        self.state, out = tail_wal(self.wal_path, self.state)
        if out["rotated"]:
            # Reset BEFORE the bad-magic drop: a WAL replaced by a
            # non-WAL file reports both in one call, and the old
            # segment's artifacts (decided prefixes, the durable
            # first-violation record) describe content that no longer
            # exists either way.
            self.rotations += 1
            d._count("rotations")
            log.warning("%s rotated under the cursor; restarting the "
                        "tail from offset 0", self.wal_path)
            self._reset_segment()
        if out["bad_magic"]:
            log.warning("%s: not a history WAL; dropping tenant",
                        self.wal_path)
            self.status = "done"
            return False
        if out["missing"]:
            return False
        if self.journal is None and self.state.header is not None \
                and self.status == "tailing":
            self._open_journal()
        if out["grew"]:
            for op in out["ops"]:
                self._track_w(op)
            self.ops.extend(out["ops"])
            self.last_growth = time.monotonic()
            if out["ops"]:
                # The daemon's ingest meter — what the service layer's
                # cluster-wide ingest-rate budget is enforced against.
                d._count("ingested_ops", len(out["ops"]))
            self._iso_tick()
        return bool(out["grew"])

    def _iso_tick(self) -> None:
        """Feed newly tailed ops to the live isolation monitor
        (isolation.IncrementalIsolation) and durably persist level
        DOWNGRADES as ``online-iso.json`` — the first-violation
        pattern, keyed to the segment incarnation. Only txn-vocabulary
        tenants ever allocate a monitor; $JT_ONLINE_ISO=0 disables the
        whole tick. The monitor is advisory observability — a failure
        here must never cost the tenant its verdict."""
        d = self.daemon
        if not d.cfg.iso or not self.is_txn \
                or self._iso_cursor >= len(self.ops):
            return
        try:
            from .isolation import IncrementalIsolation
            from .ops.txn_graph import LADDER, iso_abbrev
            if self._iso is None:
                self._iso = IncrementalIsolation()
            new = self.ops[self._iso_cursor:]
            self._iso_cursor = len(self.ops)
            level = self._iso.observe(new)
        except Exception:
            log.warning("isolation monitor tick of %s failed",
                        self.key, exc_info=True)
            return
        if level is None or level == "serializability":
            return
        prev = (self.iso_record or {}).get("level")
        if prev in LADDER and LADDER.index(level) >= LADDER.index(prev):
            return
        rec = {"run": self.key, "level": level,
               "abbrev": iso_abbrev(level),
               "prefix_ops": len(self.ops), "ino": self.state.ino,
               "detected_at": time.time()}
        atomic_write_json(self.run_dir / ONLINE_ISO, rec)
        self.iso_record = rec
        d._count("iso_downgrades")
        log.warning("ISOLATION DOWNGRADE in %s: %s (caught at a "
                    "%d-op prefix)", self.key, level, len(self.ops))

    # ----------------------------------------------------------- checks
    def _note_verdict(self, verdict, bad: Optional[int],
                      prefix_ops: int, prov: str) -> None:
        """Fold one check's verdict into the tenant's running state.
        Only an EXPLICIT True/False is a verdict: a host-engine
        ``"unknown"`` (config budget exhausted) carries no information
        — it must neither latch ``valid_so_far`` false, nor persist a
        first-violation record, nor count as the first verdict (a
        post-mortem recheck of the same run would say unknown, not
        invalid)."""
        d = self.daemon
        if verdict not in (True, False):
            d._count("unknown_verdicts")
            return
        if verdict is False:
            self.valid_so_far = False
        elif self.valid_so_far is None:
            self.valid_so_far = True
        if self.t_first_verdict is None:
            self.t_first_verdict = time.monotonic()
            ttfv = self.t_first_verdict - self.t_admitted
            telemetry.REGISTRY.histogram("online.ttfv_s").observe(ttfv)
            telemetry.REGISTRY.histogram(
                "online.ttfv_s", tenant=self.name).observe(ttfv)
        if verdict is False and self.first_violation is None:
            fv = {"run": self.key, "op_index": bad,
                  "prefix_ops": prefix_ops, "mode": prov,
                  "ino": self.state.ino, "detected_at": time.time()}
            atomic_write_json(self.run_dir / FIRST_VIOLATION, fv)
            self.first_violation = fv
            d._count("first_violations")
            log.warning("FIRST VIOLATION in %s: op %s (caught at a "
                        "%d-op prefix, %s)", self.key, bad, prefix_ops,
                        prov)

    def interim_check(self, shed: bool) -> None:
        """Dispatch one rolling prefix check. Journal-gated: a prefix
        length decided by an earlier daemon incarnation is never
        re-dispatched (ChunkJournal.record enforces it structurally)."""
        d = self.daemon
        k = len(self.ops)
        if k < d.cfg.min_check_ops or k == self.checked_ops \
                or k in self._decided:
            return
        with telemetry.correlation_scope(self.corr_id()), \
                telemetry.span("online.check", tenant=self.key,
                               ops=k, shed=bool(shed)):
            d._fire("encode")
            r = prov = None
            if d.cfg.incremental and not shed:
                # The O(new ops) path: resume the resident device
                # frontier over the delta. The dispatch-stage fault
                # fires BEFORE the carry is touched, so an injected
                # fault costs a retried tick, never a poisoned carry.
                d._fire("dispatch")
                out = d.engine.check_delta(self)
                if out is not None:
                    r, prov = out
            if r is None:
                history = checkable_prefix(self.ops)
                if not (d.cfg.incremental and not shed):
                    d._fire("dispatch")
                r, prov = d.engine.check(history, shed=shed)
            verdict = r.get("valid")
            bad = _bad_index(r)
            if verdict in (True, False):
                # Only explicit verdicts are DECIDED: an "unknown" is
                # neither journaled (a restart should re-try it) nor
                # latched — but checked_ops still advances, so this
                # incarnation doesn't hot-loop the same undecidable
                # prefix every poll.
                if self.journal is not None:
                    self.journal.record([k], [verdict], [bad], [prov])
                self._decided[k] = (bool(verdict), bad, prov)
            self.checked_ops = k
            self._widen_counted = False
            self.stats["checks"] += 1
            self.stats["host_checks" if prov == "online-host"
                       else "device_checks"] += 1
            self.last_check_t = time.monotonic()
            d._count("checks")
            d._count("host_checks" if prov == "online-host"
                     else "device_checks")
            self._note_verdict(verdict, bad, k, prov)

    # --------------------------------------------------------- finalize
    def should_finalize(self) -> bool:
        if self.status != "tailing":
            return False
        if self.state.header is None:
            # No durable header: the writer fsyncs it at WAL creation,
            # so a headerless file past the quiescence window was
            # killed inside that first fsync (or isn't growing a
            # header ever). There is nothing salvageable — the
            # post-mortem sweep refuses the same WAL — but the tenant
            # must still RETIRE (durable unknown verdict), or
            # ``watch --until-idle`` polls a dead run forever.
            return (time.monotonic() - self.last_growth) \
                >= self.daemon.cfg.crash_quiet_s
        if self.state.phase == "analyzed":
            return True
        return (not self._alive()
                and (time.monotonic() - self.last_growth)
                >= self.daemon.cfg.crash_quiet_s)

    def _drain_tail(self) -> None:
        """Consume the WAL to its durable end before finalizing. The
        ingest bound (``max_buffered_ops``) can legitimately leave
        unread bytes behind a backlogged checker; the FINAL verdict
        must cover the whole segment regardless — a post-mortem
        recheck would — so the drain bypasses the buffer bound (memory
        here is bounded by the WAL itself, exactly like salvage's full
        read). Bounded iterations: each call consumes up to the tail
        read budget, and a segment that keeps growing mid-drain is a
        live writer, which should_finalize already excluded."""
        for _ in range(4096):
            self.state, out = tail_wal(self.wal_path, self.state)
            if out["rotated"]:
                self.rotations += 1
                self.daemon._count("rotations")
                self._reset_segment()
            if not out["grew"]:
                return
            for op in out["ops"]:
                self._track_w(op)
            self.ops.extend(out["ops"])

    def _final_history(self) -> Tuple[List[Op], bool]:
        """The exact history a post-mortem recheck would see. Complete
        runs prefer the stored history.jsonl (byte-equal to the WAL —
        test_durability pins it — and what Store.recheck reads);
        crashed runs apply salvage_history, the same transform
        Store.salvage materializes."""
        if self.state.phase == "analyzed":
            hist = self.run_dir / "history.jsonl"
            if hist.exists():
                from .history.codec import read_jsonl
                try:
                    return read_jsonl(hist), False
                except Exception:
                    pass
            return index([op.with_() for op in self.ops]), False
        history, _dangling = salvage_history(self.ops)
        return history, True

    def finalize(self) -> None:
        """The run is over (analyzed, or the writer died): produce the
        durable final verdict through the parity-exact engine call,
        then retire the prefix journal — online-verdict.json gates any
        later restart."""
        d = self.daemon
        d._fire("encode")
        self._drain_tail()
        # The monitor's last word covers the whole drained segment, so
        # its final verdict and the post-mortem certification describe
        # the same ops.
        self._iso_tick()
        with telemetry.correlation_scope(self.corr_id()), \
                telemetry.span("online.finalize", tenant=self.key,
                               ops=len(self.ops)):
            self._finalize_inner(d)

    def _finalize_inner(self, d) -> None:
        if self.state.header is None:
            # Killed before the header fsync: nothing salvageable
            # (Store.salvage raises "empty WAL" on the same file).
            # Retire with a durable UNKNOWN — never a claimed pass.
            self.result = {"valid": "unknown",
                           "error": "no durable WAL header"}
            self.salvaged = True
            atomic_write_json(self.run_dir / ONLINE_VERDICT, {
                "run": self.key, "valid": "unknown", "bad_index": None,
                "ops": 0, "ino": self.state.ino, "salvaged": True,
                "unrecoverable": "no durable WAL header",
                "model": repr(d.cfg.model),
                "checks": self.stats["checks"], "first_violation": None,
                "ttfv_s": None, "finalized_at": time.time(),
                "result": self.result})
            self.status = "done"
            d._count("finalized")
            log.warning("finalized %s as UNKNOWN: no durable WAL "
                        "header (killed inside the first fsync?)",
                        self.key)
            return
        history, salvaged = self._final_history()
        d._fire("dispatch")
        r, prov = d.engine.check(history, final=True)
        bad = _bad_index(r)
        self.result = r
        self.salvaged = salvaged
        self.stats["checks"] += 1
        self.stats["device_checks"] += 1
        d._count("checks")
        d._count("device_checks")
        self._note_verdict(r.get("valid"), bad, len(history), prov)
        verdict = {
            "run": self.key, "valid": r.get("valid"),
            "bad_index": bad, "ops": len(history),
            "ino": self.state.ino,
            "salvaged": salvaged, "model": repr(d.cfg.model),
            "checks": self.stats["checks"],
            "first_violation": self.first_violation,
            "ttfv_s": (round(self.t_first_verdict - self.t_admitted, 4)
                       if self.t_first_verdict is not None else None),
            "finalized_at": time.time(),
            "result": r,
        }
        # Exotic values (Op objects from the host engine) degrade to
        # repr for the FILE; the in-memory result keeps full fidelity.
        verdict = json.loads(json.dumps(verdict, default=repr))
        atomic_write_json(self.run_dir / ONLINE_VERDICT, verdict)
        if self.journal is not None:
            self.journal.finish()
            self.journal = None
        self._drop_frontier(invalidated=False)
        mark = self.run_dir / ONLINE_DEFERRED
        if mark.exists():
            mark.unlink()
        self.status = "done"
        d._count("finalized")
        log.info("finalized %s: valid=%s bad=%s (%s, %d ops, %d checks)",
                 self.key, r.get("valid"), bad,
                 "salvaged" if salvaged else "complete", len(history),
                 self.stats["checks"])

    # ------------------------------------------------------------ defer
    def defer(self) -> None:
        """Overload L3: pause this tenant durably, release its buffer
        (the WAL itself is the queue; the journal keeps its decided
        prefixes, so resuming re-dispatches none of them)."""
        self.deferred_at = time.time()
        atomic_write_json(self.run_dir / ONLINE_DEFERRED,
                          {"run": self.key,
                           "deferred_at": self.deferred_at,
                           "pending": self.pending})
        if self.journal is not None:
            self.journal.close()
            self.journal = None
        self.ops = []
        self.state = TailState()
        self._open = set()
        self.peak_w = 0
        # The monitor re-feeds from op 0 when the tail re-buffers; the
        # durable downgrade record (online-iso.json) carries the floor
        # across the pause.
        self._iso = None
        self._iso_cursor = 0
        self.status = "deferred"

    def resume(self) -> None:
        mark = self.run_dir / ONLINE_DEFERRED
        if mark.exists():
            mark.unlink()
        self.status = "tailing"
        self.deferred_at = None
        self.last_growth = time.monotonic()

    def close(self) -> None:
        # The carried frontier goes with the tenant: a ServiceWorker
        # releasing (or losing) a tenant must not pin its bitsets for
        # the worker's lifetime — the journal checkpoint is the
        # durable copy the next owner resumes from.
        self._drop_frontier(invalidated=False)
        if self.journal is not None:
            self.journal.close()
            self.journal = None

    def summary(self) -> dict:
        return {"status": self.status, "phase": self.state.phase,
                "ops": len(self.ops), "checked_ops": self.checked_ops,
                "pending": self.pending, "peak_w": self.peak_w,
                "valid_so_far": self.valid_so_far,
                "first_violation": (self.first_violation or {}).get(
                    "op_index"),
                "salvaged": self.salvaged,
                "checks": self.stats["checks"],
                "host_checks": self.stats["host_checks"],
                "resumed_prefixes": self.stats["resumed_prefixes"],
                "incremental": bool(
                    (self.key, self.state.ino)
                    in self.daemon.engine.resident.frontiers),
                "delta_checks": self.stats.get("delta_checks", 0),
                "rotations": self.rotations,
                # Live isolation verdict (txn tenants): the monitor's
                # current abbreviated level, else the durable downgrade
                # record's — None for non-transactional tenants.
                "iso": (self._iso.abbrev() if self._iso is not None
                        else (self.iso_record or {}).get("abbrev")),
                # Wire-fed tenant (landed by the ingest plane rather
                # than a filesystem writer) — display-only: every
                # checking/finalization path treats both identically.
                "wire": (self.state.header or {}).get("ingest")
                == "wire"}


# --------------------------------------------------------------- daemon

class OnlineDaemon:
    """The multi-tenant online checking service. ``tick()`` is one
    poll pass (tests drive it directly); ``run()`` is the jittered
    serving loop ``jepsen-tpu watch`` wraps in a GracefulShutdown."""

    def __init__(self, store: Optional[Store] = None,
                 config: Optional[OnlineConfig] = None,
                 faults: Optional[DaemonFaultInjector] = None):
        self.store = store if store is not None else DEFAULT
        self.cfg = config if config is not None else OnlineConfig()
        self.engine = OnlineCheckEngine(self.cfg)
        self.faults = faults if faults is not None \
            else DaemonFaultInjector.from_env()
        self.tenants: Dict[Tuple[str, str], OnlineTenant] = {}
        self._refused: set = set()
        self.stats = {"ticks": 0, "admitted": 0, "refused": 0,
                      "checks": 0, "device_checks": 0, "host_checks": 0,
                      "shed": 0, "shed_wclass": 0, "widened": 0,
                      "deferred": 0, "resumed": 0, "rate_deferred": 0,
                      "backpressure": 0, "rotations": 0,
                      "stage_faults": 0, "check_errors": 0,
                      "unknown_verdicts": 0, "first_violations": 0,
                      "finalized": 0, "resumed_prefixes": 0,
                      "ingested_ops": 0, "iso_downgrades": 0,
                      "delta_ops": 0, "frontier_resumes": 0,
                      "frontier_invalidations": 0,
                      "deferred_starvation_rescues": 0}
        self._t0 = time.monotonic()
        # Cluster observability plane: periodic registry frames into
        # this worker's series ring file plus the cadence-bounded SLO
        # alert evaluator (doc/observability.md). Both are tick hooks
        # that cost one monotonic compare when not due.
        self._series = series_mod.SeriesWriter(self.store.base) \
            if series_mod.enabled() else None
        self._alerts = alerts_mod.AlertEvaluator(self.store.base) \
            if alerts_mod.enabled() else None

    # ---------------------------------------------------------- helpers
    def _count(self, key: str, n: int = 1) -> None:
        self.stats[key] = self.stats.get(key, 0) + n
        telemetry.REGISTRY.counter(f"online.{key}").inc(n)

    def _fire(self, stage: str) -> None:
        if self.faults is not None:
            self.faults.fire(stage)

    def _active(self) -> List[OnlineTenant]:
        return [t for t in self.tenants.values()
                if t.status == "tailing"]

    # -------------------------------------------------------- admission
    def discover(self) -> None:
        """Admit every incomplete run in the store (live WAL, no
        results.json) up to the tenant bound. Finalized-by-us runs
        rehydrate as ``done`` from their verdict file — free."""
        for name, ts in self.store.incomplete(include_salvaged=True):
            key = (name, ts)
            if key in self.tenants:
                continue
            active = sum(1 for t in self.tenants.values()
                         if t.status != "done")
            if active >= self.cfg.max_tenants:
                # One refusal EVENT per run, not one per poll — the
                # counter is an SLO transition signal, and a steady
                # over-capacity store must not grow it at tick rate.
                if key not in self._refused:
                    self._refused.add(key)
                    self._count("refused")
                continue
            self._refused.discard(key)
            t = OnlineTenant(self, name, ts,
                             self.store.run_dir(name, ts))
            self.tenants[key] = t
            if t.status != "done":
                self._count("admitted")

    def overload_level(self) -> int:
        """0..3 by total undecided backlog — the ladder's input."""
        cfg = self.cfg
        pending = sum(t.pending for t in self._active())
        telemetry.REGISTRY.gauge("online.pending_ops").set(pending)
        telemetry.REGISTRY.gauge("online.tenants").set(
            len(self._active()))
        if pending >= cfg.defer_pending_ops:
            return 3
        if pending >= cfg.shed_pending_ops:
            return 2
        if pending >= cfg.overload_pending_ops:
            return 1
        return 0

    # ------------------------------------------------------------- tick
    def _service_check(self, t: OnlineTenant, level: int) -> None:
        cfg = self.cfg
        if t.status != "tailing":
            return
        if t.should_finalize():
            try:
                t.finalize()
            except DaemonFault:
                # Retried next tick; finalize is idempotent (the
                # verdict file lands atomically at the very end).
                self._count("stage_faults")
            except Exception:
                # A real engine failure must not kill the SERVICE —
                # the other tenants keep their verdicts; this one
                # retries at poll cadence, loudly.
                self._count("check_errors")
                log.warning("finalize of %s failed; retrying next "
                            "tick", t.key, exc_info=True)
            return
        interval = max(cfg.min_check_ops, cfg.check_interval_ops)
        widened = interval * cfg.widen_factor
        if t.pending < interval:
            t._widen_counted = False
        if level >= 1 and interval <= t.pending < widened:
            # This check would have run at the base cadence; widening
            # deferred it — the L1 ladder rung, counted once per
            # deferred check (not once per idle poll re-visiting the
            # same backlog).
            if not t._widen_counted:
                t._widen_counted = True
                self._count("widened")
            return
        if t.pending < (widened if level >= 1 else interval):
            return
        if cfg.rate_checks_per_s > 0 and \
                (time.monotonic() - t.last_check_t) \
                < 1.0 / cfg.rate_checks_per_s:
            self._count("rate_deferred")
            return
        shed = level >= 2
        if t.peak_w > cfg.max_w:
            # W-class admission: an over-wide prefix is exponential
            # device cost — it rides the host oracle instead.
            shed = True
            self._count("shed_wclass")
        if shed:
            self._count("shed")
        try:
            t.interim_check(shed)
        except DaemonFault:
            self._count("stage_faults")
        except Exception:
            self._count("check_errors")
            log.warning("interim check of %s failed; retrying next "
                        "tick", t.key, exc_info=True)

    def tick(self) -> int:
        """One poll pass: ingest (tail) every active tenant FIRST, so
        the overload level sees the true backlog, then walk the ladder
        and service checks/finalizations fresh-prefix-first. Returns
        the overload level the check phase ran at."""
        self.stats["ticks"] += 1
        telemetry.REGISTRY.counter("online.ticks").inc()
        self.discover()
        for t in self._active():
            try:
                self._fire("tail")
                t.tail()
            except DaemonFault:
                self._count("stage_faults")
        level = self.overload_level()
        active = self._active()
        if level >= 3 and len(active) > 1:
            # L3: pause the STALEST tenant (durably) — the freshest
            # prefixes keep their time-to-first-verdict.
            stalest = min(active, key=lambda t: t.last_growth)
            log.warning("overload: deferring tenant %s (%d ops "
                        "pending)", stalest.key, stalest.pending)
            stalest.defer()
            self._count("deferred")
        elif level <= 1:
            deferred = [t for t in self.tenants.values()
                        if t.status == "deferred"]
            if deferred:
                t = min(deferred, key=lambda t: t.t_admitted)
                t.resume()
                self._count("resumed")
        if level >= 2 and self.cfg.defer_max_s > 0:
            # Deferred-starvation deadline: "resumes as load drops" is
            # not a liveness guarantee under a PERSISTENTLY busy
            # daemon. Past defer_max_s the stalest deferred tenant is
            # force-admitted ahead of fresh prefixes, load or no load.
            now = time.time()
            overdue = [t for t in self.tenants.values()
                       if t.status == "deferred"
                       and t.deferred_at is not None
                       and now - t.deferred_at
                       >= self.cfg.defer_max_s]
            if overdue:
                t = min(overdue, key=lambda t: t.deferred_at)
                log.warning(
                    "deferred tenant %s blew its %.0fs re-admission "
                    "deadline under sustained load; force-admitting "
                    "it ahead of fresh prefixes", t.key,
                    self.cfg.defer_max_s)
                t.resume()
                self._count("resumed")
                self._count("deferred_starvation_rescues")
        # Fresh-prefix-first: the most recently grown tenants are
        # serviced first, so a hot run's verdict lag stays at one
        # interval even when a cold backlog exists.
        for t in sorted(self._active(), key=lambda t: -t.last_growth):
            self._service_check(t, level)
        self._persist_registry()
        if self._series is not None:
            self._series.maybe_append()
        if self._alerts is not None:
            self._alerts.maybe_eval()
        return level

    def _persist_registry(self) -> None:
        try:
            self.store.save_online_registry({
                "updated_at": time.time(), "pid": os.getpid(),
                "stats": dict(self.stats),
                "tenants": {t.key: t.summary()
                            for t in self.tenants.values()}})
        except Exception:
            log.debug("online registry persist failed", exc_info=True)

    # ------------------------------------------------------------- loop
    def idle(self) -> bool:
        return all(t.status == "done" for t in self.tenants.values())

    def run(self, *, stop=None, ticks: Optional[int] = None,
            until_idle: bool = False) -> dict:
        """The serving loop: tick, then sleep a jittered poll interval
        (early-woken by ``stop``). Bounded by ``ticks`` when given;
        ``until_idle`` exits once every tenant is finalized."""
        n = 0
        while True:
            self.tick()
            n += 1
            if ticks is not None and n >= ticks:
                break
            if until_idle and self.idle():
                break
            if stop is not None and stop.is_set():
                break
            delay = self.cfg.poll_s * (
                1.0 + self.cfg.jitter * random.random())
            if stop is not None:
                if stop.wait(delay):
                    break
            else:
                time.sleep(delay)
        return self.status()

    def status(self) -> dict:
        return {"wall_s": round(time.monotonic() - self._t0, 3),
                "stats": dict(self.stats),
                "tenants": {t.key: t.summary()
                            for t in self.tenants.values()},
                "slo": telemetry.metrics_prefixed("online."),
                "valid": all(t.valid_so_far is not False
                             for t in self.tenants.values())}

    def close(self) -> None:
        """Daemon shutdown: close (never delete) every open journal —
        decided prefixes are the next incarnation's resume point — and
        leave the registry current."""
        for t in self.tenants.values():
            t.close()
        self._persist_registry()
        if self._series is not None:
            # The shutdown frame: the series' last word for this
            # worker is its final counter state, not mid-flight.
            self._series.close(final_frame=True)
            self._series = None


def watch_store(store: Optional[Store] = None, *, model=None,
                stop=None, ticks: Optional[int] = None,
                until_idle: bool = False, **cfg_kw) -> dict:
    """One-call service entry (the ``jepsen-tpu watch`` body): build a
    daemon over ``store`` and serve. Returns the final status dict."""
    cfg = OnlineConfig(model=model, **cfg_kw)
    daemon = OnlineDaemon(store=store, config=cfg)
    try:
        return daemon.run(stop=stop, ticks=ticks, until_idle=until_idle)
    finally:
        daemon.close()
