"""RobustIRC suite: set semantics on a Raft-replicated IRC network.

Mirrors the reference suite (robustirc/src/jepsen/robustirc.clj): build
via the Go toolchain (go get, 26-38), upload the shared TLS cert/key
(40-44), start the primary with ``-singlenode`` to found the network,
then every other node joins it with ``-join=<primary>:13001`` (46-79);
teardown is killall + network-dir wipe (81-84). Messages posted to a
channel and read back form the set workload (102-170) — shared with
the elasticsearch module here — run against casd's set endpoints in
local mode.
"""
from __future__ import annotations

import tempfile
from pathlib import Path

from ..control import core as c
from ..control import util as cu
from ..db import DB
from ..os_impl import debian
from ..runtime import primary, synchronize
from .elasticsearch import SetClient, set_workload
from .local_common import service_test

# Explicit absolute paths — '~' would be shell-quoted by the command
# escaper and never tilde-expand on the node.
GOPATH = "/root/gocode"
BINARY = f"{GOPATH}/bin/robustirc"
DATA_DIR = "/var/lib/robustirc"
PORT = 13001
NETWORK = "jepsen"
PASSWORD = "secret"


def _common_flags(node) -> list:
    return [f"-listen={node}:{PORT}",
            f"-network_password={PASSWORD}",
            f"-network_name={NETWORK}",
            "-tls_cert_path=/tmp/cert.pem",
            "-tls_ca_file=/tmp/cert.pem",
            "-tls_key_path=/tmp/key.pem"]


class RobustIrcDB(DB):
    """Go-built RobustIRC network (robustirc.clj:23-84): primary founds
    the network single-node, the rest join it."""

    def __init__(self, cert: str | None = None, key: str | None = None):
        # Local paths of a pre-generated TLS pair (the reference ships
        # resources/cert.pem + key.pem from gencert.go). With none
        # given, a self-signed pair is generated on the node instead —
        # silently starting daemons that would die on missing cert
        # files is not an option.
        self.cert = cert
        self.key = key

    def setup(self, test, node):
        with c.su():
            cu.meh(c.exec_, "killall", "robustirc")
            debian.install(["golang-go", "mercurial"])
            c.exec_("env", f"GOPATH={GOPATH}", "go", "get", "-u",
                    "github.com/robustirc/robustirc")
            if self.cert is not None:
                if not Path(self.cert).exists():
                    raise FileNotFoundError(
                        f"TLS pair {self.cert} not found locally")
                c.upload(self.cert, "/tmp/cert.pem")
                c.upload(self.key, "/tmp/key.pem")
            else:
                # One shared pair for the whole network (every node's
                # -tls_ca_file must validate every other node): the
                # primary generates it, the control host relays it to
                # the rest. Per-node certs would break raft joins.
                pair = test.setdefault("_robustirc_tls", {})
                if node == primary(test):
                    c.exec_("openssl", "req", "-x509", "-newkey",
                            "rsa:2048", "-keyout", "/tmp/key.pem",
                            "-out", "/tmp/cert.pem", "-days", "365",
                            "-nodes", "-subj", f"/CN={NETWORK}")
                    tmp = tempfile.mkdtemp(prefix="jepsen-robustirc-")
                    for f in ("cert.pem", "key.pem"):
                        c.download(f"/tmp/{f}", f"{tmp}/{f}")
                        pair[f] = f"{tmp}/{f}"
                synchronize(test)
                if node != primary(test):
                    for f in ("cert.pem", "key.pem"):
                        c.upload(pair[f], f"/tmp/{f}")
            c.exec_("rm", "-rf", DATA_DIR)
            c.exec_("mkdir", "-p", DATA_DIR)
            synchronize(test)
            if node == primary(test):
                cu.start_daemon(
                    {"logfile": f"{DATA_DIR}/robustirc.log",
                     "pidfile": f"{DATA_DIR}/robustirc.pid",
                     "chdir": DATA_DIR},
                    BINARY, *_common_flags(node), "-singlenode")
            synchronize(test)
            if node != primary(test):
                cu.start_daemon(
                    {"logfile": f"{DATA_DIR}/robustirc.log",
                     "pidfile": f"{DATA_DIR}/robustirc.pid",
                     "chdir": DATA_DIR},
                    BINARY, *_common_flags(node),
                    f"-join={primary(test)}:{PORT}")
            synchronize(test)

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "killall", "robustirc")
            c.exec_("rm", "-rf", DATA_DIR)

    def log_files(self, test, node):
        return [f"{DATA_DIR}/robustirc.log"]


def robustirc_test(**opts) -> dict:
    """The set workload (robustirc.clj:102-170: post messages, read the
    channel back) in local mode against casd's set endpoints."""
    return service_test(
        "robustirc",
        SetClient(opts.get("client_timeout", 0.5)),
        set_workload(opts), **opts)
