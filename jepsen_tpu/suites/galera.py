"""Galera suite: bank serializability on MariaDB Galera.

Mirrors the reference suite (galera/src/jepsen/galera.clj): install from
the mariadb apt repo with debconf-preseeded root password and a
squirreled copy of the stock data dir (34-57), write the wsrep cluster
address into jepsen.cnf (64-73), bootstrap the primary with
``service mysql start --wsrep-new-cluster`` while the rest plain-start
and join (102-122), create the jepsen database + grant (96-100), and
teardown by killing mysqld, truncating logs, and restoring the stock
data dir (123-131). The workload (galera.clj:240-339) is the bank
family — shared with the cockroach module here — run against casd's
bank endpoints in local mode.
"""
from __future__ import annotations

from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..runtime import primary, synchronize

REPO_LINE = ("deb http://sfo1.mirrors.digitalocean.com/mariadb/repo/10.0/"
             "debian jessie main")
KEYSERVER = "keyserver.ubuntu.com"
KEY = "0xcbcb082a1bb943db"
LOG_FILES = ["/var/log/syslog", "/var/log/mysql.log", "/var/log/mysql.err"]
DIR = "/var/lib/mysql"
STOCK_DIR = "/var/lib/mysql-stock"

DEBCONF = [
    "mariadb-galera-server-10.0 mysql-server/root_password password jepsen",
    "mariadb-galera-server-10.0 mysql-server/root_password_again password "
    "jepsen",
    "mariadb-galera-server-10.0 mysql-server-5.1/start_on_boot boolean "
    "false",
]


def cluster_address(test: dict) -> str:
    """gcomm:// over every node (galera.clj:59-62)."""
    return "gcomm://" + ",".join(str(n) for n in test.get("nodes") or [])


def jepsen_cnf(test: dict) -> str:
    """The reference's resources/jepsen.cnf with %CLUSTER_ADDRESS%
    substituted (galera.clj:64-73)."""
    return "\n".join([
        "[mysqld]",
        "wsrep_provider=/usr/lib/galera/libgalera_smm.so",
        f"wsrep_cluster_address={cluster_address(test)}",
        "wsrep_cluster_name=jepsen",
        "binlog_format=ROW",
        "default_storage_engine=InnoDB",
        "innodb_autoinc_lock_mode=2",
    ])


def sql(statement: str) -> str:
    """Eval a SQL string via the CLI (galera.clj:81-84)."""
    return c.exec_("mysql", "-u", "root", "--password=jepsen", "-e",
                   statement)


def setup_db() -> None:
    """Create the jepsen database + grant (galera.clj:96-100)."""
    sql("create database if not exists jepsen;")
    sql("GRANT ALL PRIVILEGES ON jepsen.* "
        "TO 'jepsen'@'%' IDENTIFIED BY 'jepsen';")


class GaleraDB(DB):
    """MariaDB Galera cluster (galera.clj:34-131)."""

    def setup(self, test, node):
        with c.su():
            debian.add_repo("galera", REPO_LINE, KEYSERVER, KEY)
            for line in DEBCONF:
                c.exec_star(f"echo {c.escape(line)} | "
                            f"debconf-set-selections")
            debian.install(["rsync"])
            if "mariadb-galera-server" not in debian.installed(
                    ["mariadb-galera-server"]):
                debian.install(["mariadb-galera-server"])
                c.exec_("service", "mysql", "stop")
                # Squirrel away a stock copy so teardown can restore a
                # pristine data dir (galera.clj:55-57).
                c.exec_("rm", "-rf", STOCK_DIR)
                c.exec_("cp", "-rp", DIR, STOCK_DIR)
            c.exec_("echo", jepsen_cnf(test), lit(">"),
                    "/etc/mysql/conf.d/jepsen.cnf")
            if node == primary(test):
                c.exec_("service", "mysql", "start",
                        "--wsrep-new-cluster")
            synchronize(test)
            if node != primary(test):
                c.exec_("service", "mysql", "start")
            synchronize(test)
        setup_db()

    def teardown(self, test, node):
        with c.su():
            cu.meh(cu.grepkill, "mysqld")
            for f in LOG_FILES:
                cu.meh(c.exec_, "truncate", "-c", "--size", "0", f)
            # The stock copy only exists after a prior setup — and the
            # harness cycles teardown FIRST (db.cycle), so a fresh node
            # must pass through here unharmed.
            if cu.exists(STOCK_DIR):
                c.exec_("rm", "-rf", DIR)
                c.exec_("cp", "-rp", STOCK_DIR, DIR)

    def log_files(self, test, node):
        return LOG_FILES


def galera_test(**opts) -> dict:
    """The bank workload (galera.clj:240-339) in local mode against
    casd's bank endpoints."""
    from .cockroachdb import bank_service_test
    return bank_service_test("galera", **opts)
