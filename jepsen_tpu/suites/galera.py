"""Galera suite: bank serializability on MariaDB Galera.

Mirrors the reference suite (galera/src/jepsen/galera.clj): install from
the mariadb apt repo with debconf-preseeded root password and a
squirreled copy of the stock data dir (34-57), write the wsrep cluster
address into jepsen.cnf (64-73), bootstrap the primary with
``service mysql start --wsrep-new-cluster`` while the rest plain-start
and join (102-122), create the jepsen database + grant (96-100), and
teardown by killing mysqld, truncating logs, and restoring the stock
data dir (123-131). The workload (galera.clj:240-339) is the bank
family — shared with the cockroach module here — run against casd's
bank endpoints in local mode.
"""
from __future__ import annotations

from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..runtime import primary, synchronize
from .local_common import ServiceClient

REPO_LINE = ("deb http://sfo1.mirrors.digitalocean.com/mariadb/repo/10.0/"
             "debian jessie main")
KEYSERVER = "keyserver.ubuntu.com"
KEY = "0xcbcb082a1bb943db"
LOG_FILES = ["/var/log/syslog", "/var/log/mysql.log", "/var/log/mysql.err"]
DIR = "/var/lib/mysql"
STOCK_DIR = "/var/lib/mysql-stock"

DEBCONF = [
    "mariadb-galera-server-10.0 mysql-server/root_password password jepsen",
    "mariadb-galera-server-10.0 mysql-server/root_password_again password "
    "jepsen",
    "mariadb-galera-server-10.0 mysql-server-5.1/start_on_boot boolean "
    "false",
]


def cluster_address(test: dict) -> str:
    """gcomm:// over every node (galera.clj:59-62)."""
    return "gcomm://" + ",".join(str(n) for n in test.get("nodes") or [])


def jepsen_cnf(test: dict) -> str:
    """The reference's resources/jepsen.cnf with %CLUSTER_ADDRESS%
    substituted (galera.clj:64-73)."""
    return "\n".join([
        "[mysqld]",
        "wsrep_provider=/usr/lib/galera/libgalera_smm.so",
        f"wsrep_cluster_address={cluster_address(test)}",
        "wsrep_cluster_name=jepsen",
        "binlog_format=ROW",
        "default_storage_engine=InnoDB",
        "innodb_autoinc_lock_mode=2",
    ])


def sql(statement: str) -> str:
    """Eval a SQL string via the CLI (galera.clj:81-84)."""
    return c.exec_("mysql", "-u", "root", "--password=jepsen", "-e",
                   statement)


def setup_db() -> None:
    """Create the jepsen database + grant (galera.clj:96-100)."""
    sql("create database if not exists jepsen;")
    sql("GRANT ALL PRIVILEGES ON jepsen.* "
        "TO 'jepsen'@'%' IDENTIFIED BY 'jepsen';")


class GaleraDB(DB):
    """MariaDB Galera cluster (galera.clj:34-131)."""

    def setup(self, test, node):
        with c.su():
            debian.add_repo("galera", REPO_LINE, KEYSERVER, KEY)
            for line in DEBCONF:
                c.exec_star(f"echo {c.escape(line)} | "
                            f"debconf-set-selections")
            debian.install(["rsync"])
            if "mariadb-galera-server" not in debian.installed(
                    ["mariadb-galera-server"]):
                debian.install(["mariadb-galera-server"])
                c.exec_("service", "mysql", "stop")
                # Squirrel away a stock copy so teardown can restore a
                # pristine data dir (galera.clj:55-57).
                c.exec_("rm", "-rf", STOCK_DIR)
                c.exec_("cp", "-rp", DIR, STOCK_DIR)
            c.exec_("echo", jepsen_cnf(test), lit(">"),
                    "/etc/mysql/conf.d/jepsen.cnf")
            if node == primary(test):
                c.exec_("service", "mysql", "start",
                        "--wsrep-new-cluster")
            synchronize(test)
            if node != primary(test):
                c.exec_("service", "mysql", "start")
            synchronize(test)
        setup_db()

    def teardown(self, test, node):
        with c.su():
            cu.meh(cu.grepkill, "mysqld")
            for f in LOG_FILES:
                cu.meh(c.exec_, "truncate", "-c", "--size", "0", f)
            # The stock copy only exists after a prior setup — and the
            # harness cycles teardown FIRST (db.cycle), so a fresh node
            # must pass through here unharmed.
            if cu.exists(STOCK_DIR):
                c.exec_("rm", "-rf", DIR)
                c.exec_("cp", "-rp", STOCK_DIR, DIR)

    def log_files(self, test, node):
        return LOG_FILES


# ------------------------------------------------------- dirty reads
# galera/src/jepsen/galera/dirty_reads.clj (shared by the percona
# suite): writers compete to set EVERY row of a table to one unique
# value inside a transaction; readers read all rows. The checker hunts
# two anomalies: a FAILED transaction's value visible to any reader
# (dirty read), and reads whose rows disagree (inconsistent read —
# reported, not validity-bearing, matching the reference).


class DirtyReadsClient(ServiceClient):
    """write x to all rows / read all rows over /dirty/<name>
    (dirty_reads.clj:29-67). ``abort`` ops request a server-side
    rollback — the definite :fail whose value must never be seen."""

    def __init__(self, timeout: float = 0.5, rows: int = 4):
        super().__init__(timeout)
        self.rows = rows

    def setup(self, test, node):
        cl = super().setup(test, node)
        cl.rows = self.rows
        cl._req("POST", "/dirty/jepsen", {"op": "init", "rows": cl.rows})
        return cl

    def invoke(self, test, op):
        import urllib.error
        f = op["f"]

        def body():
            if f == "read":
                r = self._req("GET", "/dirty/jepsen")
                return {**op, "type": "ok",
                        "value": [int(x) for x in r["xs"]]}
            if f == "write":
                form = {"op": "write", "x": op["value"]}
                if op.get("abort"):
                    form["abort"] = "1"
                try:
                    self._req("POST", "/dirty/jepsen", form)
                    return {**op, "type": "ok"}
                except urllib.error.HTTPError as e:
                    if e.code == 409:
                        return {**op, "type": "fail", "error": "aborted"}
                    raise
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "write")


class DirtyReadsChecker:
    """A failed transaction's value visible to any reader is a dirty
    read (dirty_reads.clj:72-95); reads whose rows disagree are
    reported as inconsistent."""

    def check(self, test, model, history, opts=None) -> dict:
        failed = {op.value for op in history
                  if op.type == "fail" and op.f == "write"}
        reads = [op.value for op in history
                 if op.type == "ok" and op.f == "read"
                 and isinstance(op.value, list)]
        inconsistent = [r for r in reads if len(set(r)) > 1]
        filthy = [r for r in reads if any(x in failed for x in r)]
        return {"valid": not filthy,
                "reads": len(reads),
                "inconsistent-reads": inconsistent[:10],
                "inconsistent-count": len(inconsistent),
                "dirty-reads": filthy[:10],
                "dirty-count": len(filthy)}


# The drain phase's aborted write uses a value no _dirty_gen counter
# reaches, so the checker attributes it unambiguously.
DRAIN_WRITE_VALUE = 999_999_999


def _dirty_gen(abort_every: int):
    """Reads vs unique-value writes; every ``abort_every``-th write
    requests a rollback (the reference's aborts come from deadlock
    retries; here they're explicit so the seeded run aborts reliably)."""
    import itertools
    import threading
    counter = itertools.count(1)
    lock = threading.Lock()

    def gen(test, process, ctx):
        if ctx.rng.random() < 0.5:
            return {"type": "invoke", "f": "read", "value": None}
        with lock:
            x = next(counter)
        op = {"type": "invoke", "f": "write", "value": x}
        if abort_every and x % abort_every == 0:
            op["abort"] = True
        return op

    return gen


def dirty_reads_workload(opts: dict) -> dict:
    from .. import gen as g
    n_ops = opts.get("n_ops", 200)
    main = g.limit(n_ops, g.stagger(
        1 / 100, _dirty_gen(opts.get("abort_every", 4))))
    # Drain phases: after the main mix, ONE aborted write followed —
    # behind a barrier, so it has completed — by ONE final read. In
    # atomic mode the abort leaves nothing (healthy runs stay valid);
    # under --dirty-split-ms its half-applied rows are still in the
    # table when the read lands, so the seeded violation is observed
    # deterministically instead of depending on a reader racing the
    # split window under scheduler load.
    drain_write = g.once({"type": "invoke", "f": "write",
                          "value": DRAIN_WRITE_VALUE, "abort": True})
    drain_read = g.once({"type": "invoke", "f": "read", "value": None})
    return {
        "generator": g.phases(main, drain_write, drain_read),
        "checker": DirtyReadsChecker(),
        "model": None,
    }


def dirty_reads_test(split_ms: int = 0, name: str = "galera-dirty",
                     **opts) -> dict:
    """The dirty-reads test; ``split_ms > 0`` seeds the row-at-a-time
    isolation bug (failed transactions leave visible rows)."""
    from .local_common import service_test
    daemon_args = (["--dirty-split-ms", str(split_ms)] if split_ms
                   else [])
    return service_test(
        name,
        DirtyReadsClient(opts.get("client_timeout", 0.5),
                         opts.get("rows", 4)),
        dirty_reads_workload(opts), daemon_args=daemon_args, **opts)


def galera_test(workload: str = "bank", split_ms: int = 0,
                **opts) -> dict:
    """Workload dispatch (the reference splits these across
    galera.clj:240-339 and galera/dirty_reads.clj). ``split_ms`` seeds
    the matching fault either way: the split-transfer race for bank,
    the row-at-a-time visibility bug for dirty."""
    if workload == "dirty":
        return dirty_reads_test(split_ms=split_ms, **opts)
    from .cockroachdb import bank_service_test
    return bank_service_test("galera", split_ms=split_ms, **opts)
