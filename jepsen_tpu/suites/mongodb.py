"""MongoDB suites: document CAS over a replica set.

Mirrors both reference variants:

  * mongodb-smartos (mongodb-smartos/src/jepsen/mongodb_smartos/core.clj)
    — pkgin install (40-47), mongod.conf deploy (49-53), svcadm
    start/stop (55-70), data wipe (72-79), and replica-set join: the
    primary initiates the set and awaits election, others just await
    the config (262-300). ``MongoSmartOSDB``.
  * mongodb-rocks (mongodb-rocks/src/jepsen/mongodb_rocks.clj) — .deb
    download + dpkg install with a pluggable storage engine (29-46).
    ``MongoRocksDB``.

The reference drives replica-set admin through the Java driver
(replica-set-initiate!, core.clj:128-146); here the same commands ride
the node-side ``mongo --quiet --eval`` shell (the reference's own
mongo! helper, core.clj:87-91), keeping the whole bootstrap on the
command stream. The workload (document_cas.clj) is the CAS-register
family, run against casd in local mode.
"""
from __future__ import annotations

import json

from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import smartos
from ..runtime import primary, synchronize
from .etcd import EtcdClient, workload as register_workload
from .local_common import service_test

REPLICA_SET = "jepsen"
SMARTOS_CONF = "/opt/local/etc/mongod.conf"
DEBIAN_CONF = "/etc/mongod.conf"
LOG_FILE = "/var/log/mongodb/mongod.log"
DATA_DIR = "/var/lib/mongodb"


def mongod_conf(engine: str | None = None) -> str:
    """The reference's resources/mongod.conf baseline: replica set name,
    bind everywhere, journaled storage (+ optional engine override for
    the rocks variant)."""
    lines = [
        "systemLog:",
        "  destination: file",
        f"  path: {LOG_FILE}",
        "storage:",
        f"  dbPath: {DATA_DIR}",
        "  journal:",
        "    enabled: true",
    ]
    if engine:
        lines.append(f"  engine: {engine}")
    lines += [
        "replication:",
        f"  replSetName: {REPLICA_SET}",
        "net:",
        "  bindIp: 0.0.0.0",
    ]
    return "\n".join(lines)


def mongo_eval(cmd: str) -> str:
    """Run a mongo-shell command on the node, JSON out (the reference's
    mongo! helper, core.clj:87-91)."""
    return c.exec_("mongo", "--quiet", "--eval", f"printjson({cmd})")


def replica_set_config(test: dict) -> dict:
    """Target replica-set config: one member per node, ids by position
    (core.clj:240-247)."""
    return {"_id": REPLICA_SET,
            "members": [{"_id": i, "host": f"{n}:27017"}
                        for i, n in enumerate(test.get("nodes") or [])]}


def join_replica_set(test: dict, node) -> None:
    """The primary initiates the set with the full member config and
    polls until an election yields a primary (core.clj:262-300);
    non-primaries have nothing to do — they learn the config over the
    wire."""
    if node != primary(test):
        synchronize(test)
        return
    cfg = json.dumps(replica_set_config(test))
    mongo_eval(f"rs.initiate({cfg})")
    # await-primary (core.clj:228-232): poll ismaster until someone wins.
    cu.await_cmd(
        "mongo --quiet --eval 'rs.isMaster().ismaster' | grep -q true",
        "mongodb-primary-election")
    synchronize(test)


class MongoSmartOSDB(DB):
    """pkgin-installed mongod under SMF (core.clj:40-79 + 262-300)."""

    def __init__(self, db_version: str = "3.2.0",
                 tools_version: str = "3.2.0"):
        self.db_version = db_version
        self.tools_version = tools_version

    def setup(self, test, node):
        with c.su():
            smartos.install({"mongodb": self.db_version,
                             "mongo-tools": self.tools_version})
            c.exec_("mkdir", "-p", DATA_DIR)
            c.exec_("chown", "-R", "mongodb:mongodb", DATA_DIR)
            c.exec_("echo", mongod_conf(), lit(">"), SMARTOS_CONF)
            cu.meh(c.exec_, "svcadm", "clear", "mongodb")
            c.exec_("svcadm", "enable", "-r", "mongodb")
        join_replica_set(test, node)

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "svcadm", "disable", "mongodb")
            cu.meh(c.exec_, "pkill", "-9", "mongod")
            c.exec_("rm", "-rf", lit("/var/log/mongodb/*"))
            c.exec_("rm", "-rf", lit(f"{DATA_DIR}/*"))

    def log_files(self, test, node):
        return [LOG_FILE]


class MongoRocksDB(DB):
    """.deb-installed mongod with a pluggable storage engine
    (mongodb_rocks.clj:29-58)."""

    def __init__(self, url: str, engine: str = "rocksdb"):
        self.url = url
        self.engine = engine

    def setup(self, test, node):
        with c.su():
            with c.cd(cu.tmp_dir()):
                f = cu.wget(self.url)
                c.exec_("dpkg", "-i", "--force-confask",
                        "--force-confnew", f)
            c.exec_("mkdir", "-p", DATA_DIR)
            c.exec_("echo", mongod_conf(self.engine), lit(">"),
                    DEBIAN_CONF)
            c.exec_("service", "mongod", "restart")
        join_replica_set(test, node)

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "service", "mongod", "stop")
            cu.meh(c.exec_, "pkill", "-9", "mongod")
            c.exec_("rm", "-rf", lit("/var/log/mongodb/*"))
            c.exec_("rm", "-rf", lit(f"{DATA_DIR}/*"))

    def log_files(self, test, node):
        return [LOG_FILE]


def mongodb_test(workload: str = "register", split_ms: int = 0,
                 **opts) -> dict:
    """Workload dispatch: register (document_cas.clj — per-key document
    CAS) or transfer (transfer.clj — the bank family as document
    transactions), in local mode against casd. ``split_ms`` seeds the
    split-transfer race for the transfer workload."""
    if workload == "transfer":
        from .cockroachdb import bank_service_test
        return bank_service_test("mongodb-transfer", split_ms=split_ms,
                                 **opts)
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "mongodb",
        EtcdClient(opts.get("client_timeout", 0.5)),
        register_workload(opts), **opts)
