"""Percona XtraDB Cluster suite: bank serializability.

Mirrors the reference suite (percona/src/jepsen/percona.clj): percona
apt repo + pin-preferences + debconf-preseeded root passwords, pinned
install with a squirreled stock data dir (34-71), wsrep jepsen.cnf —
the primary bootstraps with an EMPTY ``gcomm://`` while joiners list
every node (73-89), ``service mysql start bootstrap-pxc`` on the
primary vs plain start (118-138), jepsen db + grant (113-117), and the
same truncate-logs + restore-stock-dir teardown as galera (139-147).
Workload: the bank family against casd in local mode.
"""
from __future__ import annotations

from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..runtime import primary, synchronize
from .galera import DIR, STOCK_DIR, setup_db

REPO_LINE = "deb http://repo.percona.com/apt jessie main"
KEYSERVER = "keys.gnupg.net"
KEY = "1C4CBDCDCD2EFD2A"
PACKAGE = "percona-xtradb-cluster-56"
LOG_FILES = ["/var/log/syslog", "/var/log/mysql.log", "/var/log/mysql.err",
             "/var/lib/mysql/queries.log"]

# Pin percona's repo above the distro's (resources/apt-prefs).
APT_PREFS = "\n".join(["Package: *",
                       "Pin: release o=Percona Development Team",
                       "Pin-Priority: 1001"])

DEBCONF = [
    f"{PACKAGE} mysql-server/root_password password jepsen",
    f"{PACKAGE} mysql-server/root_password_again password jepsen",
    f"{PACKAGE} mysql-server-5.1/start_on_boot boolean false",
    "percona-xtradb-cluster-server-5.6 "
    "percona-xtradb-cluster-server/root_password_again password jepsen",
    "percona-xtradb-cluster-server-5.6 "
    "percona-xtradb-cluster-server/root_password password jepsen",
]


def cluster_address(test: dict, node) -> str:
    """The primary bootstraps a NEW cluster (empty gcomm), joiners list
    everyone (percona.clj:73-79)."""
    if node == primary(test):
        return "gcomm://"
    return "gcomm://" + ",".join(str(n) for n in test.get("nodes") or [])


def jepsen_cnf(test: dict, node) -> str:
    """resources/jepsen.cnf with %CLUSTER_ADDRESS% substituted
    (percona.clj:80-89)."""
    return "\n".join([
        "[mysqld]",
        "wsrep_provider=/usr/lib/libgalera_smm.so",
        f"wsrep_cluster_address={cluster_address(test, node)}",
        "wsrep_cluster_name=jepsen",
        "wsrep_sst_method=rsync",
        "binlog_format=ROW",
        "default_storage_engine=InnoDB",
        "innodb_autoinc_lock_mode=2",
    ])


class PerconaDB(DB):
    """Percona XtraDB cluster (percona.clj:34-147)."""

    def __init__(self, version: str = "5.6.25-25.12-1.jessie"):
        self.version = version

    def setup(self, test, node):
        with c.su():
            debian.add_repo("percona", REPO_LINE, KEYSERVER, KEY)
            c.exec_("echo", APT_PREFS, lit(">"),
                    "/etc/apt/preferences.d/00percona.pref")
            debian.install(["rsync"])
            if debian.installed_version(PACKAGE) != self.version:
                for line in DEBCONF:
                    c.exec_star(f"echo {c.escape(line)} | "
                                f"debconf-set-selections")
                # Keep our config away from the package's first start
                # and start from a clean data dir (percona.clj:60-65).
                c.exec_("rm", "-rf", "/etc/mysql/conf.d/jepsen.cnf")
                c.exec_("rm", "-rf", DIR)
                debian.install([f"{PACKAGE}={self.version}"])
                c.exec_("service", "mysql", "stop")
                c.exec_("rm", "-rf", STOCK_DIR)
                c.exec_("cp", "-rp", DIR, STOCK_DIR)
            c.exec_("echo", jepsen_cnf(test, node), lit(">"),
                    "/etc/mysql/conf.d/jepsen.cnf")
            if node == primary(test):
                c.exec_("service", "mysql", "start", "bootstrap-pxc")
            synchronize(test)
            if node != primary(test):
                c.exec_("service", "mysql", "start")
            synchronize(test)
        setup_db()

    def teardown(self, test, node):
        with c.su():
            cu.meh(cu.grepkill, "mysqld")
            for f in LOG_FILES:
                cu.meh(c.exec_, "truncate", "-c", "--size", "0", f)
            # Stock copy exists only after a prior setup; teardown runs
            # first on a fresh node (db.cycle).
            if cu.exists(STOCK_DIR):
                c.exec_("rm", "-rf", DIR)
                c.exec_("cp", "-rp", STOCK_DIR, DIR)

    def log_files(self, test, node):
        return LOG_FILES


def percona_test(workload: str = "bank", split_ms: int = 0,
                 **opts) -> dict:
    """Workload dispatch (percona.clj:233-331 bank;
    percona/dirty_reads.clj — the dirty-reads family shared with
    galera)."""
    if workload == "dirty":
        from .galera import dirty_reads_test
        return dirty_reads_test(split_ms=split_ms, name="percona-dirty",
                                **opts)
    from .cockroachdb import bank_service_test
    return bank_service_test("percona", split_ms=split_ms, **opts)
