"""LogCabin suite: CAS register on the LogCabin Raft store.

Mirrors the reference suite (logcabin/src/jepsen/logcabin.clj): install
by building from source on the node (git clone + scons, 23-46), write
the per-node config (serverId + listenAddresses, 66-76), bootstrap the
initial cluster on the primary (78-84), start every daemon (86-93),
reconfigure the full member set from the primary (103-115), and tear
down with grepkill + storage wipe (95-101, db at 120-150). The workload
is the CAS-register family (TreeOps write/read/cas there), run against
casd in local mode.
"""
from __future__ import annotations

from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..runtime import primary, synchronize
from .etcd import EtcdClient, workload as register_workload
from .local_common import service_test

GIT_URL = "https://github.com/logcabin/logcabin.git"
BUILD_DEPS = ["git-core", "protobuf-compiler", "libprotobuf-dev",
              "libcrypto++-dev", "g++", "scons"]
CONFIG_FILE = "/root/logcabin.conf"
LOG_FILE = "/root/logcabin.log"
PID_FILE = "/root/logcabin.pid"
STORE_DIR = "/root/storage"
BINARY = "/root/LogCabin"
RECONFIGURE = "/root/Reconfigure"
TREEOPS = "/root/TreeOps"
PORT = 5254


def server_id(test: dict, node) -> str:
    """1-based position in the node list (the reference derives ids
    from node names, logcabin.clj:48-50; positions are unique and
    numeric for ANY hostnames)."""
    return str(1 + list(test.get("nodes") or [node]).index(node))


def server_addr(node) -> str:
    return f"{node}:{PORT}"


class LogCabinDB(DB):
    """Source-built LogCabin cluster (logcabin.clj:23-150): clone +
    scons on each node, per-node config, primary bootstraps the initial
    single-server cluster, then reconfigures to the full member set once
    every daemon is up."""

    def setup(self, test, node):
        with c.su():
            debian.install(BUILD_DEPS)
            with c.cd("/"):
                if not cu.exists("logcabin"):
                    c.exec_("git", "clone", "--depth", "1", GIT_URL)
                    with c.cd("/logcabin"):
                        c.exec_("git", "submodule", "update", "--init")
            with c.cd("/logcabin"):
                c.exec_("scons")
            for built, dest in (("build/LogCabin", BINARY),
                                ("build/Examples/Reconfigure", RECONFIGURE),
                                ("build/Examples/TreeOps", TREEOPS)):
                c.exec_("cp", "-f", f"/logcabin/{built}", dest)
            c.exec_("echo",
                    f"serverId = {server_id(test, node)}\n"
                    f"listenAddresses = {server_addr(node)}",
                    lit(">"), CONFIG_FILE)
            if node == primary(test):
                # Bootstrap seeds the Raft log with a one-server
                # configuration (logcabin.clj:78-84); only the primary
                # does it, exactly once.
                with c.cd("/root"):
                    c.exec_(BINARY, "-c", CONFIG_FILE, "-l", LOG_FILE,
                            "--bootstrap")
            synchronize(test)
            with c.cd("/root"):
                c.exec_(BINARY, "-c", CONFIG_FILE, "-d", "-l", LOG_FILE,
                        "-p", PID_FILE)
            synchronize(test)
            if node == primary(test):
                # Grow the cluster to the full member set
                # (logcabin.clj:103-115).
                addrs = ",".join(server_addr(n) for n in test["nodes"])
                with c.cd("/root"):
                    c.exec_(RECONFIGURE, "-c", lit(addrs), "set",
                            *[lit(server_addr(n)) for n in test["nodes"]])

    def teardown(self, test, node):
        with c.su():
            cu.grepkill("LogCabin")
            c.exec_("rm", "-rf", PID_FILE, STORE_DIR, LOG_FILE)

    def log_files(self, test, node):
        return [LOG_FILE]


def logcabin_test(**opts) -> dict:
    """The register workload (logcabin.clj TreeOps client) in local
    mode against casd."""
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "logcabin",
        EtcdClient(opts.get("client_timeout", 0.5)),
        register_workload(opts), **opts)
