"""Crate suite: register + set semantics on the Crate SQL cluster.

Mirrors the reference suite (crate/src/jepsen/crate.clj): apt-repo
install with the crate signing key + pinned version and boot-disable
(167-180), crate.yml templating — node name, expected node count,
majority for minimum_master_nodes, unicast host list (187-202) —
``service crate start`` (205-209), and grepkill + log/data wipe
teardown (db at 211-229). Its workloads are version-read registers
(crate.clj:232-320) and the lost-updates/dirty-read set family
(lost_updates.clj, dirty_read.clj) — the register family here runs
against casd in local mode.
"""
from __future__ import annotations

import json

from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..utils.core import majority
from .etcd import EtcdClient, workload as register_workload
from .local_common import service_test

KEY_URL = "https://cdn.crate.io/downloads/apt/DEB-GPG-KEY-crate"
REPO_LINE = "deb https://cdn.crate.io/downloads/apt/stable/ jessie main"
CONF = "/etc/crate/crate.yml"
LOG_FILE = "/var/log/crate/crate.log"


def crate_yml(node, test: dict) -> str:
    """The reference's resources/crate.yml with $NAME/$N/$MAJORITY/
    $HOSTS substituted (crate.clj:187-202)."""
    nodes = test.get("nodes") or []
    hosts = json.dumps([net_helpers.ip(str(n)) for n in nodes])
    return "\n".join([
        "cluster.name: jepsen",
        f"node.name: {node}",
        f"gateway.expected_nodes: {len(nodes)}",
        f"gateway.recover_after_nodes: {majority(len(nodes))}",
        f"discovery.zen.minimum_master_nodes: {majority(len(nodes))}",
        "discovery.zen.ping.multicast.enabled: false",
        f"discovery.zen.ping.unicast.hosts: {hosts}",
        "network.host: 0.0.0.0",
    ])


class CrateDB(DB):
    """Apt-repo Crate cluster (crate.clj:167-229)."""

    def __init__(self, version: str = "0.55.2-1~jessie"):
        self.version = version

    def setup(self, test, node):
        with c.su():
            debian.install(["apt-transport-https"])
            debian.install_jdk()
            with c.cd("/tmp"):
                c.exec_("wget", KEY_URL)
                c.exec_("apt-key", "add", "DEB-GPG-KEY-crate")
                c.exec_("rm", "DEB-GPG-KEY-crate")
            debian.add_repo("crate", REPO_LINE)
            debian.install([f"crate={self.version}"])
            c.exec_("update-rc.d", "crate", "disable")
            c.exec_("echo", crate_yml(node, test), lit(">"), CONF)
            c.exec_("service", "crate", "start")

    def teardown(self, test, node):
        with c.su():
            cu.grepkill("crate")
            c.exec_("rm", "-rf", lit("/var/log/crate/*"))
            c.exec_("rm", "-rf", lit("/var/lib/crate/*"))

    def log_files(self, test, node):
        return [LOG_FILE]


def crate_test(**opts) -> dict:
    """The version-read register workload (crate.clj:232-320) in local
    mode against casd."""
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "crate",
        EtcdClient(opts.get("client_timeout", 0.5)),
        register_workload(opts), **opts)
