"""Crate suite: register + set semantics on the Crate SQL cluster.

Mirrors the reference suite (crate/src/jepsen/crate.clj): apt-repo
install with the crate signing key + pinned version and boot-disable
(167-180), crate.yml templating — node name, expected node count,
majority for minimum_master_nodes, unicast host list (187-202) —
``service crate start`` (205-209), and grepkill + log/data wipe
teardown (db at 211-229). Its workloads are version-read registers
(crate.clj:232-320) and the lost-updates/dirty-read set family
(lost_updates.clj, dirty_read.clj) — the register family here runs
against casd in local mode.
"""
from __future__ import annotations

import json

from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..utils.core import majority
from .etcd import EtcdClient, workload as register_workload
from .local_common import ServiceClient, service_test

KEY_URL = "https://cdn.crate.io/downloads/apt/DEB-GPG-KEY-crate"
REPO_LINE = "deb https://cdn.crate.io/downloads/apt/stable/ jessie main"
CONF = "/etc/crate/crate.yml"
LOG_FILE = "/var/log/crate/crate.log"


def crate_yml(node, test: dict) -> str:
    """The reference's resources/crate.yml with $NAME/$N/$MAJORITY/
    $HOSTS substituted (crate.clj:187-202)."""
    nodes = test.get("nodes") or []
    hosts = json.dumps([net_helpers.ip(str(n)) for n in nodes])
    return "\n".join([
        "cluster.name: jepsen",
        f"node.name: {node}",
        f"gateway.expected_nodes: {len(nodes)}",
        f"gateway.recover_after_nodes: {majority(len(nodes))}",
        f"discovery.zen.minimum_master_nodes: {majority(len(nodes))}",
        "discovery.zen.ping.multicast.enabled: false",
        f"discovery.zen.ping.unicast.hosts: {hosts}",
        "network.host: 0.0.0.0",
    ])


class CrateDB(DB):
    """Apt-repo Crate cluster (crate.clj:167-229)."""

    def __init__(self, version: str = "0.55.2-1~jessie"):
        self.version = version

    def setup(self, test, node):
        with c.su():
            debian.install(["apt-transport-https"])
            debian.install_jdk()
            with c.cd("/tmp"):
                c.exec_("wget", KEY_URL)
                c.exec_("apt-key", "add", "DEB-GPG-KEY-crate")
                c.exec_("rm", "DEB-GPG-KEY-crate")
            debian.add_repo("crate", REPO_LINE)
            debian.install([f"crate={self.version}"])
            c.exec_("update-rc.d", "crate", "disable")
            c.exec_("echo", crate_yml(node, test), lit(">"), CONF)
            c.exec_("service", "crate", "start")

    def teardown(self, test, node):
        with c.su():
            cu.grepkill("crate")
            c.exec_("rm", "-rf", lit("/var/log/crate/*"))
            c.exec_("rm", "-rf", lit("/var/lib/crate/*"))

    def log_files(self, test, node):
        return [LOG_FILE]


# ---------------------------------------------------------- lost updates
# crate/src/jepsen/crate/lost_updates.clj: per-key sets grown by
# version-CAS'd read-modify-write adds, checked by the set checker
# lifted over independent keys (independent/checker checker/set,
# lost_updates.clj:110-112). A lost update = an acked add missing from
# the key's final read.


class PerKeySetClient(ServiceClient):
    """add v / read over /set/jepsen-<k> — the per-key set the
    reference grows via _version-guarded updates
    (lost_updates.clj:36-89)."""

    def invoke(self, test, op):
        from .. import independent
        k, v = op["value"]
        f = op["f"]

        def body():
            if f == "add":
                self._req("POST", f"/set/jepsen-{k}",
                          {"op": "add", "v": v})
                return {**op, "type": "ok"}
            if f == "read":
                r = self._req("GET", f"/set/jepsen-{k}")
                return {**op, "type": "ok",
                        "value": independent.tuple_(
                            k, [int(x) for x in r["vs"]])}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "add")


def lost_updates_workload(opts: dict) -> dict:
    import itertools
    import threading

    from .. import gen as g
    from .. import independent
    from ..checkers.simple import set_checker

    per_key = opts.get("ops_per_key", 40)
    threads = opts.get("threads_per_key", 2)
    # Finite key space: a time-limit cutoff mid-key leaves that key's
    # set unread (valid: unknown); bounding the keys lets healthy runs
    # terminate cleanly instead of always truncating the last key.
    n_keys = opts.get("keys", 6)

    def key_gen(k):
        counter = itertools.count()
        lock = threading.Lock()

        def add(test, process, ctx):
            with lock:
                return {"type": "invoke", "f": "add",
                        "value": next(counter)}

        # A quiescent gap before the final read lets straggling adds
        # complete — an add acked after the last read would read as
        # "lost" (the checker keys on the final read,
        # checker.clj:131-178; the reference gets the same effect from
        # its 20s quiescence window, lost_updates.clj:101-104).
        return g.concat(g.limit(per_key, g.stagger(1 / 100, add)),
                        g.sleep(0.7),
                        g.once({"type": "invoke", "f": "read",
                                "value": None}))

    return {
        "generator": independent.concurrent_generator(
            threads, iter(range(n_keys)), key_gen),
        "checker": independent.checker(set_checker()),
        "model": None,
    }


def lost_updates_test(**opts) -> dict:
    # service_test derives/validates concurrency from threads_per_key.
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "crate-lost-updates",
        PerKeySetClient(opts.get("client_timeout", 0.5)),
        lost_updates_workload(opts), **opts)


def crate_test(workload: str = "register", **opts) -> dict:
    """Workload dispatch (register — crate.clj:232-320; lost-updates —
    crate/lost_updates.clj; dirty — crate/dirty_read.clj, the
    strong-read family shared with elasticsearch)."""
    if workload == "lost-updates":
        return lost_updates_test(**opts)
    if workload == "dirty":
        from .elasticsearch import dirty_read_test
        return dirty_read_test(name="crate-dirty", **opts)
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "crate",
        EtcdClient(opts.get("client_timeout", 0.5)),
        register_workload(opts), **opts)
