"""MySQL Cluster (NDB) suite: bank serializability.

Mirrors the reference suite
(mysql-cluster/src/jepsen/mysql_cluster.clj): the one .deb install with
version guard (32-51), three node roles with disjoint NodeId ranges —
management (mgmd, ids 1+), storage (ndbd, ids 11+, first four nodes),
sql (mysqld, ids 21+) (53-75) — a shared config.ini listing every
role on every node plus a per-node my.cnf with the ndb connect string
(77-146), staged startup mgmd -> ndbd -> mysqld with barriers between
stages (187-203), and grepkill + data-wipe teardown (169-185, 205-214).
Workload: the bank family against casd in local mode.
"""
from __future__ import annotations

from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..runtime import synchronize

USER = "mysql"
MGMD_DIR = "/var/lib/mysql/cluster"
NDBD_DIR = "/var/lib/mysql/data"
MYSQLD_DIR = "/var/lib/mysql/mysql"
BIN = "/opt/mysql/server-5.6/bin"
MGMD_ID_OFFSET = 1
NDBD_ID_OFFSET = 11
MYSQLD_ID_OFFSET = 21


def _idx(test: dict, node) -> int:
    return list(test.get("nodes") or []).index(node)


def mgmd_id(test, node) -> int:
    return MGMD_ID_OFFSET + _idx(test, node)


def ndbd_id(test, node) -> int:
    return NDBD_ID_OFFSET + _idx(test, node)


def mysqld_id(test, node) -> int:
    return MYSQLD_ID_OFFSET + _idx(test, node)


def ndbd_nodes(test: dict) -> list:
    """Storage role runs on the first four nodes, sorted
    (mysql_cluster.clj:97-101)."""
    return sorted(test.get("nodes") or [])[:4]


def nodes_conf(test: dict) -> str:
    """Role sections for every node (mysql_cluster.clj:103-114)."""
    parts = []
    for n in test["nodes"]:
        parts.append(f"[ndb_mgmd]\nNodeId={mgmd_id(test, n)}\n"
                     f"hostname={n}\ndatadir={MGMD_DIR}\n")
    for n in ndbd_nodes(test):
        parts.append(f"[ndbd]\nNodeId={ndbd_id(test, n)}\n"
                     f"hostname={n}\ndatadir={NDBD_DIR}\n")
    for n in test["nodes"]:
        parts.append(f"[mysqld]\nNodeId={mysqld_id(test, n)}\n"
                     f"hostname={n}\n")
    return "\n".join(parts)


def connect_string(test: dict) -> str:
    return ",".join(str(n) for n in test.get("nodes") or [])


def my_cnf(test: dict, node) -> str:
    """resources/my.cnf with %NODE_ID%/%DATA_DIR%/%NDB_CONNECT_STRING%
    substituted (mysql_cluster.clj:120-131)."""
    return "\n".join([
        "[mysqld]",
        f"ndb-nodeid={mysqld_id(test, node)}",
        "ndbcluster",
        f"datadir={MYSQLD_DIR}",
        f"ndb-connectstring={connect_string(test)}",
        "[mysql_cluster]",
        f"ndb-connectstring={connect_string(test)}",
    ])


class MySQLClusterDB(DB):
    """MySQL Cluster with staged mgmd/ndbd/mysqld startup
    (mysql_cluster.clj:32-214)."""

    def __init__(self, version: str = "7.4.6"):
        self.version = version

    def setup(self, test, node):
        deb = f"mysql-cluster-gpl-{self.version}-debian7-x86_64.deb"
        with c.su():
            debian.install(["libaio1"])
            with c.cd("/tmp"):
                f = cu.wget("https://dev.mysql.com/get/Downloads/"
                            f"MySQL-Cluster-7.4/{deb}")
                pkg = c.exec_("dpkg-deb", "-f", f, "Package")
                if c.exec_("dpkg-deb", "-f", f, "Version") != \
                        debian.installed_version(pkg):
                    c.exec_("dpkg", "-i", "--force-confask",
                            "--force-confnew", f)
            cu.meh(c.exec_, "adduser", "--disabled-password",
                   "--gecos", lit("''"), USER)
            c.exec_("echo", my_cnf(test, node), lit(">"), "/etc/my.cnf")
            c.exec_("mkdir", "-p", MGMD_DIR)
            c.exec_("echo", nodes_conf(test), lit(">"),
                    "/etc/my.config.ini")
            # Staged bring-up with cluster-wide barriers
            # (mysql_cluster.clj:187-203).
            c.exec_(f"{BIN}/ndb_mgmd",
                    f"--ndb-nodeid={mgmd_id(test, node)}",
                    "-f", "/etc/my.config.ini")
            synchronize(test)
            if node in ndbd_nodes(test):
                c.exec_("mkdir", "-p", NDBD_DIR)
                c.exec_(f"{BIN}/ndbd",
                        f"--ndb-nodeid={ndbd_id(test, node)}")
            synchronize(test)
            c.exec_("mkdir", "-p", MYSQLD_DIR)
            c.exec_("chown", "-R", f"{USER}:{USER}", MYSQLD_DIR)
        with c.sudo(USER):
            # mysqld_safe supervises mysqld in the foreground and never
            # exits — it must be daemonized or setup hangs until the
            # transport timeout.
            cu.start_daemon(
                {"logfile": f"{MYSQLD_DIR}/mysqld_safe.log",
                 "pidfile": f"{MYSQLD_DIR}/mysqld_safe.pid",
                 "chdir": MYSQLD_DIR},
                f"{BIN}/mysqld_safe", "--defaults-file=/etc/my.cnf")

    def teardown(self, test, node):
        with c.su():
            cu.meh(cu.grepkill, "mysqld")
            cu.meh(cu.grepkill, "ndbd")
            cu.meh(cu.grepkill, "ndb_mgmd")
            c.exec_("rm", "-rf", lit(f"{MGMD_DIR}/*"),
                    lit(f"{NDBD_DIR}/*"), lit(f"{MYSQLD_DIR}/*"))

    def log_files(self, test, node):
        return [f"{MYSQLD_DIR}/error.log"]


def mysql_cluster_test(**opts) -> dict:
    """The bank workload in local mode against casd's bank endpoints."""
    from .cockroachdb import bank_service_test
    return bank_service_test("mysql-cluster", **opts)
