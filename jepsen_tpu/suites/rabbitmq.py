"""Rabbitmq-family suite: queue workload with a drain phase.

Mirrors the reference's queue test
(rabbitmq/src/jepsen/rabbitmq.clj:118-186 client,
rabbitmq/test/jepsen/rabbitmq_test.clj:46-77 wiring): an
enqueue/dequeue mix followed by a drain that empties the queue, checked
by BOTH the ordered-fold queue checker and total-queue multiset
accounting (checker.clj:109-129, 214-271).

Local mode drives casd's /queue endpoints; a state-wiping restart loses
enqueued elements, which total-queue reports as ``lost``. ``RabbitDB``
is the real-cluster automation (rabbitmq.clj:24-99: .deb install, the
shared erlang cookie, rabbitmqctl cluster join onto the primary, and
majority-mirroring policy), behind the DB protocol and command-stream
tested like EtcdDB.
"""
from __future__ import annotations

import urllib.error

from .. import gen as g
from ..checkers.core import compose
from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..ops.folds import queue_checker_tpu, total_queue_checker_tpu
from ..os_impl import debian
from ..runtime import primary, synchronize
from .local_common import ServiceClient, service_test

COOKIE = "jepsen-rabbitmq"
# The reference's resources/rabbitmq/rabbitmq.config: keep partitions
# visible to the test instead of auto-healing them.
RABBIT_CONFIG = ('[{rabbit, [{cluster_partition_handling, ignore}]}].')
HA_POLICY = ('{"ha-mode": "exactly", "ha-params": 3, '
             '"ha-sync-mode": "automatic"}')
MNESIA_DIR = "/var/lib/rabbitmq/mnesia/"
RABBIT_LOG = "/var/log/rabbitmq/rabbit.log"


class RabbitDB(DB):
    """.deb RabbitMQ cluster (rabbitmq.clj:24-99): install with
    erlang-nox, share one erlang cookie across nodes, join every
    non-primary via ``rabbitmqctl join_cluster rabbit@<primary>``, and
    enable majority mirroring; teardown nukes the beam VM and the
    mnesia dir."""

    def __init__(self, version: str = "3.5.6"):
        self.version = version

    def setup(self, test, node):
        deb = f"rabbitmq-server_{self.version}-1_all.deb"
        with c.cd("/tmp"):
            if not cu.exists(deb):
                c.exec_("wget",
                        "http://www.rabbitmq.com/releases/rabbitmq-server/"
                        f"v{self.version}/{deb}")
            with c.su():
                if "rabbitmq-server" not in debian.installed(
                        ["rabbitmq-server"]):
                    c.exec_("apt-get", "install", "-y", "erlang-nox")
                    c.exec_("dpkg", "-i", deb)
                if c.exec_("cat", "/var/lib/rabbitmq/.erlang.cookie") \
                        != COOKIE:
                    c.exec_("service", "rabbitmq-server", "stop")
                    c.exec_("echo", COOKIE, lit(">"),
                            "/var/lib/rabbitmq/.erlang.cookie")
                c.exec_("echo", RABBIT_CONFIG, lit(">"),
                        "/etc/rabbitmq/rabbitmq.config")
                c.exec_("service", "rabbitmq-server", "start")
                if node != primary(test):
                    c.exec_("rabbitmqctl", "stop_app")
                synchronize(test)
                if node != primary(test):
                    c.exec_("rabbitmqctl", "join_cluster",
                            f"rabbit@{primary(test)}")
                    c.exec_("rabbitmqctl", "start_app")
                synchronize(test)
                c.exec_("rabbitmqctl", "set_policy", "ha-maj", "jepsen.",
                        HA_POLICY)

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "killall", "-9", "beam.smp", "epmd")
            c.exec_("rm", "-rf", MNESIA_DIR)
            # No service on a fresh node (teardown runs first).
            cu.meh(c.exec_, "service", "rabbitmq-server", "stop")

    def log_files(self, test, node):
        return [RABBIT_LOG]


class QueueClient(ServiceClient):
    """enqueue / dequeue / drain over /queue/<name>. Dequeue of an
    empty queue is a definite :fail (the reference's empty-queue
    convention); drain returns the remaining elements as one op, which
    the total-queue checker expands into dequeue pairs.

    Unlike real RabbitMQ (which redelivers un-acked messages, letting
    the reference map dequeue timeouts to :fail, rabbitmq.clj:152-166),
    casd pops the element immediately with no ack — a timed-out dequeue
    the daemon still processed has removed an element, so every op here
    is mutating (timeout -> :info)."""

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "enqueue":
                self._req("POST", "/queue/jepsen",
                          {"op": "enq", "v": op["value"]})
                return {**op, "type": "ok"}
            if f == "dequeue":
                try:
                    r = self._req("POST", "/queue/jepsen", {"op": "deq"})
                    return {**op, "type": "ok", "value": int(r["v"])}
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        return {**op, "type": "fail", "error": "empty"}
                    raise
            if f == "drain":
                r = self._req("POST", "/queue/jepsen", {"op": "drain"})
                return {**op, "type": "ok",
                        "value": [int(v) for v in r["vs"]]}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=True)


def queue_workload(opts: dict) -> dict:
    """Main mix (queue_gen: consecutive-int enqueues vs dequeues), then
    one drain op once every thread is done (rabbitmq_test.clj:52-61's
    gen/phases main -> drain shape)."""
    n_ops = opts.get("n_ops", 120)
    main = g.limit(n_ops, g.stagger(1 / 60, g.queue_gen()))
    drain = g.once({"type": "invoke", "f": "drain", "value": None})
    return {
        "generator": g.phases(main, drain),
        "checker": compose({
            "queue": queue_checker_tpu(),
            "total-queue": total_queue_checker_tpu(),
        }),
        "model": None,
    }


def rabbitmq_test(**opts) -> dict:
    return service_test("rabbitmq-queue",
                        QueueClient(opts.get("client_timeout", 0.5)),
                        queue_workload(opts), **opts)
