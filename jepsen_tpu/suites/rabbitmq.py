"""Rabbitmq-family suite: queue workload with a drain phase.

Mirrors the reference's queue test
(rabbitmq/src/jepsen/rabbitmq.clj:118-186 client,
rabbitmq/test/jepsen/rabbitmq_test.clj:46-77 wiring): an
enqueue/dequeue mix followed by a drain that empties the queue, checked
by BOTH the ordered-fold queue checker and total-queue multiset
accounting (checker.clj:109-129, 214-271).

Local mode drives casd's /queue endpoints; a state-wiping restart loses
enqueued elements, which total-queue reports as ``lost``. Real-RabbitMQ
automation (AMQP client + server install, rabbitmq.clj:24-66) slots
behind the DB protocol as in the etcd suite.
"""
from __future__ import annotations

import urllib.error

from .. import gen as g
from ..checkers.core import compose
from ..ops.folds import queue_checker_tpu, total_queue_checker_tpu
from .local_common import ServiceClient, service_test


class QueueClient(ServiceClient):
    """enqueue / dequeue / drain over /queue/<name>. Dequeue of an
    empty queue is a definite :fail (the reference's empty-queue
    convention); drain returns the remaining elements as one op, which
    the total-queue checker expands into dequeue pairs.

    Unlike real RabbitMQ (which redelivers un-acked messages, letting
    the reference map dequeue timeouts to :fail, rabbitmq.clj:152-166),
    casd pops the element immediately with no ack — a timed-out dequeue
    the daemon still processed has removed an element, so every op here
    is mutating (timeout -> :info)."""

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "enqueue":
                self._req("POST", "/queue/jepsen",
                          {"op": "enq", "v": op["value"]})
                return {**op, "type": "ok"}
            if f == "dequeue":
                try:
                    r = self._req("POST", "/queue/jepsen", {"op": "deq"})
                    return {**op, "type": "ok", "value": int(r["v"])}
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        return {**op, "type": "fail", "error": "empty"}
                    raise
            if f == "drain":
                r = self._req("POST", "/queue/jepsen", {"op": "drain"})
                return {**op, "type": "ok",
                        "value": [int(v) for v in r["vs"]]}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=True)


def queue_workload(opts: dict) -> dict:
    """Main mix (queue_gen: consecutive-int enqueues vs dequeues), then
    one drain op once every thread is done (rabbitmq_test.clj:52-61's
    gen/phases main -> drain shape)."""
    n_ops = opts.get("n_ops", 120)
    main = g.limit(n_ops, g.stagger(1 / 60, g.queue_gen()))
    drain = g.once({"type": "invoke", "f": "drain", "value": None})
    return {
        "generator": g.phases(main, drain),
        "checker": compose({
            "queue": queue_checker_tpu(),
            "total-queue": total_queue_checker_tpu(),
        }),
        "model": None,
    }


def rabbitmq_test(**opts) -> dict:
    return service_test("rabbitmq-queue",
                        QueueClient(opts.get("client_timeout", 0.5)),
                        queue_workload(opts), **opts)
