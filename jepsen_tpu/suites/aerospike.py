"""Aerospike-family suite: counter workload.

Mirrors the reference's counter test
(aerospike/src/jepsen/aerospike/core.clj:400-421): concurrent
increments and reads against one counter, checked by the bounds-window
counter checker (checker.clj:321-374) — every ok read must lie within
[sum of definitely-applied adds at invoke, sum of possibly-applied adds
at completion].

Local mode drives casd's /counter endpoints; a state-wiping restart
zeroes the counter, so later reads fall below the lower bound — the
seeded violation. ``AerospikeDB`` is the real-cluster automation
(aerospike/src/aerospike/core.clj:95-180: versioned .deb install with
the faketime-skew wrapper, mesh-seed config, service start + recovery
policy), behind the DB protocol and command-stream tested like EtcdDB.
"""
from __future__ import annotations

from .. import gen as g
from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..ops.folds import counter_checker_tpu
from ..os_impl import debian
from ..runtime import primary
from .local_common import ServiceClient, service_test

AS_CONF = "/etc/aerospike/aerospike.conf"
AS_LOG = "/var/log/aerospike/aerospike.log"

# The reference's faketime wrapper body (core.clj:116-119): every asd
# start gets a random offset and rate skew, provoking clock-dependent
# bugs on every restart.
ASD_WRAPPER = ('#!/bin/bash\nfaketime -m -f "+$((RANDOM%100))s '
               'x1.${RANDOM}" /usr/local/bin/asd')


def aerospike_conf(node, test: dict) -> str:
    """The reference's resources/aerospike.conf with $NODE_ADDRESS and
    $MESH_ADDRESS substituted (core.clj:121-132): this node's IP, and
    the primary as the mesh seed."""
    return "\n".join([
        "service {",
        "  paxos-single-replica-limit 1",
        "  pidfile /var/run/aerospike/asd.pid",
        "}",
        f"logging {{ file {AS_LOG} {{ context any info }} }}",
        "network {",
        f"  service {{ address {net_helpers.ip(str(node))} port 3000 }}",
        "  heartbeat {",
        "    mode mesh",
        f"    mesh-seed-address-port "
        f"{net_helpers.ip(str(primary(test)))} 3002",
        "    port 3002",
        "  }",
        "}",
        "namespace jepsen { replication-factor 3 }",
    ])


class AerospikeDB(DB):
    """Versioned .deb Aerospike cluster (core.clj:95-180)."""

    def __init__(self, version: str = "3.5.4"):
        self.version = version

    def setup(self, test, node):
        with c.su():
            if debian.installed_version("aerospike-server-community") \
                    != f"{self.version}-1":
                debian.install(["python"])
                cu.meh(debian.uninstall,
                       ["aerospike-server-community", "aerospike-tools"])
                with c.cd("/tmp"):
                    c.exec_("wget", "-O", "aerospike.tgz",
                            "http://www.aerospike.com/download/server/"
                            f"{self.version}/artifact/debian7")
                    c.exec_("tar", "xvfz", "aerospike.tgz")
                with c.cd(f"/tmp/aerospike-server-community-"
                          f"{self.version}-debian7"):
                    c.exec_("dpkg", "-i",
                            lit("aerospike-server-community-*.deb"))
                    c.exec_("dpkg", "-i", lit("aerospike-tools-*.deb"))
                # faketime-skew the server binary (core.clj:115-119).
                c.exec_("mv", "/usr/bin/asd", "/usr/local/bin/asd")
                c.exec_("echo", ASD_WRAPPER, lit(">"), "/usr/bin/asd")
                c.exec_("chmod", "0755", "/usr/bin/asd")
            c.exec_("echo", aerospike_conf(node, test), lit(">"), AS_CONF)
            c.exec_("service", "aerospike", "start")
            c.exec_("asinfo", "-v",
                    "config-set:context=service;"
                    "paxos-recovery-policy=auto-dun-master")

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "service", "aerospike", "stop")
            cu.meh(c.exec_, "killall", "-9", "asd")
            cu.meh(c.exec_, "truncate", "--size", "0", AS_LOG)
            for d in ("data", "smd", "udf"):
                c.exec_("rm", "-rf", lit(f"/opt/aerospike/{d}/*"))

    def log_files(self, test, node):
        return [AS_LOG]


class CounterClient(ServiceClient):
    """add / read over /counter/<name> (core.clj:231-258 client)."""

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "add":
                self._req("POST", "/counter/jepsen",
                          {"delta": op["value"]})
                return {**op, "type": "ok"}
            if f == "read":
                r = self._req("GET", "/counter/jepsen")
                return {**op, "type": "ok", "value": int(r["value"])}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "add")


def _counter_gen(test, process, ctx):
    if ctx.rng.random() < 0.5:
        return {"type": "invoke", "f": "add",
                "value": 1 + ctx.rng.randrange(4)}
    return {"type": "invoke", "f": "read", "value": None}


def counter_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 200)
    return {
        "generator": g.limit(n_ops, g.stagger(1 / 80, _counter_gen)),
        "checker": counter_checker_tpu(),
        "model": None,
    }


def aerospike_test(**opts) -> dict:
    return service_test("aerospike-counter",
                        CounterClient(opts.get("client_timeout", 0.5)),
                        counter_workload(opts), **opts)
