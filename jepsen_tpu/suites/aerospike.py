"""Aerospike-family suite: counter workload.

Mirrors the reference's counter test
(aerospike/src/jepsen/aerospike/core.clj:400-421): concurrent
increments and reads against one counter, checked by the bounds-window
counter checker (checker.clj:321-374) — every ok read must lie within
[sum of definitely-applied adds at invoke, sum of possibly-applied adds
at completion].

Local mode drives casd's /counter endpoints; a state-wiping restart
zeroes the counter, so later reads fall below the lower bound — the
seeded violation. Real-Aerospike automation (core.clj:80-130, including
the faketime-skewed install) slots behind the DB protocol as in the
etcd suite.
"""
from __future__ import annotations

from .. import gen as g
from ..ops.folds import counter_checker_tpu
from .local_common import ServiceClient, service_test


class CounterClient(ServiceClient):
    """add / read over /counter/<name> (core.clj:231-258 client)."""

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "add":
                self._req("POST", "/counter/jepsen",
                          {"delta": op["value"]})
                return {**op, "type": "ok"}
            if f == "read":
                r = self._req("GET", "/counter/jepsen")
                return {**op, "type": "ok", "value": int(r["value"])}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "add")


def _counter_gen(test, process, ctx):
    if ctx.rng.random() < 0.5:
        return {"type": "invoke", "f": "add",
                "value": 1 + ctx.rng.randrange(4)}
    return {"type": "invoke", "f": "read", "value": None}


def counter_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 200)
    return {
        "generator": g.limit(n_ops, g.stagger(1 / 80, _counter_gen)),
        "checker": counter_checker_tpu(),
        "model": None,
    }


def aerospike_test(**opts) -> dict:
    return service_test("aerospike-counter",
                        CounterClient(opts.get("client_timeout", 0.5)),
                        counter_workload(opts), **opts)
