"""Chronos-family suite: job-scheduler run verification.

Mirrors the reference's only suite-local checker namespace
(chronos/src/jepsen/chronos/checker.clj): jobs are registered with a
start time, a run count, an interval, a tardiness allowance (epsilon),
and a duration; the scheduler must begin one run inside every expected
target window. The reference solves the target→run assignment with a
constraint solver (loco); targets and runs are sorted intervals of
uniform width, so greedy earliest-run matching over targets in end
order is an exact maximum matching here (classic interval scheduling
exchange argument) — no solver needed.

Checker inputs come from the history: ok ``add-job`` ops carry job
dicts, and a final ok ``read`` carries {"time": T, "runs": [{"name",
"start", "end"}...]} (the shape chronos' read phase produces). All
times are seconds (floats ok).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..checkers.core import Checker, merge_valid
from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian

JOB_DIR = "/tmp/chronos-test/"


class ChronosDB(DB):
    """Chronos on a Mesos cluster (chronos.clj:56-83): the composed
    MesosDB (mesosphere.py) brings up zookeeper + mesos, then the
    pinned chronos package is installed, the schedule horizon is
    lowered so frequent tasks aren't forgotten (chronos.clj:40-45),
    the run-artifact job dir is created, and the service started."""

    def __init__(self, mesos_version: str = "0.23.0-1.0.debian81",
                 chronos_version: str = "2.3.4-1.0.81.debian77",
                 mesos: DB | None = None):
        from .mesosphere import MesosDB
        self.chronos_version = chronos_version
        self.mesos = mesos or MesosDB(mesos_version)

    def setup(self, test, node):
        self.mesos.setup(test, node)
        with c.su():
            debian.install([f"chronos={self.chronos_version}"])
            c.exec_("echo", "1", lit(">"),
                    "/etc/chronos/conf/schedule_horizon")
            c.exec_("mkdir", "-p", JOB_DIR)
            c.exec_("service", "chronos", "start")

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "service", "chronos", "stop")
            cu.meh(cu.grepkill, "/usr/bin/chronos")
        self.mesos.teardown(test, node)
        with c.su():
            c.exec_("rm", "-rf", JOB_DIR)
            c.exec_("truncate", "--size", "0", "/var/log/messages")

    def log_files(self, test, node):
        return self.mesos.log_files(test, node) + ["/var/log/messages"]

# The reference lets the scheduler miss deadlines by a few extra
# seconds (checker.clj epsilon-forgiveness).
EPSILON_FORGIVENESS = 5


@dataclass(frozen=True)
class Job:
    name: object
    start: float        # first target time
    count: int          # how many runs are scheduled
    interval: float     # seconds between targets
    epsilon: float      # allowed tardiness per run
    duration: float     # how long a run takes

    @classmethod
    def from_value(cls, v: dict) -> "Job":
        return cls(name=v["name"], start=v["start"], count=v["count"],
                   interval=v["interval"], epsilon=v["epsilon"],
                   duration=v["duration"])


def job_targets(read_time: float, job: Job) -> List[Tuple[float, float]]:
    """[(start, latest-allowed-start)] for every target that MUST have
    begun by the read (checker.clj job->targets): targets may start up
    to epsilon late and need duration to finish, so only targets before
    read_time - epsilon - duration are due."""
    finish = read_time - job.epsilon - job.duration
    out = []
    for k in range(job.count):
        t = job.start + k * job.interval
        if t >= finish:
            break
        out.append((t, t + job.epsilon + EPSILON_FORGIVENESS))
    return out


def job_solution(read_time: float, job: Job,
                 runs: Sequence[dict]) -> dict:
    """Match this job's complete runs to its due targets
    (checker.clj job-solution). Greedy earliest-available-run per
    target in order is an exact maximum matching for uniform sorted
    windows. Returns {"valid", "job", "solution", "extra",
    "complete", "incomplete"}."""
    complete = sorted((r for r in runs if r.get("end") is not None),
                      key=lambda r: r["start"])
    incomplete = sorted((r for r in runs if r.get("end") is None),
                        key=lambda r: r["start"])
    targets = job_targets(read_time, job)
    used = [False] * len(complete)
    solution: Dict[Tuple[float, float], Optional[dict]] = {}
    valid = True
    for lo, hi in targets:
        found = None
        for i, r in enumerate(complete):
            if used[i]:
                continue
            if r["start"] > hi:
                break
            if r["start"] >= lo:
                found = i
                break
        if found is None:
            valid = False
            solution[(lo, hi)] = None
        else:
            used[found] = True
            solution[(lo, hi)] = complete[found]
    extra = [r for i, r in enumerate(complete) if not used[i]]
    return {"valid": valid, "job": job, "solution": solution,
            "extra": extra, "complete": complete,
            "incomplete": incomplete}


def solution(read_time: float, jobs: Sequence[Job],
             runs: Sequence[dict]) -> dict:
    """Partition jobs and runs by name and solve each
    (checker.clj solution)."""
    by_name: Dict[object, List[dict]] = {}
    for r in runs:
        by_name.setdefault(r["name"], []).append(r)
    sols = {j.name: job_solution(read_time, j, by_name.get(j.name, []))
            for j in jobs}
    return {
        "valid": all(s["valid"] for s in sols.values()),
        "jobs": sols,
        "extra": [r for s in sols.values() for r in s["extra"]],
        "incomplete": [r for s in sols.values() for r in s["incomplete"]],
        "read_time": read_time,
    }


class ChronosChecker(Checker):
    """History-level wrapper: collect ok add-job ops and the final ok
    read of {"time", "runs"}, then verify the schedule."""

    def check(self, test, model, history, opts=None) -> dict:
        jobs = [Job.from_value(op.value) for op in history
                if op.type == "ok" and op.f == "add-job"]
        final = None
        for op in history:
            if op.type == "ok" and op.f == "read":
                final = op.value
        if final is None:
            return {"valid": "unknown",
                    "error": "schedule was never read"}
        out = solution(final["time"], jobs, final["runs"])
        out["valid"] = merge_valid([out["valid"]])
        return out


def chronos_checker() -> Checker:
    return ChronosChecker()
