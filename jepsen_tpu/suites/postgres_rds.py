"""Postgres-RDS suite: single-endpoint bank comparison test.

Mirrors the reference suite
(postgres-rds/src/jepsen/postgres_rds.clj): there is deliberately NO
node automation — the database is a managed RDS endpoint, so the test
map has an empty node list (basic-test, 262-267) and the client carries
the endpoint address. The bank client reads all balances and moves
amounts between two accounts with an optional row-lock mode and
in-place updates (BankClient, 136-201); the checker is the balance-sum
invariant (bank-checker, 235-259). Here the same workload drives casd's
bank endpoints: ``postgres_rds_test`` is the single-node comparison
run (no nemesis by default — faults on a managed single instance are
outside the harness's reach, exactly the reference's framing), with
``endpoint`` standing in for the RDS address when given.
"""
from __future__ import annotations

from ..testing import noop_test
from .cockroachdb import BankClient, bank_workload


def endpoint_test(endpoint: str, **opts) -> dict:
    """A test map aimed at a managed endpoint: empty node list, no
    OS/DB automation (postgres_rds.clj:262-267's basic-test), client
    routed at the endpoint."""
    client = BankClient(opts.get("client_timeout", 1.0),
                        opts.get("accounts", 5), opts.get("balance", 10))
    test = noop_test(
        name="postgres-rds",
        nodes=[],
        concurrency=opts.get("concurrency", 4),
        client=client,
        client_urls={None: endpoint},
        **bank_workload(opts))
    test.update(opts)
    return test


def postgres_rds_test(**opts) -> dict:
    """The local comparison run: the bank workload against one casd
    instance, single node, no nemesis (the managed-service framing)."""
    from .cockroachdb import bank_service_test
    opts.setdefault("n_nodes", 1)
    return bank_service_test("postgres-rds", **opts)
