"""Mesos cluster automation for the chronos suite.

Mirrors chronos/src/jepsen/mesosphere.clj: the mesosphere apt repo +
pinned mesos install (26-36), a ZooKeeper ensemble underneath (db at
133-150 composes zk/db), /etc/mesos/zk + quorum config (49-58), and
role-split daemon startup — the first ``MASTER_COUNT`` sorted nodes run
mesos-master with the zk URI and majority quorum, the rest run
mesos-slave pointed at the same URI (60-118); teardown kills both and
wipes work/log dirs (121-131, 144-148).
"""
from __future__ import annotations

from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..utils.core import majority
from .zookeeper import ZookeeperDB

MASTER_COUNT = 3
MASTER_PIDFILE = "/var/run/mesos/master.pid"
SLAVE_PIDFILE = "/var/run/mesos/slave.pid"
MASTER_DIR = "/var/lib/mesos/master"
SLAVE_DIR = "/var/lib/mesos/slave"
LOG_DIR = "/var/log/mesos"
MASTER_BIN = "/usr/sbin/mesos-master"
SLAVE_BIN = "/usr/sbin/mesos-slave"
REPO_LINE = "deb http://repos.mesosphere.io/debian wheezy main"
KEYSERVER = "keyserver.ubuntu.com"
KEY = "E56151BF"


def masters(test: dict) -> list:
    """The first MASTER_COUNT sorted nodes run masters
    (mesosphere.clj:68, 101)."""
    return sorted(str(n) for n in test.get("nodes") or [])[:MASTER_COUNT]


def zk_uri(test: dict) -> str:
    """zk://n1:2181,...,nN:2181/mesos (mesosphere.clj:38-47)."""
    hosts = ",".join(f"{n}:2181" for n in test.get("nodes") or [])
    return f"zk://{hosts}/mesos"


class MesosDB(DB):
    """Mesos over a ZooKeeper ensemble (mesosphere.clj:26-150)."""

    def __init__(self, version: str = "0.23.0-1.0.debian81",
                 zk: DB | None = None):
        self.version = version
        self.zk = zk or ZookeeperDB()

    def setup(self, test, node):
        self.zk.setup(test, node)
        # Quorum must come from the masters that actually exist —
        # clusters smaller than MASTER_COUNT would otherwise demand an
        # unreachable majority and the registrar could never commit.
        quorum = majority(len(masters(test)))
        with c.su():
            debian.add_repo("mesosphere", REPO_LINE, KEYSERVER, KEY)
            debian.install([f"mesos={self.version}"])
            for d in ("/var/run/mesos", MASTER_DIR, SLAVE_DIR, LOG_DIR):
                c.exec_("mkdir", "-p", d)
            c.exec_("echo", zk_uri(test), lit(">"), "/etc/mesos/zk")
            c.exec_("echo", str(quorum), lit(">"),
                    "/etc/mesos-master/quorum")
            if str(node) in masters(test):
                cu.start_daemon(
                    {"logfile": f"{LOG_DIR}/master.stdout",
                     "pidfile": MASTER_PIDFILE, "chdir": MASTER_DIR,
                     "match_executable": False},
                    "/usr/bin/env", "GLOG_v=1", MASTER_BIN,
                    f"--hostname={node}",
                    f"--log_dir={LOG_DIR}",
                    f"--quorum={quorum}",
                    "--registry_fetch_timeout=120secs",
                    "--registry_store_timeout=5secs",
                    f"--work_dir={MASTER_DIR}",
                    "--offer_timeout=30secs",
                    f"--zk={zk_uri(test)}")
            else:
                cu.start_daemon(
                    {"logfile": f"{LOG_DIR}/slave.stdout",
                     "pidfile": SLAVE_PIDFILE, "chdir": SLAVE_DIR},
                    SLAVE_BIN,
                    f"--hostname={node}",
                    f"--log_dir={LOG_DIR}",
                    "--recovery_timeout=30secs",
                    f"--work_dir={SLAVE_DIR}",
                    f"--master={zk_uri(test)}")

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "killall", "-9", "mesos-slave")
            cu.meh(c.exec_, "rm", "-rf", SLAVE_PIDFILE)
            cu.meh(c.exec_, "killall", "-9", "mesos-master")
            cu.meh(c.exec_, "rm", "-rf", MASTER_PIDFILE)
            c.exec_("rm", "-rf", lit(f"{MASTER_DIR}/*"),
                    lit(f"{SLAVE_DIR}/*"), lit(f"{LOG_DIR}/*"))
        self.zk.teardown(test, node)

    def log_files(self, test, node):
        return (self.zk.log_files(test, node)
                + [f"{LOG_DIR}/master.stdout", f"{LOG_DIR}/slave.stdout"])
