"""Consul suite: CAS register over the v1 KV HTTP API.

Mirrors the reference suite (consul/src/jepsen/consul.clj): values are
JSON-encoded and base64-wrapped in responses; CAS is index-based — read
the key's ModifyIndex, then conditional-PUT with ``?cas=<index>``
(consul.clj:101-110's consul-cas!). The workload/checker wiring is the
etcd suite's independent-keys CAS register (the two suites share the
family, consul.clj:141-179).

Local mode drives casd's /v1/kv emulation of the same API subset, so
the client's wire handling (base64, index CAS, 404-as-absent) is
exercised against a real server; ``ConsulDB`` is the real-cluster
automation (agent bootstrap + join, consul.clj:21-54) behind the DB
protocol, command-stream tested like EtcdDB.
"""
from __future__ import annotations

import base64
import json
import urllib.error

from .. import independent
from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..db import DB
from ..suites import etcd as etcd_suite
from .local_common import ServiceClient, service_test

CONSUL_VERSION = "1.18.1"
CONSUL_URL = ("https://releases.hashicorp.com/consul/"
              f"{CONSUL_VERSION}/consul_{CONSUL_VERSION}_linux_amd64.zip")
DIR = "/opt/consul"
BINARY = f"{DIR}/consul"
PIDFILE = "/var/run/consul.pid"
DATA_DIR = "/var/lib/consul"
LOG_FILE = "/var/log/consul.log"


class ConsulDB(DB):
    """Real consul agents forming one cluster (consul.clj:21-54): the
    primary bootstraps, every other node joins it by IP; teardown kills
    the agent and wipes its data dir. Consul ships as a single static Go
    binary in a zip, deployed with the shared install_archive path."""

    def _install(self, test) -> None:
        """Fetch + unzip the agent binary into DIR. Not install_archive:
        consul's zip holds a single top-level FILE (the binary), which
        install_archive's sole-root rule would move to DIR itself;
        unzipping inside DIR yields DIR/consul."""
        url = test.get("consul_url", CONSUL_URL)
        c.exec_("mkdir", "-p", cu.TMP_DIR_BASE)
        with c.cd(cu.TMP_DIR_BASE):
            zip_path = c.expand_path(cu.wget(url))
        c.exec_("mkdir", "-p", DIR)
        with c.cd(DIR):
            c.exec_("unzip", "-o", zip_path)
        c.exec_("chmod", "+x", BINARY)

    def setup(self, test, node):
        nodes = test.get("nodes") or []
        prim = nodes[0] if nodes else node
        with c.su():
            self._install(test)
            args = ["agent", "-server", "-log-level", "debug",
                    "-client", "0.0.0.0",
                    "-bind", net_helpers.ip(str(node)),
                    "-data-dir", DATA_DIR,
                    "-node", str(node)]
            if node == prim:
                args += ["-bootstrap"]
            else:
                # -retry-join, not -join: DB setup runs on all nodes in
                # parallel, so a follower may start before the primary
                # is listening; one-shot -join would fail and kill the
                # agent.
                args += ["-retry-join", net_helpers.ip(str(prim))]
            cu.start_daemon(
                {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY, *args)

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "killall", "-9", "consul")
            c.exec_("rm", "-rf", PIDFILE, DATA_DIR, DIR)

    def log_files(self, test, node):
        return [LOG_FILE]


class ConsulClient(ServiceClient):
    """CAS register over /v1/kv with consul's error discipline: reads
    are side-effect free (any fault -> fail); a definite CAS index
    mismatch is fail; network indeterminacy on PUTs is info."""

    def _key(self, k) -> str:
        return f"/v1/kv/jepsen-{k}"

    def _get(self, k):
        """(value, modify_index) or (ABSENT, 0) when the key is
        missing."""
        try:
            rows = self._req("GET", self._key(k))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return etcd_suite.ABSENT, 0
            raise
        row = rows[0]
        value = json.loads(base64.b64decode(row["Value"]).decode())
        return value, int(row["ModifyIndex"])

    def _put(self, k, v, cas=None) -> bool:
        import urllib.request
        url = f"{self.base}{self._key(k)}"
        if cas is not None:
            url += f"?cas={cas}"
        req = urllib.request.Request(url, data=json.dumps(v).encode(),
                                     method="PUT")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return r.read().decode().strip() == "true"

    def invoke(self, test, op):
        f = op["f"]
        k, v = op["value"] if independent.is_kv(op["value"]) \
            else (None, op["value"])

        def done(typ, value=v, **extra):
            out = {**op, "type": typ, **extra}
            out["value"] = independent.tuple_(k, value) if k is not None \
                else value
            return out

        def body():
            if f == "read":
                value, _ = self._get(k)
                return done("ok", value)
            if f == "write":
                self._put(k, v)
                return done("ok")
            if f == "cas":
                old, new = v
                # index CAS: read the current value + index first; the
                # read phase has no side effects, so faults there are
                # still a definite fail (handled by the outer guard
                # only for the mutating PUT below).
                try:
                    cur, index = self._get(k)
                except Exception:
                    return done("fail", error="read-phase")
                if cur != old:
                    return done("fail", error="value-mismatch")
                ok = self._put(k, new, cas=index)
                return done("ok") if ok else \
                    done("fail", error="index-mismatch")
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f != "read")


def consul_test(**opts) -> dict:
    """Independent-keys CAS over the consul KV wire protocol
    (consul.clj:141-179 wiring, etcd-family workload). service_test
    derives/validates concurrency from threads_per_key."""
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "consul",
        ConsulClient(opts.get("client_timeout", 0.5)),
        etcd_suite.workload(opts), **opts)
