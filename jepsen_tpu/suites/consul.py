"""Consul suite: CAS register over the v1 KV HTTP API.

Mirrors the reference suite (consul/src/jepsen/consul.clj): values are
JSON-encoded and base64-wrapped in responses; CAS is index-based — read
the key's ModifyIndex, then conditional-PUT with ``?cas=<index>``
(consul.clj:101-110's consul-cas!). The workload/checker wiring is the
etcd suite's independent-keys CAS register (the two suites share the
family, consul.clj:141-179).

Local mode drives casd's /v1/kv emulation of the same API subset, so
the client's wire handling (base64, index CAS, 404-as-absent) is
exercised against a real server; real-Consul automation (agent
bootstrap, consul.clj:21-54) slots behind the DB protocol as in the
etcd suite.
"""
from __future__ import annotations

import base64
import json
import urllib.error

from .. import independent
from ..suites import etcd as etcd_suite
from .local_common import ServiceClient, service_test


class ConsulClient(ServiceClient):
    """CAS register over /v1/kv with consul's error discipline: reads
    are side-effect free (any fault -> fail); a definite CAS index
    mismatch is fail; network indeterminacy on PUTs is info."""

    def _key(self, k) -> str:
        return f"/v1/kv/jepsen-{k}"

    def _get(self, k):
        """(value, modify_index) or (ABSENT, 0) when the key is
        missing."""
        try:
            rows = self._req("GET", self._key(k))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return etcd_suite.ABSENT, 0
            raise
        row = rows[0]
        value = json.loads(base64.b64decode(row["Value"]).decode())
        return value, int(row["ModifyIndex"])

    def _put(self, k, v, cas=None) -> bool:
        import urllib.request
        url = f"{self.base}{self._key(k)}"
        if cas is not None:
            url += f"?cas={cas}"
        req = urllib.request.Request(url, data=json.dumps(v).encode(),
                                     method="PUT")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return r.read().decode().strip() == "true"

    def invoke(self, test, op):
        f = op["f"]
        k, v = op["value"] if independent.is_kv(op["value"]) \
            else (None, op["value"])

        def done(typ, value=v, **extra):
            out = {**op, "type": typ, **extra}
            out["value"] = independent.tuple_(k, value) if k is not None \
                else value
            return out

        def body():
            if f == "read":
                value, _ = self._get(k)
                return done("ok", value)
            if f == "write":
                self._put(k, v)
                return done("ok")
            if f == "cas":
                old, new = v
                # index CAS: read the current value + index first; the
                # read phase has no side effects, so faults there are
                # still a definite fail (handled by the outer guard
                # only for the mutating PUT below).
                try:
                    cur, index = self._get(k)
                except Exception:
                    return done("fail", error="read-phase")
                if cur != old:
                    return done("fail", error="value-mismatch")
                ok = self._put(k, new, cas=index)
                return done("ok") if ok else \
                    done("fail", error="index-mismatch")
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f != "read")


def consul_test(**opts) -> dict:
    """Independent-keys CAS over the consul KV wire protocol
    (consul.clj:141-179 wiring, etcd-family workload). service_test
    derives/validates concurrency from threads_per_key."""
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "consul",
        ConsulClient(opts.get("client_timeout", 0.5)),
        etcd_suite.workload(opts), **opts)
