"""ZooKeeper suite: CAS register over a ZK ensemble.

Mirrors the reference suite (zookeeper/src/jepsen/zookeeper.clj):
DB automation at 41-73 — apt-install the distro zookeeper packages,
write each node's ``myid`` from its position in the node list, append
the ``server.<id>=<node>:2888:3888`` ensemble lines to zoo.cfg, and
restart the service; teardown stops the service and wipes the version
directories and logs. The workload (zookeeper.clj:107-131) is the
CAS-register family shared with etcd — the avout zk-atom client there
maps here onto the same independent-keys register workload, run against
casd in local mode so the family's end-to-end detection is exercised
without a JVM.
"""
from __future__ import annotations

from ..control import core as c
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from .etcd import EtcdClient, workload as register_workload
from .local_common import service_test

ZK_VERSION = "3.4.5+dfsg-2"
CONF_DIR = "/etc/zookeeper/conf"
LOG_FILE = "/var/log/zookeeper/zookeeper.log"

# The distro zoo.cfg baseline the reference ships as a resource
# (zookeeper/resources/zoo.cfg): data dir, client port, quorum timing.
ZOO_CFG = "\n".join([
    "tickTime=2000",
    "initLimit=10",
    "syncLimit=5",
    "dataDir=/var/lib/zookeeper",
    "clientPort=2181",
])


def node_ids(test: dict) -> dict:
    """node -> ensemble id, by position (zookeeper.clj:19-30)."""
    return {node: i for i, node in enumerate(test.get("nodes") or [])}


def zoo_cfg_servers(test: dict) -> str:
    """The ensemble's server lines (zookeeper.clj:32-38)."""
    return "\n".join(f"server.{i}={node}:2888:3888"
                     for node, i in node_ids(test).items())


class ZookeeperDB(DB):
    """Distro-package ZooKeeper ensemble (zookeeper.clj:41-73): install
    the pinned zookeeper/zookeeperd packages, write myid + zoo.cfg, and
    bounce the service."""

    def __init__(self, version: str = ZK_VERSION):
        self.version = version

    def setup(self, test, node):
        with c.su():
            debian.install([f"{p}={self.version}" for p in
                            ("zookeeper", "zookeeper-bin", "zookeeperd")])
            c.exec_("echo", str(node_ids(test)[node]),
                    lit(">"), f"{CONF_DIR}/myid")
            c.exec_("echo", ZOO_CFG + "\n" + zoo_cfg_servers(test),
                    lit(">"), f"{CONF_DIR}/zoo.cfg")
            c.exec_("service", "zookeeper", "restart")

    def teardown(self, test, node):
        from ..control import util as cu
        with c.su():
            # Fresh nodes pass through teardown first (db.cycle): no
            # service to stop is routine, not an error.
            cu.meh(c.exec_, "service", "zookeeper", "stop")
            cu.meh(c.exec_, "rm", "-rf",
                   lit("/var/lib/zookeeper/version-*"),
                   lit("/var/log/zookeeper/*"))

    def log_files(self, test, node):
        return [LOG_FILE]


def zookeeper_test(**opts) -> dict:
    """The register workload (zookeeper.clj:107-131) in local mode:
    independent-keys CAS against casd, ZookeeperDB slotting in for real
    ensembles."""
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "zookeeper",
        EtcdClient(opts.get("client_timeout", 0.5)),
        register_workload(opts), **opts)
