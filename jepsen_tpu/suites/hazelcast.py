"""Hazelcast-family suite: lock (linearizable mutex), unique ids, and
queue workloads selected by name — mirroring the reference's
``:workload`` flag dispatch (hazelcast/src/jepsen/hazelcast.clj:278-304:
lock -> knossos Mutex via checker/linearizable, id-gen ->
checker/unique-ids, queue -> checker/total-queue).

Local mode drives the casd daemon's /lock, /ids, /queue endpoints
(resources/casd.cpp) — real processes under real kill/pause nemeses;
a state-wiping restart double-grants a held lock, resets the id
sequence (duplicate ids), and loses queued elements, each caught by
its family's checker. ``HazelcastDB`` is the real-cluster automation
(jdk install + server-uberjar upload + java -jar with the peer member
list, hazelcast.clj:63-112), behind the DB protocol and command-stream
tested like EtcdDB.
"""
from __future__ import annotations

import threading
import urllib.error

from .. import gen as g
from ..checkers.core import compose
from ..checkers.linearizable import linearizable
from ..checkers.timeline import html_timeline
from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..db import DB
from ..models.core import mutex
from ..ops.folds import unique_ids_checker_tpu
from ..os_impl import debian
from .local_common import ServiceClient, service_test

HZ_DIR = "/opt/hazelcast"
HZ_JAR = f"{HZ_DIR}/server.jar"
HZ_PIDFILE = f"{HZ_DIR}/server.pid"
HZ_LOG = f"{HZ_DIR}/server.log"


class HazelcastDB(DB):
    """Uberjar Hazelcast cluster (hazelcast.clj:63-112): the server jar
    (built locally by the reference's lein sub-project; here a
    caller-supplied artifact) is uploaded to every node and launched
    with ``--members`` listing every peer's IP; teardown stops the
    daemon and removes its log/pid."""

    def __init__(self, server_jar: str):
        self.server_jar = server_jar

    def setup(self, test, node):
        with c.su():
            debian.install_jdk()
            c.exec_("mkdir", "-p", HZ_DIR)
            c.upload(self.server_jar, HZ_JAR)
            members = ",".join(net_helpers.ip(str(n))
                               for n in test.get("nodes") or []
                               if n != node)
            with c.cd(HZ_DIR):
                cu.start_daemon(
                    {"logfile": HZ_LOG, "pidfile": HZ_PIDFILE,
                     "chdir": HZ_DIR},
                    "/usr/bin/java", "-jar", HZ_JAR, "--members", members)

    def teardown(self, test, node):
        with c.cd(HZ_DIR), c.su():
            cu.stop_daemon(HZ_PIDFILE)
            c.exec_("rm", "-rf", HZ_LOG, HZ_PIDFILE)

    def log_files(self, test, node):
        return [HZ_LOG]


class LockClient(ServiceClient):
    """Mutex over /lock/<name>: acquire/release with the calling
    process as owner (hazelcast.clj:101-132 lock client semantics)."""

    def invoke(self, test, op):
        owner = str(op.get("process"))
        form = {"op": op["f"], "owner": owner}

        def body():
            try:
                self._req("POST", "/lock/jepsen", form)
                return {**op, "type": "ok"}
            except urllib.error.HTTPError as e:
                if e.code == 409:
                    return {**op, "type": "fail", "error": "rejected"}
                raise

        return self.guarded(op, body, mutating=True)


class IdsClient(ServiceClient):
    """Unique-id generation over /ids/next (hazelcast.clj:195-219)."""

    def invoke(self, test, op):
        def body():
            body_json = self._req("POST", "/ids/next")
            return {**op, "type": "ok", "value": body_json["id"]}

        return self.guarded(op, body, mutating=True)


class _AlternatingLockGen(g.Generator):
    """Each thread alternates acquire -> release (the hazelcast lock
    workload's per-process cycle, hazelcast.clj:285-287)."""

    def __init__(self):
        self._next = {}
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        thread = ctx.thread_of(process)
        with self._lock:
            f = self._next.get(thread, "acquire")
            self._next[thread] = "release" if f == "acquire" else "acquire"
        return {"type": "invoke", "f": f, "value": None}


def lock_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 80)
    return {
        "generator": g.limit(n_ops, g.stagger(1 / 40,
                                              _AlternatingLockGen())),
        "checker": compose({
            "linear": linearizable(
                backend=opts.get("checker_backend", "tpu")),
            "timeline": html_timeline(),
        }),
        "model": mutex(),
    }


def ids_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 150)
    gen = g.limit(n_ops, g.stagger(
        1 / 100, lambda test, process, ctx: {"type": "invoke",
                                             "f": "generate",
                                             "value": None}))
    return {"generator": gen,
            "checker": unique_ids_checker_tpu(),
            "model": None}


def queue_workload(opts: dict) -> dict:
    """Enqueue/dequeue mix then a drain phase — shared with the
    rabbitmq suite, where it is the headline workload."""
    from .rabbitmq import queue_workload as rq
    return rq(opts)


WORKLOADS = {"lock": lock_workload, "ids": ids_workload,
             "queue": queue_workload}


def hazelcast_test(workload: str = "lock", **opts) -> dict:
    """Local-mode hazelcast-family test (workload dispatch mirroring
    hazelcast.clj:278-304 + 340-343's --workload flag)."""
    from .rabbitmq import QueueClient
    clients = {"lock": LockClient, "ids": IdsClient, "queue": QueueClient}
    w = WORKLOADS[workload](opts)
    return service_test(f"hazelcast-{workload}",
                        clients[workload](opts.get("client_timeout", 0.5)),
                        w, **opts)
