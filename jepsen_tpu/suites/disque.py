"""Disque suite: total-queue accounting on the Disque job queue.

Mirrors the reference suite (disque/src/jepsen/disque.clj): build from
source on the node (git clone + make at a pinned rev, 40-53), deploy
the config file (55-62), start under start-stop-daemon with a pidfile
(72-92), join every node to the primary via ``disque cluster meet
<primary-ip> 7711`` (94-104), and stop/wipe with killall + data rm
(106-119). The workload (disque.clj:121-213) is the queue/total-queue
family with a drain phase — shared with the rabbitmq module here — run
against casd's queue endpoints in local mode.
"""
from __future__ import annotations

import socket

from ..client import Client
from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..runtime import primary, synchronize
from .local_common import service_test
from .rabbitmq import QueueClient, queue_workload

GIT_URL = "https://github.com/antirez/disque.git"
DIR = "/opt/disque"
DATA_DIR = "/var/lib/disque"
PIDFILE = "/var/run/disque.pid"
BINARY = f"{DIR}/src/disque-server"
CONTROL = f"{DIR}/src/disque"
CONFIG_FILE = f"{DIR}/disque.conf"
LOG_FILE = f"{DATA_DIR}/log"
PORT = 7711

# The reference's resources/disque.conf with %DATA_DIR% substituted
# (disque.clj:55-62).
CONFIG = "\n".join([
    f"port {PORT}",
    f"dir {DATA_DIR}",
    "appendonly yes",
])


class DisqueDB(DB):
    """Source-built Disque cluster (disque.clj:40-119)."""

    def __init__(self, version: str = "8a9290c"):
        self.version = version

    def setup(self, test, node):
        with c.su():
            debian.install(["git-core", "build-essential"])
            with c.cd("/opt"):
                if not cu.exists("disque"):
                    c.exec_("git", "clone", GIT_URL)
            with c.cd(DIR):
                c.exec_("git", "pull")
                c.exec_("git", "reset", "--hard", self.version)
                c.exec_("make")
            c.exec_("echo", CONFIG, lit(">"), CONFIG_FILE)
            c.exec_("mkdir", "-p", DATA_DIR)
            cu.start_daemon(
                {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY, CONFIG_FILE)
        # Everyone meets the primary (disque.clj:94-104).
        synchronize(test)
        p = primary(test)
        if node != p:
            out = c.exec_(CONTROL, "-p", str(PORT), "cluster", "meet",
                          net_helpers.ip(str(p)), str(PORT))
            assert out.strip() == "OK", out

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "killall", "-9", "disque-server")
            cu.meh(c.exec_, "rm", "-rf", PIDFILE)
            cu.meh(c.exec_, "rm", "-rf", lit(f"{DATA_DIR}/*"), LOG_FILE)

    def log_files(self, test, node):
        return [LOG_FILE]


# ------------------------------------------------------- RESP client
# The reference's data plane is jedis speaking RESP to real Disque
# (disque.clj:129-150: addjob/getjob). casd serves the same command
# subset on --resp-port, so the local-mode suite drives a genuine
# binary wire protocol end to end — socket framing, bulk strings,
# null-array empty replies — not an HTTP emulation.


class RespConnection:
    """One RESP connection: array-of-bulk-strings commands out, typed
    replies (+simple, -error, :int, $bulk, *array) back."""

    def __init__(self, host: str, port: int, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self.buf = b""

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass

    def cmd(self, *args):
        out = b"*%d\r\n" % len(args)
        for a in args:
            b = str(a).encode()
            out += b"$%d\r\n%s\r\n" % (len(b), b)
        self.sock.sendall(out)
        return self._reply()

    def _recv(self) -> None:
        chunk = self.sock.recv(4096)
        if not chunk:
            raise ConnectionResetError("RESP peer closed")
        self.buf += chunk

    def _line(self) -> bytes:
        while b"\r\n" not in self.buf:
            self._recv()
        line, _, self.buf = self.buf.partition(b"\r\n")
        return line

    def _reply(self):
        line = self._line()
        t, rest = chr(line[0]), line[1:]
        if t == "+":
            return rest.decode()
        if t == "-":
            raise RespError(rest.decode())
        if t == ":":
            return int(rest)
        if t == "$":
            n = int(rest)
            if n < 0:
                return None
            while len(self.buf) < n + 2:
                self._recv()
            s, self.buf = self.buf[:n], self.buf[n + 2:]
            return s.decode()
        if t == "*":
            n = int(rest)
            if n < 0:
                return None
            return [self._reply() for _ in range(n)]
        raise ValueError(f"bad RESP type byte {line!r}")


class RespError(Exception):
    pass


class DisqueRespClient(Client):
    """Queue client over the RESP plane with the disque error
    discipline (disque.clj:152-166): connection refusal before a
    request is sent is a definite :fail; a timeout or mid-flight reset
    on addjob/getjob is :info (the daemon may have processed it —
    getjob POPS under this at-least-once model, so it mutates too).
    The connection re-dials lazily after any failure — a restarted
    daemon kills live sockets."""

    def __init__(self, timeout: float = 0.5):
        self.timeout = timeout
        self.node = None
        self.host = None
        self.port = None
        self._conn = None

    def setup(self, test, node):
        from urllib.parse import urlparse

        from .etcd import RESP_OFFSET
        cl = DisqueRespClient(self.timeout)
        cl.node = node
        urls = test.get("client_urls") or {}
        u = urlparse(urls.get(node, f"http://{node}:2379"))
        cl.host, cl.port = u.hostname, (u.port or 2379) + RESP_OFFSET
        return cl

    def _connection(self) -> RespConnection:
        if self._conn is None:
            self._conn = RespConnection(self.host, self.port,
                                        self.timeout)
        return self._conn

    def _drop(self):
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def _ack(self, conn, job_id):
        """ACKJOB after a received GETJOB reply. The pop already
        happened server-side (at-least-once model: ack is a no-op), so
        an ack failure never makes the dequeue indeterminate — swallow
        it and just re-dial next op."""
        try:
            conn.cmd("ACKJOB", job_id)
        except (socket.timeout, TimeoutError, ConnectionError, OSError):
            self._drop()

    def invoke(self, test, op):
        f = op["f"]
        try:
            conn = self._connection()
        except (ConnectionError, OSError) as e:
            # Refused/unreachable before anything was sent: definite
            # no-op.
            self._drop()
            return {**op, "type": "fail", "error": str(e)}
        try:
            if f == "enqueue":
                conn.cmd("ADDJOB", "jepsen", op["value"], 0)
                return {**op, "type": "ok"}
            if f == "dequeue":
                got = conn.cmd("GETJOB", "NOHANG", "FROM", "jepsen")
                if got is None:
                    return {**op, "type": "fail", "error": "empty"}
                _q, _id, body = got[0]
                self._ack(conn, _id)
                return {**op, "type": "ok", "value": int(body)}
            if f == "drain":
                vs = []
                while True:
                    try:
                        got = conn.cmd("GETJOB", "NOHANG", "FROM",
                                       "jepsen")
                    except (socket.timeout, TimeoutError,
                            ConnectionError, OSError):
                        # Elements already received are determinate;
                        # discarding them would count every one as a
                        # false lost. The unobserved tail stays
                        # indeterminate either way.
                        self._drop()
                        return {**op, "type": "ok", "value": vs,
                                "error": "partial drain"}
                    if got is None:
                        break
                    vs.append(int(got[0][2]))
                    self._ack(conn, got[0][1])
                return {**op, "type": "ok", "value": vs}
            raise ValueError(f"unknown op {f}")
        except (socket.timeout, TimeoutError):
            self._drop()
            return {**op, "type": "info", "error": "timeout"}
        except (ConnectionError, OSError) as e:
            # Mid-flight reset on a mutating command: indeterminate.
            self._drop()
            return {**op, "type": "info", "error": str(e)}


def disque_test(data_plane: str = "resp", **opts) -> dict:
    """The queue+drain workload (disque.clj:121-213) in local mode.
    ``data_plane="resp"`` (default) speaks the disque RESP command
    subset over a raw socket — the reference's actual wire protocol
    shape; "http" keeps the casd HTTP emulation."""
    if data_plane == "resp":
        opts["casd_resp"] = True
        client = DisqueRespClient(opts.get("client_timeout", 0.5))
    else:
        client = QueueClient(opts.get("client_timeout", 0.5))
    return service_test("disque", client, queue_workload(opts), **opts)
