"""Disque suite: total-queue accounting on the Disque job queue.

Mirrors the reference suite (disque/src/jepsen/disque.clj): build from
source on the node (git clone + make at a pinned rev, 40-53), deploy
the config file (55-62), start under start-stop-daemon with a pidfile
(72-92), join every node to the primary via ``disque cluster meet
<primary-ip> 7711`` (94-104), and stop/wipe with killall + data rm
(106-119). The workload (disque.clj:121-213) is the queue/total-queue
family with a drain phase — shared with the rabbitmq module here — run
against casd's queue endpoints in local mode.
"""
from __future__ import annotations

from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..os_impl import debian
from ..runtime import primary, synchronize
from .local_common import service_test
from .rabbitmq import QueueClient, queue_workload

GIT_URL = "https://github.com/antirez/disque.git"
DIR = "/opt/disque"
DATA_DIR = "/var/lib/disque"
PIDFILE = "/var/run/disque.pid"
BINARY = f"{DIR}/src/disque-server"
CONTROL = f"{DIR}/src/disque"
CONFIG_FILE = f"{DIR}/disque.conf"
LOG_FILE = f"{DATA_DIR}/log"
PORT = 7711

# The reference's resources/disque.conf with %DATA_DIR% substituted
# (disque.clj:55-62).
CONFIG = "\n".join([
    f"port {PORT}",
    f"dir {DATA_DIR}",
    "appendonly yes",
])


class DisqueDB(DB):
    """Source-built Disque cluster (disque.clj:40-119)."""

    def __init__(self, version: str = "8a9290c"):
        self.version = version

    def setup(self, test, node):
        with c.su():
            debian.install(["git-core", "build-essential"])
            with c.cd("/opt"):
                if not cu.exists("disque"):
                    c.exec_("git", "clone", GIT_URL)
            with c.cd(DIR):
                c.exec_("git", "pull")
                c.exec_("git", "reset", "--hard", self.version)
                c.exec_("make")
            c.exec_("echo", CONFIG, lit(">"), CONFIG_FILE)
            c.exec_("mkdir", "-p", DATA_DIR)
            cu.start_daemon(
                {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
                BINARY, CONFIG_FILE)
        # Everyone meets the primary (disque.clj:94-104).
        synchronize(test)
        p = primary(test)
        if node != p:
            out = c.exec_(CONTROL, "-p", str(PORT), "cluster", "meet",
                          net_helpers.ip(str(p)), str(PORT))
            assert out.strip() == "OK", out

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "killall", "-9", "disque-server")
            cu.meh(c.exec_, "rm", "-rf", PIDFILE)
            cu.meh(c.exec_, "rm", "-rf", lit(f"{DATA_DIR}/*"), LOG_FILE)

    def log_files(self, test, node):
        return [LOG_FILE]


def disque_test(**opts) -> dict:
    """The queue+drain workload (disque.clj:121-213) in local mode
    against casd's queue endpoints."""
    return service_test(
        "disque",
        QueueClient(opts.get("client_timeout", 0.5)),
        queue_workload(opts), **opts)
