"""Shared scaffold for the coordination-service suites.

The reference covers its checker families with per-database suites
(hazelcast lock/queue/ids, aerospike counter, rabbitmq queue+drain,
elasticsearch set). Those servers are JVM artifacts this environment
can't run; what the suites actually prove — each checker family
detecting a seeded violation in histories recorded from *real
processes* under *real fault injection* — is preserved by driving the
same workloads against the compiled casd daemon's coordination
endpoints (resources/casd.cpp): lock, unique ids, counter, queue,
set. State is in-memory unless persisted, so the one kill+restart
nemesis seeds a genuine violation in every family.

Each suite module mirrors its reference counterpart's workload wiring
and cites it; real-server automation slots behind the DB protocol the
way EtcdDB does in the etcd suite.
"""
from __future__ import annotations

import json
import socket
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from .. import gen as g
from ..client import Client
from ..os_ import NoopOS
from ..testing import noop_test
from .etcd import (CasdDB, _casd_pauser, _casd_restarter, _with_nemesis,
                   derive_concurrency)


class ServiceClient(Client):
    """Base HTTP client for casd's coordination endpoints with the
    etcd-suite error discipline (etcd.clj:101-136): timeouts and
    mid-flight resets on mutating ops are :info (may have applied),
    definite rejections and read faults are :fail."""

    def __init__(self, timeout: float = 0.5):
        self.timeout = timeout
        self.base: Optional[str] = None
        self.node = None

    def setup(self, test, node):
        cl = type(self)(self.timeout)
        cl.node = node
        urls = test.get("client_urls") or {}
        cl.base = urls.get(node, f"http://{node}:2379")
        return cl

    def _req(self, method: str, path: str, form: Optional[dict] = None):
        url = f"{self.base}{path}"
        data = urllib.parse.urlencode(form).encode() \
            if form is not None else b""
        req = urllib.request.Request(
            url, data=data if method != "GET" else None, method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def guarded(self, op: dict, body, *, mutating: bool) -> dict:
        """Run ``body()`` (returns the completed op) under the standard
        exception -> fail/info mapping."""
        try:
            return body()
        except (socket.timeout, TimeoutError):
            return {**op, "type": "info" if mutating else "fail",
                    "error": "timeout"}
        except (ConnectionError, urllib.error.URLError) as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, (socket.timeout, TimeoutError)):
                return {**op, "type": "info" if mutating else "fail",
                        "error": "timeout"}
            if isinstance(reason, ConnectionRefusedError) or not mutating:
                return {**op, "type": "fail", "error": str(reason)}
            return {**op, "type": "info", "error": str(reason)}


def service_test(name: str, client: Client, workload: dict,
                 nemesis_mode: Optional[str] = None, persist: bool = True,
                 daemon_args=(), **opts) -> dict:
    """A local-mode suite test over real casd processes: same daemon
    deploy / start-stop-daemon / nemesis wiring as etcd.casd_test, with
    a suite-supplied client + workload (generator/checker/model).
    ``daemon_args``: extra casd flags (fault-seeding knobs like
    --bank-split-ms)."""
    n = opts.get("n_nodes", 1)
    nodes = [f"n{i + 1}" for i in range(n)]
    base = opts.get("base_port", 24790)
    ports = {node: base + i for i, node in enumerate(nodes)}
    db = CasdDB(persist=persist, extra_args=daemon_args)
    # Independent-keys workloads need concurrency to be a multiple of
    # the thread-group size; derive/validate once for every suite.
    tpk = opts.get("threads_per_key")
    if tpk:
        opts["concurrency"] = derive_concurrency(
            n, tpk, opts.get("concurrency"))
    test = noop_test(
        name=name,
        nodes=nodes,
        concurrency=opts.get("concurrency", 4),
        ssh={"local": True},
        os=NoopOS(),
        db=db,
        client=client,
        casd_ports=ports,
        casd_dir=opts.get("casd_dir", f"/tmp/jepsen/{name}"),
        # casd nodes don't replicate: every client routes to nodes[0]'s
        # store so the workload still reads as one shared object (etcd
        # suite rationale, etcd.casd_test); the pause/restart nemeses
        # default-target nodes[0] for the same reason.
        client_urls={node: f"http://127.0.0.1:{ports[nodes[0]]}"
                     for node in nodes},
        **workload)
    if nemesis_mode == "pause":
        test["nemesis"] = _casd_pauser(test)
    elif nemesis_mode == "restart":
        test["nemesis"] = _casd_restarter(db)
    nem_gen = None
    if test.get("nemesis"):
        import itertools
        cadence = opts.get("nemesis_cadence", 1.0)
        nem_gen = g.seq(itertools.cycle([g.sleep(cadence),
                                         {"type": "info", "f": "start"},
                                         g.sleep(cadence),
                                         {"type": "info", "f": "stop"}]))
    _with_nemesis(test, nem_gen, opts.get("time_limit", 30))
    test.update({k: v for k, v in opts.items()
                 if k not in ("n_nodes", "concurrency", "name")})
    return test
