"""Shared scaffold for the coordination-service suites.

The reference covers its checker families with per-database suites
(hazelcast lock/queue/ids, aerospike counter, rabbitmq queue+drain,
elasticsearch set). Those servers are JVM artifacts this environment
can't run; what the suites actually prove — each checker family
detecting a seeded violation in histories recorded from *real
processes* under *real fault injection* — is preserved by driving the
same workloads against the compiled casd daemon's coordination
endpoints (resources/casd.cpp): lock, unique ids, counter, queue,
set. State is in-memory unless persisted, so the one kill+restart
nemesis seeds a genuine violation in every family.

Each suite module mirrors its reference counterpart's workload wiring
and cites it; real-server automation slots behind the DB protocol the
way EtcdDB does in the etcd suite.
"""
from __future__ import annotations

import json
import socket
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from .. import gen as g
from ..client import Client
from ..os_ import NoopOS
from ..testing import noop_test
from .etcd import (CasdDB, _casd_pauser, _casd_restarter, _with_nemesis,
                   resolve_daemon_args,
                   derive_concurrency)


def final_read_deadline_s(test) -> float:
    """Retry budget for a workload's VERDICT-BEARING final reads
    (sets / dirty-read strong reads): they run in the post-time-limit
    final phase, possibly while a restart nemesis still has the
    daemon down, and a fast-failing connection error there turns the
    whole run into a "never read" unknown. Scaled from the test's OWN
    knobs — several nemesis cycles plus several client timeouts — not
    a fixed sleep, so slow hosts that stretch every phase stretch the
    deadline with it (the r13 deflake discipline)."""
    cadence = float(test.get("nemesis_cadence") or 1.0)
    timeout = float(test.get("client_timeout") or 0.5)
    return max(5.0, 4 * cadence + 10 * timeout)


class ServiceClient(Client):
    """Base HTTP client for casd's coordination endpoints with the
    etcd-suite error discipline (etcd.clj:101-136): timeouts and
    mid-flight resets on mutating ops are :info (may have applied),
    definite rejections and read faults are :fail.

    ``retrying(test, body)`` runs a read body under the
    final-read-deadline retry loop: transport faults retry until the
    deadline (an HTTPError is a real server answer and propagates) —
    the final-phase read primitive."""

    def retrying(self, test, body):
        import time as _time
        deadline = _time.monotonic() + final_read_deadline_s(test)
        while True:
            try:
                return body()
            except urllib.error.HTTPError:
                raise               # a real server answer
            except (ConnectionError, OSError, urllib.error.URLError):
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(0.05)

    def __init__(self, timeout: float = 0.5):
        self.timeout = timeout
        self.base: Optional[str] = None
        self.node = None

    def setup(self, test, node):
        cl = type(self)(self.timeout)
        cl.node = node
        urls = test.get("client_urls") or {}
        cl.base = urls.get(node, f"http://{node}:2379")
        return cl

    def _req(self, method: str, path: str, form: Optional[dict] = None):
        url = f"{self.base}{path}"
        data = urllib.parse.urlencode(form).encode() \
            if form is not None else b""
        req = urllib.request.Request(
            url, data=data if method != "GET" else None, method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def guarded(self, op: dict, body, *, mutating: bool) -> dict:
        """Run ``body()`` (returns the completed op) under the standard
        exception -> fail/info mapping."""
        try:
            return body()
        except (socket.timeout, TimeoutError):
            return {**op, "type": "info" if mutating else "fail",
                    "error": "timeout"}
        except (ConnectionError, urllib.error.URLError) as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, (socket.timeout, TimeoutError)):
                return {**op, "type": "info" if mutating else "fail",
                        "error": "timeout"}
            if isinstance(reason, ConnectionRefusedError) or not mutating:
                return {**op, "type": "fail", "error": str(reason)}
            return {**op, "type": "info", "error": str(reason)}


# The reference's named skew magnitudes (cockroach nemesis.clj:257-269:
# small/subcritical/critical are within/at/over the HLC max-offset;
# big/huge pair with `slowing`). Values in ms; the bumper applies them
# as negative offsets (backwards skew is what regresses an oracle).
SKEWS = {"small": 100, "subcritical": 200, "critical": 250,
         "big": 500, "huge": 5000}


def _clock_curl(t, node, **form) -> None:
    """POST the daemon's /ctl/clock admin seam from ON the node (rides
    the control plane, like the real clock tools in nemesis/time.py)."""
    from ..control.core import exec_star
    port = t["casd_ports"][node]
    data = "&".join(f"{k}={v}" for k, v in form.items())
    exec_star(f"curl -sf -X POST -d {data} "
              f"http://127.0.0.1:{port}/ctl/clock >/dev/null")


def _clock_reset(t, node) -> str:
    _clock_curl(t, node, set_ms=0)
    return "reset"


def _casd_clock_bumper(offset_ms: int = -60000, targeter=None,
                       skew: Optional[str] = None):
    """Bump the targeted daemon's wall clock by offset_ms at :start
    (or by a named reference magnitude via ``skew``), reset at :stop —
    the local-mode analog of bump-time on a node's real clock
    (cockroach nemesis.clj:233-255's bump-time; the C tool path is
    nemesis/time.py)."""
    from ..nemesis import core as nem
    if skew is not None:
        offset_ms = -SKEWS[skew]

    def start(t, node):
        _clock_curl(t, node, delta_ms=offset_ms)
        return f"bumped {offset_ms}ms"

    return nem.node_start_stopper(targeter or (lambda nodes: nodes[0]),
                                  start, _clock_reset)


def _casd_clock_strober(delta_ms: int = 200, period_ms: int = 10,
                        duration_s: float = 2.0, targeter=None):
    """Flip the daemon's clock between +delta and normal every period
    for duration (strobe-time semantics, nemesis.clj:202-230 /
    resources/strobe-time.c), as one blocking node-side loop."""
    from ..control.core import exec_star
    from ..nemesis import core as nem

    flips = max(1, int(duration_s * 1000 / (2 * period_ms)))

    def start(t, node):
        port = t["casd_ports"][node]
        url = f"http://127.0.0.1:{port}/ctl/clock"
        exec_star(
            f"for i in $(seq {flips}); do "
            f"curl -sf -X POST -d set_ms={delta_ms} {url} >/dev/null; "
            f"sleep {period_ms / 1000}; "
            f"curl -sf -X POST -d set_ms=0 {url} >/dev/null; "
            f"sleep {period_ms / 1000}; done")
        return f"strobed {flips}x{delta_ms}ms"

    return nem.node_start_stopper(targeter or (lambda nodes: nodes[0]),
                                  start, _clock_reset)


def service_test(name: str, client: Client, workload: dict,
                 nemesis_mode: Optional[str] = None, persist: bool = True,
                 daemon_args=(), **opts) -> dict:
    """A local-mode suite test over real casd processes: same daemon
    deploy / start-stop-daemon / nemesis wiring as etcd.casd_test, with
    a suite-supplied client + workload (generator/checker/model).
    ``daemon_args``: extra casd flags (fault-seeding knobs like
    --bank-split-ms)."""
    n = opts.get("n_nodes", 1)
    nodes = [f"n{i + 1}" for i in range(n)]
    base = opts.get("base_port", 24790)
    ports = {node: base + i for i, node in enumerate(nodes)}
    db = CasdDB(persist=persist,
                extra_args=resolve_daemon_args(daemon_args, opts),
                resp=bool(opts.get("casd_resp")))
    # Independent-keys workloads need concurrency to be a multiple of
    # the thread-group size; derive/validate once for every suite.
    tpk = opts.get("threads_per_key")
    if tpk:
        opts["concurrency"] = derive_concurrency(
            n, tpk, opts.get("concurrency"))
    test = noop_test(
        name=name,
        nodes=nodes,
        concurrency=opts.get("concurrency", 4),
        ssh={"local": True},
        os=NoopOS(),
        db=db,
        client=client,
        casd_ports=ports,
        casd_dir=opts.get("casd_dir", f"/tmp/jepsen/{name}"),
        # casd nodes don't replicate: every client routes to nodes[0]'s
        # store so the workload still reads as one shared object (etcd
        # suite rationale, etcd.casd_test); the pause/restart nemeses
        # default-target nodes[0] for the same reason.
        client_urls={node: f"http://127.0.0.1:{ports[nodes[0]]}"
                     for node in nodes},
        **workload)
    if nemesis_mode == "pause":
        test["nemesis"] = _casd_pauser(test)
    elif nemesis_mode == "restart":
        test["nemesis"] = _casd_restarter(db)
    elif nemesis_mode == "clock":
        test["nemesis"] = _casd_clock_bumper(
            opts.get("clock_offset_ms", -60000),
            skew=opts.get("clock_skew"))
    elif nemesis_mode == "strobe":
        test["nemesis"] = _casd_clock_strober(
            opts.get("strobe_delta_ms", 200),
            opts.get("strobe_period_ms", 10),
            opts.get("strobe_duration_s", 2.0))
    nem_gen = None
    if test.get("nemesis"):
        import itertools
        cadence = opts.get("nemesis_cadence", 1.0)
        nem_gen = g.seq(itertools.cycle([g.sleep(cadence),
                                         {"type": "info", "f": "start"},
                                         g.sleep(cadence),
                                         {"type": "info", "f": "stop"}]))
    _with_nemesis(test, nem_gen, opts.get("time_limit", 30))
    test.update({k: v for k, v in opts.items()
                 if k not in ("n_nodes", "concurrency", "name")})
    return test
