"""Elasticsearch-family suite: set workload.

Mirrors the reference's set test
(elasticsearch/src/jepsen/system/elasticsearch.clj:204-253): concurrent
adds of distinct integers, then one final read of the whole set,
checked by the set checker's lost/unexpected/recovered accounting
(checker.clj:131-178).

Local mode drives casd's /set endpoints; a state-wiping restart loses
acknowledged elements — the seeded ``lost`` violation. ``EsDB`` is the
real-cluster automation (tarball install + elasticsearch.yml templating
+ daemon start with a green-health wait, core.clj:212-296), behind the
DB protocol and command-stream tested like EtcdDB.
"""
from __future__ import annotations

import json
import threading

from .. import gen as g
from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..ops.folds import set_checker_tpu
from ..os_impl import debian
from ..utils.core import majority
from .local_common import ServiceClient, service_test

ES_USER = "elasticsearch"
ES_DIR = "/opt/elasticsearch"
ES_PIDFILE = "/tmp/elasticsearch.pid"
ES_LOG = f"{ES_DIR}/logs/stdout.log"
CLUSTER = "jepsen"


def elasticsearch_yml(node, test: dict) -> str:
    """The reference's resources/elasticsearch.yml with $CLUSTER/$NAME/
    $N/$MAJORITY/$HOSTS substituted (core.clj:221-238)."""
    nodes = test.get("nodes") or []
    hosts = json.dumps([net_helpers.ip(str(n)) for n in nodes])
    return "\n".join([
        f"cluster.name: {CLUSTER}",
        f"node.name: {node}",
        f"gateway.expected_nodes: {len(nodes)}",
        f"gateway.recover_after_nodes: {majority(len(nodes))}",
        f"discovery.zen.minimum_master_nodes: {majority(len(nodes))}",
        f"discovery.zen.ping.unicast.hosts: {hosts}",
        "network.host: 0.0.0.0",
    ])


class EsDB(DB):
    """Tarball-installed Elasticsearch cluster (core.clj:212-296):
    jdk + dedicated user + install_archive, yml templating, daemon
    start under the es user with a cluster-health wait, teardown =
    stop + data wipe + log truncation."""

    def __init__(self, tarball_url: str):
        self.tarball_url = tarball_url

    def setup(self, test, node):
        with c.su():
            debian.install_jdk()
            cu.ensure_user(ES_USER)
            cu.install_archive(self.tarball_url, ES_DIR)
            c.exec_("chown", "-R", f"{ES_USER}:{ES_USER}", ES_DIR)
            c.exec_("echo", elasticsearch_yml(node, test), lit(">"),
                    f"{ES_DIR}/config/elasticsearch.yml")
            c.exec_("sysctl", "-w", "vm.max_map_count=262144")
        with c.cd(ES_DIR), c.sudo(ES_USER):
            c.exec_("mkdir", "-p", f"{ES_DIR}/logs")
            cu.start_daemon(
                {"logfile": ES_LOG, "pidfile": ES_PIDFILE,
                 "chdir": ES_DIR},
                "bin/elasticsearch")
        # wait for green (core.clj:247-261's `wait`).
        cu.await_cmd(
            "curl -sf 'http://localhost:9200/_cluster/health"
            "?wait_for_status=green&timeout=1s' >/dev/null",
            "elasticsearch-green")

    def teardown(self, test, node):
        with c.su():
            cu.stop_daemon(ES_PIDFILE, "java")
            c.exec_("rm", "-rf", lit(f"{ES_DIR}/data/*"))
            for log_ in self.log_files(test, node):
                cu.meh(c.exec_, "truncate", "--size", "0", log_)

    def log_files(self, test, node):
        return [ES_LOG, f"{ES_DIR}/logs/{CLUSTER}.log"]


class SetClient(ServiceClient):
    """add / read over /set/<name>."""

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "add":
                self._req("POST", "/set/jepsen",
                          {"op": "add", "v": op["value"]})
                return {**op, "type": "ok"}
            if f == "read":
                r = self._req("GET", "/set/jepsen")
                return {**op, "type": "ok",
                        "value": [int(v) for v in r["vs"]]}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "add")


class _AddGen(g.Generator):
    """Consecutive-int adds (each element attempted once)."""

    def __init__(self):
        self._i = -1
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        with self._lock:
            self._i += 1
            return {"type": "invoke", "f": "add", "value": self._i}


def set_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 150)
    main = g.limit(n_ops, g.stagger(1 / 80, _AddGen()))
    final = g.once({"type": "invoke", "f": "read", "value": None})
    return {
        "generator": g.phases(main, final),
        "checker": set_checker_tpu(),
        "model": None,
    }


def elasticsearch_test(**opts) -> dict:
    return service_test("elasticsearch-set",
                        SetClient(opts.get("client_timeout", 0.5)),
                        set_workload(opts), **opts)
