"""Elasticsearch-family suite: set workload.

Mirrors the reference's set test
(elasticsearch/src/jepsen/system/elasticsearch.clj:204-253): concurrent
adds of distinct integers, then one final read of the whole set,
checked by the set checker's lost/unexpected/recovered accounting
(checker.clj:131-178).

Local mode drives casd's /set endpoints; a state-wiping restart loses
acknowledged elements — the seeded ``lost`` violation. Real-server
automation slots behind the DB protocol as in the etcd suite.
"""
from __future__ import annotations

import threading

from .. import gen as g
from ..ops.folds import set_checker_tpu
from .local_common import ServiceClient, service_test


class SetClient(ServiceClient):
    """add / read over /set/<name>."""

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "add":
                self._req("POST", "/set/jepsen",
                          {"op": "add", "v": op["value"]})
                return {**op, "type": "ok"}
            if f == "read":
                r = self._req("GET", "/set/jepsen")
                return {**op, "type": "ok",
                        "value": [int(v) for v in r["vs"]]}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "add")


class _AddGen(g.Generator):
    """Consecutive-int adds (each element attempted once)."""

    def __init__(self):
        self._i = -1
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        with self._lock:
            self._i += 1
            return {"type": "invoke", "f": "add", "value": self._i}


def set_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 150)
    main = g.limit(n_ops, g.stagger(1 / 80, _AddGen()))
    final = g.once({"type": "invoke", "f": "read", "value": None})
    return {
        "generator": g.phases(main, final),
        "checker": set_checker_tpu(),
        "model": None,
    }


def elasticsearch_test(**opts) -> dict:
    return service_test("elasticsearch-set",
                        SetClient(opts.get("client_timeout", 0.5)),
                        set_workload(opts), **opts)
