"""Elasticsearch-family suite: set workload.

Mirrors the reference's set test
(elasticsearch/src/jepsen/system/elasticsearch.clj:204-253): concurrent
adds of distinct integers, then one final read of the whole set,
checked by the set checker's lost/unexpected/recovered accounting
(checker.clj:131-178).

Local mode drives casd's /set endpoints; a state-wiping restart loses
acknowledged elements — the seeded ``lost`` violation. ``EsDB`` is the
real-cluster automation (tarball install + elasticsearch.yml templating
+ daemon start with a green-health wait, core.clj:212-296), behind the
DB protocol and command-stream tested like EtcdDB.
"""
from __future__ import annotations

import json
import threading

from .. import gen as g
from ..control import core as c
from ..control import net_helpers
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..ops.folds import set_checker_tpu
from ..os_impl import debian
from ..utils.core import majority
from .local_common import ServiceClient, service_test

ES_USER = "elasticsearch"
ES_DIR = "/opt/elasticsearch"
ES_PIDFILE = "/tmp/elasticsearch.pid"
ES_LOG = f"{ES_DIR}/logs/stdout.log"
CLUSTER = "jepsen"


def elasticsearch_yml(node, test: dict) -> str:
    """The reference's resources/elasticsearch.yml with $CLUSTER/$NAME/
    $N/$MAJORITY/$HOSTS substituted (core.clj:221-238)."""
    nodes = test.get("nodes") or []
    hosts = json.dumps([net_helpers.ip(str(n)) for n in nodes])
    return "\n".join([
        f"cluster.name: {CLUSTER}",
        f"node.name: {node}",
        f"gateway.expected_nodes: {len(nodes)}",
        f"gateway.recover_after_nodes: {majority(len(nodes))}",
        f"discovery.zen.minimum_master_nodes: {majority(len(nodes))}",
        f"discovery.zen.ping.unicast.hosts: {hosts}",
        "network.host: 0.0.0.0",
    ])


class EsDB(DB):
    """Tarball-installed Elasticsearch cluster (core.clj:212-296):
    jdk + dedicated user + install_archive, yml templating, daemon
    start under the es user with a cluster-health wait, teardown =
    stop + data wipe + log truncation."""

    def __init__(self, tarball_url: str):
        self.tarball_url = tarball_url

    def setup(self, test, node):
        with c.su():
            debian.install_jdk()
            cu.ensure_user(ES_USER)
            cu.install_archive(self.tarball_url, ES_DIR)
            c.exec_("chown", "-R", f"{ES_USER}:{ES_USER}", ES_DIR)
            c.exec_("echo", elasticsearch_yml(node, test), lit(">"),
                    f"{ES_DIR}/config/elasticsearch.yml")
            c.exec_("sysctl", "-w", "vm.max_map_count=262144")
        with c.cd(ES_DIR), c.sudo(ES_USER):
            c.exec_("mkdir", "-p", f"{ES_DIR}/logs")
            cu.start_daemon(
                {"logfile": ES_LOG, "pidfile": ES_PIDFILE,
                 "chdir": ES_DIR},
                "bin/elasticsearch")
        # wait for green (core.clj:247-261's `wait`).
        cu.await_cmd(
            "curl -sf 'http://localhost:9200/_cluster/health"
            "?wait_for_status=green&timeout=1s' >/dev/null",
            "elasticsearch-green")

    def teardown(self, test, node):
        with c.su():
            cu.stop_daemon(ES_PIDFILE, "java")
            c.exec_("rm", "-rf", lit(f"{ES_DIR}/data/*"))
            for log_ in self.log_files(test, node):
                cu.meh(c.exec_, "truncate", "--size", "0", log_)

    def log_files(self, test, node):
        return [ES_LOG, f"{ES_DIR}/logs/{CLUSTER}.log"]


class SetClient(ServiceClient):
    """add / read over /set/<name>. The read is the workload's FINAL
    verdict-bearing phase (final_generator) — it retries transport
    faults under the shared final-read deadline, so a restart-nemesis
    down-window costs latency, never the verdict (the r13 deflake)."""

    def invoke(self, test, op):
        f = op["f"]

        def read_once():
            r = self._req("GET", "/set/jepsen")
            return {**op, "type": "ok",
                    "value": [int(v) for v in r["vs"]]}

        def body():
            if f == "add":
                self._req("POST", "/set/jepsen",
                          {"op": "add", "v": op["value"]})
                return {**op, "type": "ok"}
            if f == "read":
                return self.retrying(test, read_once)
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "add")


class _AddGen(g.Generator):
    """Consecutive-int adds (each element attempted once)."""

    def __init__(self):
        self._i = -1
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        with self._lock:
            self._i += 1
            return {"type": "invoke", "f": "add", "value": self._i}


def set_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 150)
    main = g.limit(n_ops, g.stagger(1 / 80, _AddGen()))
    # Final read outside the time limit (the final_generator seam) —
    # the same r13 deflake as the cockroach sets suite: a stretched
    # add phase must cost ops, never the verdict-bearing read.
    final = g.once({"type": "invoke", "f": "read", "value": None})
    return {
        "generator": main,
        "final_generator": final,
        "checker": set_checker_tpu(),
        "model": None,
    }


# ------------------------------------------------------- dirty read
# elasticsearch/src/jepsen/elasticsearch/dirty_read.clj: writers insert
# consecutive values while readers chase the most recent in-flight
# write; a final strong-read phase reads the whole set from every
# worker. The checker's set algebra (dirty_read.clj:106-156): a read
# observing a value missing from every strong read is a DIRTY read
# (saw uncommitted state); an acked write missing from the strong
# union is LOST; strong readers disagreeing means divergent replicas.


class DirtyReadClient(ServiceClient):
    """write v / read v (did a specific recent write become visible?) /
    strong-read (full set) over /set (dirty_read.clj:32-84).

    Strong reads are the workload's verdict: all of them fire
    near-simultaneously at the final-phase barrier, so without a
    retry one restart-nemesis down-window fails every one of them at
    once and the checker can only say "no strong reads completed" —
    they ride the shared final-read deadline instead (the r13
    deflake)."""

    def invoke(self, test, op):
        f = op["f"]

        def read_set():
            r = self._req("GET", "/set/jepsen")
            return [int(v) for v in r["vs"]]

        def body():
            if f == "write":
                self._req("POST", "/set/jepsen",
                          {"op": "add", "v": op["value"]})
                return {**op, "type": "ok"}
            if f == "strong-read":
                return {**op, "type": "ok",
                        "value": self.retrying(test, read_set)}
            if f == "read":
                # Observed iff the chased value is present.
                if op["value"] in read_set():
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "not-found"}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "write")


class DirtyReadChecker:
    """dirty = ok reads whose value is in NO strong read; lost = ok
    writes missing from the strong union; strong readers must agree
    (dirty_read.clj:106-156)."""

    def check(self, test, model, history, opts=None) -> dict:
        writes, reads, strong = set(), set(), []
        for op in history:
            if op.type != "ok":
                continue
            if op.f == "write":
                writes.add(op.value)
            elif op.f == "read":
                reads.add(op.value)
            elif op.f == "strong-read" and isinstance(op.value, list):
                strong.append(set(op.value))
        if not strong:
            return {"valid": "unknown",
                    "error": "no strong reads completed"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        dirty = reads - on_some
        lost = writes - on_some
        agree = on_all == on_some
        return {"valid": bool(agree and not dirty and not lost),
                "nodes-agree": agree,
                "read-count": len(reads),
                "on-some-count": len(on_some),
                "dirty": sorted(dirty)[:10], "dirty-count": len(dirty),
                "lost": sorted(lost)[:10], "lost-count": len(lost),
                "some-lost-count": len(writes - on_all)}


class _RWGen(g.Generator):
    """First ``writers`` threads write consecutive values; the rest
    chase the most recent write (dirty_read.clj:160-189's rw-gen)."""

    def __init__(self, writers: int):
        self.writers = writers
        self._last = 0
        self._next = 0
        self._lock = threading.Lock()

    def op(self, test, process, ctx):
        if ctx.thread_of(process) < self.writers:
            with self._lock:
                v = self._next
                self._next += 1
                self._last = v
            return {"type": "invoke", "f": "write", "value": v}
        with self._lock:
            v = self._last
        return {"type": "invoke", "f": "read", "value": v}


def dirty_read_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 200)
    writers = opts.get("writers", 2)
    main = g.limit(n_ops, g.stagger(1 / 100, _RWGen(writers)))
    # One strong read per worker (the reference expects exactly
    # :concurrency of them, dirty_read.clj:135-140). Rides the
    # final_generator seam: the strong-read phase runs AFTER the
    # time-limited main phase, so a slow box that stretches the rw
    # walk past the budget still reads the final sets — the checker's
    # "no strong reads completed" unknown is reserved for genuinely
    # read-less histories, not scheduler weather (the same r13
    # deflake as the cockroach sets suite).
    final = g.each(lambda: g.once({"type": "invoke", "f": "strong-read",
                                   "value": None}))
    return {
        "generator": main,
        "final_generator": final,
        "checker": DirtyReadChecker(),
        "model": None,
    }


def dirty_read_test(name: str = "elasticsearch-dirty", **opts) -> dict:
    return service_test(name,
                        DirtyReadClient(opts.get("client_timeout", 0.5)),
                        dirty_read_workload(opts), **opts)


def elasticsearch_test(workload: str = "set", **opts) -> dict:
    """Workload dispatch (set — system/elasticsearch.clj:204-253; dirty
    — elasticsearch/dirty_read.clj)."""
    if workload == "dirty":
        return dirty_read_test(**opts)
    return service_test("elasticsearch-set",
                        SetClient(opts.get("client_timeout", 0.5)),
                        set_workload(opts), **opts)
