"""RethinkDB suite: document CAS register.

Mirrors the reference suite (rethinkdb/src/jepsen/rethinkdb.clj):
install from the vendor apt repo with a pinned version, optionally
faketime-wrapping the binary (52-66); write the instance config with
one ``join=<node>:29015`` line per node plus server-name/tag (68-88);
``service rethinkdb start`` (89-95); teardown stops the service,
kills stragglers, and wipes the data dir (db at 122-142). The workload
(document_cas.clj) is the CAS-register family over a document table,
run against casd in local mode with per-key independence.
"""
from __future__ import annotations

from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from .. import faketime
from ..os_impl import debian
from .etcd import EtcdClient, workload as register_workload
from .local_common import service_test

REPO_LINE = "deb http://download.rethinkdb.com/apt jessie main"
KEY_URL = "https://download.rethinkdb.com/apt/pubkey.gpg"
CONF = "/etc/rethinkdb/instances.d/jepsen.conf"
LOG_FILE = "/var/log/rethinkdb"
DATA_DIR = "/var/lib/rethinkdb"

# The reference's resources/jepsen.conf baseline: bind everywhere,
# fixed ports, our log file.
BASE_CONF = "\n".join([
    "bind=all",
    "driver-port=28015",
    "cluster-port=29015",
    f"log-file={LOG_FILE}",
])


def join_lines(test: dict) -> str:
    """One join line per node (rethinkdb.clj:68-74)."""
    return "\n".join(f"join={n}:29015" for n in (test.get("nodes") or []))


class RethinkDB(DB):
    """Apt-repo RethinkDB cluster (rethinkdb.clj:52-142). ``rate``
    applies the suite's faketime clock-rate skew to the server binary
    (rethinkdb.clj:62: faketime-wrapper!)."""

    def __init__(self, version: str = "2.3.4~0jessie",
                 rate: float | None = None):
        self.version = version
        self.rate = rate

    def setup(self, test, node):
        with c.su():
            debian.add_repo("rethinkdb", REPO_LINE)
            c.exec_star(f"wget -qO - {KEY_URL} | apt-key add -")
            debian.install([f"rethinkdb={self.version}"])
            if self.rate is not None:
                faketime.wrap("/usr/bin/rethinkdb", self.rate)
            c.exec_("touch", LOG_FILE)
            c.exec_("chown", "rethinkdb:rethinkdb", LOG_FILE)
            c.exec_("echo",
                    BASE_CONF + "\n\n" + join_lines(test) + "\n\n"
                    + f"server-name={node}\nserver-tag={node}\n",
                    lit(">"), CONF)
            c.exec_("service", "rethinkdb", "start")

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "service", "rethinkdb", "stop")
            cu.grepkill("rethinkdb")
            c.exec_("rm", "-rf", lit(f"{DATA_DIR}/*"), LOG_FILE)

    def log_files(self, test, node):
        return [LOG_FILE]


def rethinkdb_test(**opts) -> dict:
    """The document-CAS register workload (document_cas.clj) in local
    mode against casd."""
    opts.setdefault("threads_per_key", 2)
    return service_test(
        "rethinkdb",
        EtcdClient(opts.get("client_timeout", 0.5)),
        register_workload(opts), **opts)
