"""etcd suite: CAS-register linearizability over independent keys.

Mirrors the reference's canonical modern suite
(etcd/src/jepsen/etcd.clj): DB automation at 45-99 (tarball install,
daemon start with cluster flags, log collection), the HTTP client with
exception→fail/info mapping at 101-136, and the workload wiring at
149-180 (independent concurrent keys × CAS mix, linearizable checker +
timeline + perf, partitioning nemesis). North-star config #1.

Two DBs share the client and workload:

  * ``EtcdDB``  — real etcd on cluster nodes over SSH (v2 keys API).
  * ``CasdDB``  — the in-CI stand-in: jepsen_tpu/resources/casd.cpp, a
    compiled CAS server speaking the same v2 subset, deployed by
    compiling the shipped source on the "node" (the same
    upload-and-gcc discipline as the clock tools, nemesis/time.clj
    pattern), started under start-stop-daemon with a pidfile. In-memory
    by default — kill+restart wipes state, which the checker must
    catch; ``persist=True`` adds a replayed write log, making restarts
    harmless.
"""
from __future__ import annotations

import json
import logging
import socket
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path
from typing import Optional

from .. import gen as g
from .. import independent
from ..checkers.core import compose
from ..checkers.linearizable import linearizable
from ..checkers.perf import perf
from ..checkers.timeline import html_timeline
from ..client import Client
from ..control import core as c
from ..control import util as cu
from ..db import DB
from ..models.core import cas_register
from ..nemesis import core as nem
from ..os_ import NoopOS
from ..testing import noop_test

log = logging.getLogger("jepsen.etcd")

# A read observing "key absent" is a REAL observation, distinct from the
# no-observation None (which the model treats as a wildcard, the knossos
# nil-read convention). Without the distinction a state-wiping restart
# is invisible: every post-wipe read would look like an unknown read.
# The workload model starts as CASRegister(ABSENT) so pre-first-write
# reads linearize, and a post-write ABSENT read is a violation.
ABSENT = "absent"

ETCD_VERSION = "v3.5.12"
ETCD_URL = ("https://github.com/etcd-io/etcd/releases/download/"
            f"{ETCD_VERSION}/etcd-{ETCD_VERSION}-linux-amd64.tar.gz")
DIR = "/opt/etcd"


def client_url(test: dict, node) -> str:
    """Base URL of a node's client API. Suites populate
    test["client_urls"]; defaults to the node's 2379."""
    urls = test.get("client_urls") or {}
    return urls.get(node, f"http://{node}:2379")


def peer_url(node) -> str:
    return f"http://{node}:2380"


class EtcdDB(DB):
    """Real etcd on a cluster node (etcd.clj:45-99): install the release
    tarball, start with static initial-cluster bootstrap, tear down by
    killing and wiping the data dir."""

    def setup(self, test, node):
        with c.su():
            cu.install_archive(test.get("etcd_url", ETCD_URL), DIR)
            initial = ",".join(f"{n}={peer_url(n)}"
                               for n in test["nodes"])
            cu.start_daemon(
                {"logfile": f"{DIR}/etcd.log", "pidfile": f"{DIR}/etcd.pid",
                 "chdir": DIR},
                f"{DIR}/etcd",
                "--name", str(node),
                "--listen-peer-urls", peer_url(node),
                "--listen-client-urls", f"http://0.0.0.0:2379",
                "--advertise-client-urls", client_url(test, node),
                "--initial-advertise-peer-urls", peer_url(node),
                "--initial-cluster-state", "new",
                "--initial-cluster", initial,
                "--enable-v2")

    def teardown(self, test, node):
        with c.su():
            cu.grepkill("etcd")
            c.exec_("rm", "-rf", DIR)

    def log_files(self, test, node):
        return [f"{DIR}/etcd.log"]


def resolve_daemon_args(daemon_args, opts: dict) -> list:
    """Suite-level fault knobs that translate to casd flags.
    ``wipe_after_ops``: deterministic seeded data loss (casd
    --wipe-after-ops) — the violation no longer depends on a nemesis
    kill racing the workload phase under scheduler load."""
    args = list(daemon_args)
    if opts.get("wipe_after_ops"):
        args += ["--wipe-after-ops", str(opts["wipe_after_ops"])]
    return args


# RESP data plane rides the HTTP port + this offset (casd --resp-port).
RESP_OFFSET = 1000


class CasdDB(DB):
    """The local-mode stand-in: compile the shipped casd source on the
    node and run it under start-stop-daemon. One instance per logical
    node, ports from test["casd_ports"]; ``resp=True`` additionally
    serves the disque RESP plane on port + RESP_OFFSET."""

    def __init__(self, persist: bool = True, extra_args=(),
                 resp: bool = False):
        self.persist = persist
        self.extra_args = list(extra_args)
        self.resp = resp

    def _dir(self, test, node) -> str:
        return f"{test.get('casd_dir', '/tmp/jepsen/casd')}/{node}"

    def setup(self, test, node):
        d = self._dir(test, node)
        src = Path(__file__).resolve().parent.parent / "resources/casd.cpp"
        c.exec_("mkdir", "-p", d)
        c.upload(str(src), f"{d}/casd.cpp")
        if not cu.exists(f"{d}/casd"):
            c.exec_("g++", "-O2", "-std=c++17", "-o", f"{d}/casd",
                    f"{d}/casd.cpp", "-lpthread")
        # Stale harness bookkeeping from a run that crashed before
        # teardown must not leak into this one: casd-wipe.state records
        # "the seeded wipe already fired", so loading a leftover copy
        # silently disarms a deterministic seeded-violation test (the
        # run shapes like a pass). A stale pidfile can likewise confuse
        # start-stop-daemon. The WAL is left alone — persist=True means
        # surviving restarts is the point.
        c.exec_("rm", "-f", f"{d}/casd-wipe.state", f"{d}/casd.pid")
        port = test["casd_ports"][node]
        args = ["--port", port]
        if self.persist:
            args += ["--persist", f"{d}/casd.wal"]
        if self.resp:
            args += ["--resp-port", str(port + RESP_OFFSET)]
        args += self.extra_args
        cu.start_daemon(
            {"logfile": f"{d}/casd.log", "pidfile": f"{d}/casd.pid",
             "chdir": d},
            f"{d}/casd", *args)
        # Wait for the listener before declaring the node up.
        cu.await_cmd(
            f"curl -sf http://127.0.0.1:{port}/health >/dev/null",
            "casd", tries=50, sleep=0.1)

    def teardown(self, test, node):
        d = self._dir(test, node)
        cu.stop_daemon(f"{d}/casd.pid")
        c.exec_("rm", "-rf", d)

    def log_files(self, test, node):
        return [f"{self._dir(test, node)}/casd.log"]


class EtcdClient(Client):
    """CAS register over the v2 keys HTTP API with the reference's
    exception mapping (etcd.clj:101-136): indeterminate network faults
    on mutating ops are :info, definite rejections and safe read faults
    are :fail."""

    def __init__(self, timeout: float = 1.0):
        self.timeout = timeout
        self.node = None
        self.base = None

    def setup(self, test, node):
        cl = EtcdClient(self.timeout)
        cl.node = node
        cl.base = client_url(test, node)
        return cl

    # -- HTTP ----------------------------------------------------------
    def _req(self, method: str, key, form: Optional[dict] = None):
        url = f"{self.base}/v2/keys/jepsen-{key}"
        data = urllib.parse.urlencode(form).encode() if form else None
        req = urllib.request.Request(url, data=data, method=method)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read().decode())

    def invoke(self, test, op):
        f = op["f"]
        k, v = op["value"] if independent.is_kv(op["value"]) \
            else (None, op["value"])

        def done(typ, value=v, **extra):
            out = {**op, "type": typ, **extra}
            out["value"] = independent.tuple_(k, value) if k is not None \
                else value
            return out

        try:
            if f == "read":
                try:
                    body = self._req("GET", k)
                    return done("ok", int(body["node"]["value"]))
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        return done("ok", ABSENT)
                    raise
            elif f == "write":
                self._req("PUT", k, {"value": v})
                return done("ok")
            elif f == "cas":
                old, new = v
                try:
                    self._req("PUT", k, {"value": new, "prevValue": old})
                    return done("ok")
                except urllib.error.HTTPError as e:
                    if e.code == 412:          # compare failed
                        return done("fail", error="cas-mismatch")
                    if e.code == 404:          # key absent: definitely no-op
                        return done("fail", error="key-absent")
                    raise
            raise ValueError(f"unknown op {f}")
        except (socket.timeout, TimeoutError) as e:
            # Timeout: a read definitely observed nothing (fail is safe);
            # a mutation may or may not have applied (info).
            typ = "fail" if f == "read" else "info"
            return done(typ, error="timeout")
        except (ConnectionError, urllib.error.URLError) as e:
            reason = getattr(e, "reason", e)
            if isinstance(reason, (socket.timeout, TimeoutError)):
                typ = "fail" if f == "read" else "info"
                return done(typ, error="timeout")
            # Connection refused/reset before a response: refused means
            # the request never reached a server (fail); reset
            # mid-flight on a mutation is indeterminate.
            if isinstance(reason, ConnectionRefusedError) or f == "read":
                return done("fail", error=str(reason))
            return done("info", error=str(reason))


def workload(test_opts: dict) -> dict:
    """Independent-keys CAS workload + checker composition
    (etcd.clj:149-180): n threads per key, a stagger'd read/write/cas
    mix limited per key, checked by the device-batched linearizable
    checker with timeline + perf."""
    per_key = test_opts.get("ops_per_key", 300)
    threads = test_opts.get("threads_per_key", 5)
    backend = test_opts.get("checker_backend", "tpu")

    generator = independent.concurrent_generator(
        threads, iter(range(10**9)),
        lambda k: g.limit(per_key,
                          g.stagger(1 / 50,
                                    g.cas_gen(test_opts.get(
                                        "n_values", 5)))))
    checker = compose({
        "independent": independent.batch_checker()
        if backend == "tpu" else independent.checker(linearizable()),
        "timeline": html_timeline(),
        "perf": perf(),
    })
    return {"generator": generator, "checker": checker,
            "model": cas_register(ABSENT),
            # Serializable record of the workload's analysis constants:
            # the replay seam (cli recheck / jepsen_tpu.recheck) reads
            # these from the stored test.json instead of trusting
            # operator flags.
            "invariants": {"independent": True,
                           "threads_per_key": threads,
                           "ops_per_key": per_key,
                           "n_values": test_opts.get("n_values", 5)}}


def _with_nemesis(test: dict, nemesis_gen, time_limit: float) -> None:
    """Route client ops vs the nemesis schedule and bound the run —
    the time limit must cover the (infinite) nemesis stream too, or
    the nemesis worker never exits (the reference wraps the combined
    generator: etcd.clj:167-179).

    A workload may hand over a ``final_generator`` (the reference's
    :final-generator idiom): client ops that run AFTER the bounded
    main phase — outside the time limit — so a run whose main phase
    the scheduler stretched past the budget still performs its final
    reads instead of flaking with "never read" verdicts on slow hosts
    (the checker can't judge what was never observed). The final
    phase synchronizes over CLIENT threads only; the nemesis stream
    stays bounded by its own time limit, so the nemesis worker exits
    while the clients read."""
    client_gen = test["generator"]
    final = test.pop("final_generator", None)
    bounded = g.time_limit(time_limit, client_gen)
    if final is not None:
        bounded = g.phases(bounded, final)
    if nemesis_gen is not None:
        test["generator"] = g.nemesis(
            g.time_limit(time_limit, nemesis_gen), bounded)
    else:
        test["generator"] = g.clients(bounded)


def etcd_test(**opts) -> dict:
    """The real-cluster etcd test (etcd.clj:149-180): 5 nodes, random
    half partitions on a 5s cadence."""
    nodes = opts.get("nodes", ["n1", "n2", "n3", "n4", "n5"])
    test = noop_test(
        name="etcd",
        nodes=nodes,
        concurrency=opts.get("concurrency", 3 * len(nodes)),
        db=EtcdDB(),
        client=EtcdClient(),
        nemesis=nem.partition_random_halves(),
        **workload(opts))
    import itertools
    _with_nemesis(test,
                  g.seq(itertools.cycle([{"type": "info", "f": "start"},
                                         g.sleep(5),
                                         {"type": "info", "f": "stop"},
                                         g.sleep(5)])),
                  opts.get("time_limit", 30))
    test.update({k: v for k, v in opts.items()
                 if k not in ("nodes", "concurrency")})
    return test


def derive_concurrency(n_nodes: int, threads_per_key: int,
                       concurrency: Optional[int]) -> int:
    """The concurrent generator requires concurrency to be a multiple
    of threads_per_key; derive the default (>= 2n workers) and validate
    explicit pairs up front rather than at first poll."""
    tpk = threads_per_key
    if concurrency is None:
        return tpk * max(1, -(-2 * n_nodes // tpk))
    if concurrency % tpk != 0:
        raise ValueError(
            f"concurrency ({concurrency}) must be a multiple of "
            f"threads_per_key ({tpk})")
    return concurrency


def _casd_pauser(test, targeter=None) -> Client:
    """SIGSTOP/SIGCONT one node's casd (hammer-time semantics,
    nemesis.clj:227-241, targeted per port so only that logical node
    stalls). casd nodes don't replicate and every client routes to
    nodes[0], so the default target is the node clients actually talk
    to — a random target would mostly stall daemons with no traffic,
    making seeded violations unobservable."""
    def start(test, node):
        # casd may be absent mid-restart; pkill's exit 1 must not abort
        # the nemesis worker.
        c.exec_star(f"pkill -STOP -f '[c]asd --port "
                    f"{test['casd_ports'][node]}' || true")
        return "paused"

    def stop(test, node):
        c.exec_star(f"pkill -CONT -f '[c]asd --port "
                    f"{test['casd_ports'][node]}' || true")
        return "resumed"

    return nem.node_start_stopper(targeter or (lambda nodes: nodes[0]),
                                  start, stop)


def _casd_restarter(db: CasdDB, targeter=None) -> Client:
    """Kill -9 one node's casd and restart it — with persist=False this
    wipes the register, a real consistency violation the checker must
    flag. Default target = nodes[0], the node clients talk to (see
    _casd_pauser).

    Kill and restart happen within ONE nemesis op so the node's dead
    window is just the daemon's own startup time; independent keys are
    short-lived, and a long dead window would let every key die (as
    fail/info timeouts) before the wipe becomes observable, hiding the
    violation from the checker."""
    def start(test, node):
        c.exec_star(f"pkill -9 -f '[c]asd --port "
                    f"{test['casd_ports'][node]}' || true")
        db.setup(test, node)
        return "killed+restarted"

    def stop(test, node):
        return "nop"

    return nem.node_start_stopper(targeter or (lambda nodes: nodes[0]),
                                  start, stop)


def casd_test(nemesis_mode: str = "pause", persist: bool = True,
              daemon_args=(), **opts) -> dict:
    """The local-mode etcd-suite test: N real casd processes on
    localhost ports, driven through the LocalTransport. ``nemesis_mode``:
    "pause" (SIGSTOP hammer), "restart" (kill -9 + restart), or None.

    casd nodes don't replicate (real etcd does), so with n_nodes > 1
    every client routes to the primary's store for correctness while the
    other nodes still run real daemons — multi-node setup/teardown/log
    paths get exercised without pretending unreplicated stores form one
    register. Single-node tests exercise the fault semantics."""
    n = opts.get("n_nodes", 1)
    nodes = [f"n{i + 1}" for i in range(n)]
    base = opts.get("base_port", 23790)
    ports = {node: base + i for i, node in enumerate(nodes)}
    db = CasdDB(persist=persist,
                extra_args=resolve_daemon_args(daemon_args, opts))
    concurrency = derive_concurrency(n, opts.get("threads_per_key", 5),
                                     opts.get("concurrency"))
    test = noop_test(
        name=opts.get("name", "etcd-casd"),
        nodes=nodes,
        concurrency=concurrency,
        ssh={"local": True},
        os=NoopOS(),
        db=db,
        client=EtcdClient(timeout=opts.get("client_timeout", 0.5)),
        casd_ports=ports,
        casd_dir=opts.get("casd_dir", "/tmp/jepsen/casd"),
        client_urls={node: f"http://127.0.0.1:{ports[nodes[0]]}"
                     for node in nodes},
        **workload(opts))
    if nemesis_mode == "pause":
        test["nemesis"] = _casd_pauser(test)
    elif nemesis_mode == "restart":
        test["nemesis"] = _casd_restarter(db)
    nem_gen = None
    if test.get("nemesis"):
        import itertools
        cadence = opts.get("nemesis_cadence", 2.0)
        nem_gen = g.seq(itertools.cycle([g.sleep(cadence),
                                         {"type": "info", "f": "start"},
                                         g.sleep(cadence),
                                         {"type": "info", "f": "stop"}]))
    _with_nemesis(test, nem_gen, opts.get("time_limit", 30))
    test.update({k: v for k, v in opts.items()
                 if k not in ("n_nodes", "concurrency", "name")})
    return test
