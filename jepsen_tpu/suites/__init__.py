"""Per-database test suites (the reference's L8 layer).

Each suite module exposes ``*_test(...)`` builders returning test maps
that runtime.run executes — DB automation, wire-protocol clients,
workloads, nemesis wiring, and checker composition for one real system
(reference: etcd/, hazelcast/, aerospike/, rabbitmq/, cockroachdb/, ...
each an independent Leiningen project over the jepsen library).

Suites run in two modes:

  * **cluster** — real nodes over SSH, the reference's deployment shape;
  * **local**   — the same suite against real local processes through
    the LocalTransport (control.core), with per-node ports/directories.
    This is the CI mode: daemons really start, get SIGSTOPped, killed,
    and restarted, and the checkers really catch the violations those
    faults induce.
"""
