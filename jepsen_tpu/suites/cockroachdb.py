"""Cockroachdb-family suite: the bank serializability workload and the
nemesis-product sweep runner — north-star config #5.

Mirrors the reference's richest suite:

  * bank workload + balance-sum checker
    (cockroachdb/src/jepsen/cockroach/bank.clj:112-143): concurrent
    transfers between accounts plus whole-bank reads; under
    serializable isolation every read's balances sum to the invariant
    total and never go negative — a short sum is read skew, a negative
    balance a lost update.
  * product sweep runner (cockroachdb/src/jepsen/cockroach/runner.clj:
    94-138): build and run one test per combination of named option
    lists (the reference sweeps nemesis x nemesis2 pairs), aggregate
    validity across the product.

Local mode drives casd's /bank endpoints. The daemon's transfers are
atomic by default; the ``--bank-split-ms N`` flag releases the store
lock between debit and credit for N ms — a REAL isolation bug
(mid-transfer state observable), which is the seeded violation the
checker must catch. Real-CockroachDB automation (JDBC client +
cluster install, cockroach.clj:136-164) slots behind the DB protocol
as in the etcd suite.
"""
from __future__ import annotations

import itertools
import urllib.error

from .. import gen as g
from ..checkers.core import Checker, merge_valid
from .local_common import ServiceClient, service_test


class BankClient(ServiceClient):
    """transfer / read over /bank/<name> (bank.clj:55-110 client). The
    first client setup initializes the accounts (idempotent server
    side)."""

    def __init__(self, timeout: float = 0.5, accounts: int = 5,
                 balance: int = 10):
        super().__init__(timeout)
        self.accounts = accounts
        self.balance = balance

    def setup(self, test, node):
        cl = super().setup(test, node)
        cl.accounts = self.accounts
        cl.balance = self.balance
        cl._req("POST", "/bank/jepsen",
                {"op": "init", "accounts": cl.accounts,
                 "balance": cl.balance})
        return cl

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "transfer":
                v = op["value"]
                try:
                    self._req("POST", "/bank/jepsen",
                              {"op": "transfer", "from": v["from"],
                               "to": v["to"], "amount": v["amount"]})
                    return {**op, "type": "ok"}
                except urllib.error.HTTPError as e:
                    if e.code == 409:     # insufficient funds: no-op
                        return {**op, "type": "fail",
                                "error": "insufficient"}
                    if e.code == 404:
                        return {**op, "type": "fail",
                                "error": "no-such-account"}
                    raise
            if f == "read":
                r = self._req("GET", "/bank/jepsen")
                balances = {int(k): int(vv)
                            for k, vv in r["balances"].items()}
                return {**op, "type": "ok", "value": balances}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "transfer")


class BankChecker(Checker):
    """Balance-sum invariant over ok reads (bank.clj:112-143): every
    read must see exactly ``accounts`` balances summing to the constant
    total, none negative."""

    def __init__(self, accounts: int = 5, balance: int = 10):
        self.accounts = accounts
        self.total = accounts * balance

    def check(self, test, model, history, opts=None) -> dict:
        bad = []
        n_reads = 0
        for op in history:
            if not (op.type == "ok" and op.f == "read"
                    and isinstance(op.value, dict)):
                continue
            n_reads += 1
            balances = op.value
            err = None
            if len(balances) != self.accounts:
                err = f"{len(balances)} accounts, expected {self.accounts}"
            elif sum(balances.values()) != self.total:
                err = (f"total {sum(balances.values())}, "
                       f"expected {self.total}")
            elif any(b < 0 for b in balances.values()):
                err = "negative balance"
            if err:
                bad.append({"op": op.to_dict(), "error": err})
        if n_reads == 0:
            return {"valid": "unknown", "error": "bank was never read"}
        return {"valid": not bad, "reads": n_reads,
                "bad-reads": bad[:10],
                "bad-read-count": len(bad)}


def _transfer_gen(accounts: int, max_amount: int):
    def gen(test, process, ctx):
        if ctx.rng.random() < 0.6:
            a = ctx.rng.randrange(accounts)
            b = ctx.rng.randrange(accounts - 1)
            if b >= a:
                b += 1
            return {"type": "invoke", "f": "transfer",
                    "value": {"from": a, "to": b,
                              "amount": 1 + ctx.rng.randrange(max_amount)}}
        return {"type": "invoke", "f": "read", "value": None}

    return gen


def bank_workload(opts: dict) -> dict:
    accounts = opts.get("accounts", 5)
    balance = opts.get("balance", 10)
    n_ops = opts.get("n_ops", 300)
    return {
        "generator": g.limit(n_ops, g.stagger(
            1 / 100, _transfer_gen(accounts,
                                   opts.get("max_amount", 5)))),
        "checker": BankChecker(accounts, balance),
        "model": None,
    }


def bank_test(split_ms: int = 0, **opts) -> dict:
    """The local bank test; ``split_ms > 0`` seeds the non-atomic
    transfer race the checker must catch."""
    daemon_args = (["--bank-split-ms", str(split_ms)] if split_ms else [])
    return service_test(
        "cockroach-bank",
        BankClient(opts.get("client_timeout", 0.5),
                   opts.get("accounts", 5), opts.get("balance", 10)),
        bank_workload(opts), daemon_args=daemon_args, **opts)


class TimestampClient(ServiceClient):
    """Monotonic-timestamp grants over /ts/next (the role of cockroach's
    hybrid-logical-clock reads in monotonic.clj)."""

    def invoke(self, test, op):
        def body():
            r = self._req("POST", "/ts/next")
            return {**op, "type": "ok", "value": int(r["ts"])}

        return self.guarded(op, body, mutating=True)


class MonotonicChecker(Checker):
    """Real-time monotonicity (cockroachdb/src/jepsen/cockroach/
    monotonic.clj:163+): an ok-granted timestamp must exceed every
    timestamp granted by ops that COMPLETED before this op was invoked.
    Concurrent grants may complete out of order — that's fine; going
    backwards across a real-time boundary is the violation (a reset
    clock/oracle)."""

    def check(self, test, model, history, opts=None) -> dict:
        cur_max = None
        floor: dict = {}     # process -> max completed ts at its invoke
        bad = []
        n = 0
        for op in history:
            if op.f != "ts" or not op.is_client:
                continue
            if op.type == "invoke":
                floor[op.process] = cur_max
            elif op.type == "ok":
                lo = floor.pop(op.process, None)
                n += 1
                if lo is not None and op.value is not None \
                        and op.value <= lo:
                    bad.append({"op": op.to_dict(), "floor": lo})
                if cur_max is None or (op.value is not None
                                       and op.value > cur_max):
                    cur_max = op.value
        return {"valid": not bad, "grants": n,
                "regressions": bad[:10], "regression-count": len(bad)}


def monotonic_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 200)
    return {
        "generator": g.limit(n_ops, g.stagger(
            1 / 100, lambda test, process, ctx: {"type": "invoke",
                                                 "f": "ts",
                                                 "value": None})),
        "checker": MonotonicChecker(),
        "model": None,
    }


def monotonic_test(**opts) -> dict:
    """Timestamp-oracle monotonicity test; a state-wiping restart
    resets the oracle, and post-restart grants regress below completed
    pre-restart grants — the seeded violation."""
    return service_test(
        "cockroach-monotonic",
        TimestampClient(opts.get("client_timeout", 0.5)),
        monotonic_workload(opts), **opts)


def product_sweep(build_test, dimensions: dict, run_fn=None) -> dict:
    """Run one test per combination of named option lists and aggregate
    validity — the reference's nemesis-product runner
    (runner.clj:94-138), generalized to arbitrary option dimensions.

    ``build_test(**combo)`` must return a test map. Returns
    {"valid", "runs": {label: results}}; the label encodes the combo.
    """
    if run_fn is None:
        from ..runtime import run as run_fn
    keys = list(dimensions)
    runs = {}
    for combo in itertools.product(*(dimensions[k] for k in keys)):
        combo_opts = dict(zip(keys, combo))
        label = ",".join(f"{k}={v}" for k, v in combo_opts.items())
        runs[label] = run_fn(build_test(**combo_opts))["results"]
    return {
        "valid": merge_valid(r["valid"] for r in runs.values())
        if runs else True,
        "runs": runs,
    }
