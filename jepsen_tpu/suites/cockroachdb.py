"""Cockroachdb-family suite: all seven reference workloads (bank,
multitable bank, register, sets, sequential, comments, Adya G2, plus
the monotonic-timestamp oracle) and the nemesis-product sweep runner —
north-star config #5.

Mirrors the reference's richest suite:

  * bank workload + balance-sum checker
    (cockroachdb/src/jepsen/cockroach/bank.clj:112-143): concurrent
    transfers between accounts plus whole-bank reads; under
    serializable isolation every read's balances sum to the invariant
    total and never go negative — a short sum is read skew, a negative
    balance a lost update.
  * product sweep runner (cockroachdb/src/jepsen/cockroach/runner.clj:
    94-138): build and run one test per combination of named option
    lists (the reference sweeps nemesis x nemesis2 pairs), aggregate
    validity across the product.

Local mode drives casd's /bank endpoints. The daemon's transfers are
atomic by default; the ``--bank-split-ms N`` flag releases the store
lock between debit and credit for N ms — a REAL isolation bug
(mid-transfer state observable), which is the seeded violation the
checker must catch. ``CockroachAuto`` is the real-cluster automation
(cockroach/auto.clj:142-217: tarball install under a dedicated user +
the on-node bumptime clock tool, start-stop-daemon with the linearizable
/ max-offset env and a --join list on non-primaries, kill + store
wipe), behind the DB protocol and command-stream tested like EtcdDB.
"""
from __future__ import annotations

import itertools
import threading
import time
import urllib.error

from .. import gen as g
from .. import independent
from ..checkers.core import Checker, merge_valid
from ..control import core as c
from ..control import util as cu
from ..control.core import lit
from ..db import DB
from ..nemesis import time as nt
from ..os_impl import debian
from ..runtime import primary
from .local_common import ServiceClient, service_test

CR_USER = "cockroach"
CR_PATH = "/opt/cockroach"
CR_BIN = f"{CR_PATH}/cockroach"
CR_STORE = f"{CR_PATH}/cockroach-data"
CR_PIDFILE = f"{CR_PATH}/pid"
CR_LOGS = f"{CR_PATH}/logs"
CR_ERRLOG = f"{CR_LOGS}/cockroach.stderr"
CR_VERLOG = f"{CR_LOGS}/version.txt"


class CockroachAuto(DB):
    """Real-cluster CockroachDB automation (cockroach/auto.clj).

    setup = install (142-155: deps, dedicated user, tarball, log dir,
    chown, on-node bumptime build per install-bumptime! at 122-140 via
    the shared clock-tool path) + version log (179-183) + start
    (192-206): start-stop-daemon --chuid cockroach with
    COCKROACH_LINEARIZABLE/COCKROACH_MAX_OFFSET env, ``start
    --insecure``, and ``--join=<other nodes>`` on every non-primary.
    teardown = kill + store wipe (auto.clj:207-213; cockroach.clj's
    wipe)."""

    def __init__(self, tarball: str | None = None,
                 insecure: bool = True):
        self.tarball = tarball
        self.insecure = insecure

    def setup(self, test, node):
        with c.su():
            debian.install(["tcpdump", "ntpdate"])
            cu.ensure_user(CR_USER)
            cu.install_archive(test.get("tarball", self.tarball), CR_PATH)
            c.exec_("mkdir", "-p", CR_PATH, CR_LOGS)
            c.exec_("chown", "-R", f"{CR_USER}:{CR_USER}", CR_PATH)
        nt.install()                     # bumptime/strobe clock tools
        with c.sudo(CR_USER):
            c.exec_star(f"{CR_BIN} version > {CR_VERLOG} 2>&1")
            flags = ["start"]
            if self.insecure:
                flags.append("--insecure")
            if node != primary(test):
                others = ",".join(str(n) for n in test["nodes"]
                                  if n != node)
                flags.append(f"--join={others}")
            linearizable = "true" if test.get("linearizable") else "false"
            c.exec_("env",
                    f"COCKROACH_LINEARIZABLE={linearizable}",
                    "COCKROACH_MAX_OFFSET=250ms",
                    "start-stop-daemon", "--start", "--background",
                    "--make-pidfile", "--remove-pidfile",
                    "--pidfile", CR_PIDFILE, "--no-close",
                    "--chuid", CR_USER, "--chdir", CR_PATH,
                    "--exec", CR_BIN, "--",
                    *flags, "--logtostderr",
                    lit(">>"), CR_ERRLOG, lit("2>&1"))

    def teardown(self, test, node):
        with c.su():
            cu.meh(c.exec_, "killall", "-9", "cockroach")
            c.exec_("rm", "-rf", CR_STORE, CR_PIDFILE)

    def log_files(self, test, node):
        return [CR_ERRLOG, CR_VERLOG]


class BankClient(ServiceClient):
    """transfer / read over /bank/<name> (bank.clj:55-110 client). The
    first client setup initializes the accounts (idempotent server
    side)."""

    def __init__(self, timeout: float = 0.5, accounts: int = 5,
                 balance: int = 10):
        super().__init__(timeout)
        self.accounts = accounts
        self.balance = balance

    def setup(self, test, node):
        cl = super().setup(test, node)
        cl.accounts = self.accounts
        cl.balance = self.balance
        cl._req("POST", "/bank/jepsen",
                {"op": "init", "accounts": cl.accounts,
                 "balance": cl.balance})
        return cl

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "transfer":
                v = op["value"]
                try:
                    self._req("POST", "/bank/jepsen",
                              {"op": "transfer", "from": v["from"],
                               "to": v["to"], "amount": v["amount"]})
                    return {**op, "type": "ok"}
                except urllib.error.HTTPError as e:
                    if e.code == 409:     # insufficient funds: no-op
                        return {**op, "type": "fail",
                                "error": "insufficient"}
                    if e.code == 404:
                        return {**op, "type": "fail",
                                "error": "no-such-account"}
                    raise
            if f == "read":
                r = self._req("GET", "/bank/jepsen")
                balances = {int(k): int(vv)
                            for k, vv in r["balances"].items()}
                return {**op, "type": "ok", "value": balances}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "transfer")


class BankChecker(Checker):
    """Balance-sum invariant over ok reads (bank.clj:112-143): every
    read must see exactly ``accounts`` balances summing to the constant
    total, none negative."""

    def __init__(self, accounts: int = 5, balance: int = 10):
        self.accounts = accounts
        self.total = accounts * balance

    def check(self, test, model, history, opts=None) -> dict:
        bad = []
        n_reads = 0
        for op in history:
            if not (op.type == "ok" and op.f == "read"
                    and isinstance(op.value, dict)):
                continue
            n_reads += 1
            balances = op.value
            err = None
            if len(balances) != self.accounts:
                err = f"{len(balances)} accounts, expected {self.accounts}"
            elif sum(balances.values()) != self.total:
                err = (f"total {sum(balances.values())}, "
                       f"expected {self.total}")
            elif any(b < 0 for b in balances.values()):
                err = "negative balance"
            if err:
                bad.append({"op": op.to_dict(), "error": err})
        if n_reads == 0:
            return {"valid": "unknown", "error": "bank was never read"}
        return {"valid": not bad, "reads": n_reads,
                "bad-reads": bad[:10],
                "bad-read-count": len(bad)}


def _transfer_gen(accounts: int, max_amount: int):
    def gen(test, process, ctx):
        if ctx.rng.random() < 0.6:
            a = ctx.rng.randrange(accounts)
            b = ctx.rng.randrange(accounts - 1)
            if b >= a:
                b += 1
            return {"type": "invoke", "f": "transfer",
                    "value": {"from": a, "to": b,
                              "amount": 1 + ctx.rng.randrange(max_amount)}}
        return {"type": "invoke", "f": "read", "value": None}

    return gen


def bank_workload(opts: dict) -> dict:
    accounts = opts.get("accounts", 5)
    balance = opts.get("balance", 10)
    n_ops = opts.get("n_ops", 300)
    return {
        "generator": g.limit(n_ops, g.stagger(
            1 / 100, _transfer_gen(accounts,
                                   opts.get("max_amount", 5)))),
        "checker": BankChecker(accounts, balance),
        "model": None,
        # The invariant constants land in test.json (checker objects
        # are nonserializable), so `recheck --model bank` re-derives
        # the SAME invariant the run was checked under instead of
        # trusting hardcoded operator flags (VERDICT r5 weak #6).
        "invariants": {"family": "bank", "accounts": accounts,
                       "balance": balance},
    }


def bank_service_test(name: str, daemon_args=(), *, split_ms: int = 0,
                      **opts) -> dict:
    """A local-mode bank-family test (shared by the galera / percona /
    mysql-cluster / mongodb-transfer / postgres-rds suites, which all
    run this workload family against their own DB automation).
    ``split_ms > 0`` seeds the non-atomic transfer race."""
    if split_ms:
        daemon_args = list(daemon_args) + ["--bank-split-ms",
                                           str(split_ms)]
    return service_test(
        name,
        BankClient(opts.get("client_timeout", 0.5),
                   opts.get("accounts", 5), opts.get("balance", 10)),
        bank_workload(opts), daemon_args=daemon_args, **opts)


def bank_test(split_ms: int = 0, **opts) -> dict:
    """The local bank test; ``split_ms > 0`` seeds the non-atomic
    transfer race the checker must catch."""
    return bank_service_test("cockroach-bank", split_ms=split_ms, **opts)


class TimestampClient(ServiceClient):
    """Monotonic-timestamp grants over /ts/next (the role of cockroach's
    hybrid-logical-clock reads in monotonic.clj)."""

    def invoke(self, test, op):
        def body():
            r = self._req("POST", "/ts/next")
            return {**op, "type": "ok", "value": int(r["ts"])}

        return self.guarded(op, body, mutating=True)


class MonotonicChecker(Checker):
    """Real-time monotonicity (cockroachdb/src/jepsen/cockroach/
    monotonic.clj:163+): an ok-granted timestamp must exceed every
    timestamp granted by ops that COMPLETED before this op was invoked.
    Concurrent grants may complete out of order — that's fine; going
    backwards across a real-time boundary is the violation (a reset
    clock/oracle)."""

    def check(self, test, model, history, opts=None) -> dict:
        cur_max = None
        floor: dict = {}     # process -> max completed ts at its invoke
        bad = []
        n = 0
        for op in history:
            if op.f != "ts" or not op.is_client:
                continue
            if op.type == "invoke":
                floor[op.process] = cur_max
            elif op.type == "ok":
                lo = floor.pop(op.process, None)
                n += 1
                if lo is not None and op.value is not None \
                        and op.value <= lo:
                    bad.append({"op": op.to_dict(), "floor": lo})
                if cur_max is None or (op.value is not None
                                       and op.value > cur_max):
                    cur_max = op.value
        return {"valid": not bad, "grants": n,
                "regressions": bad[:10], "regression-count": len(bad)}


def monotonic_workload(opts: dict) -> dict:
    n_ops = opts.get("n_ops", 200)
    return {
        "generator": g.limit(n_ops, g.stagger(
            1 / 100, lambda test, process, ctx: {"type": "invoke",
                                                 "f": "ts",
                                                 "value": None})),
        "checker": MonotonicChecker(),
        "model": None,
    }


def monotonic_test(**opts) -> dict:
    """Timestamp-oracle monotonicity test. Violation seams: a
    state-wiping restart resets the counter oracle (post-restart grants
    regress below completed pre-restart grants); with ``ts_wall=True``
    the oracle trusts the daemon's wall clock, and the clock/strobe
    nemeses (nemesis_mode="clock"/"strobe") skew it backwards — the
    local composition of clock skew against a time-sensitive workload
    (cockroach monotonic.clj x nemesis.clj:233-269)."""
    if opts.get("ts_wall"):
        opts["daemon_args"] = list(opts.get("daemon_args", ())) + \
            ["--ts-wall"]
    opts.pop("ts_wall", None)
    return service_test(
        "cockroach-monotonic",
        TimestampClient(opts.get("client_timeout", 0.5)),
        monotonic_workload(opts), **opts)


# ------------------------------------------------------------- register

def register_workload(opts: dict) -> dict:
    """Independent-keys CAS register with the reference's generator
    shape (register.clj:85-103): per key, a reserved band of writer/cas
    threads vs readers, delay_til-aligned to provoke races, checked by
    the device-batched linearizable checker."""
    from .etcd import ABSENT
    from ..models.core import cas_register

    per_key = opts.get("ops_per_key", 60)
    tpk = opts.get("threads_per_key", 4)
    nv = opts.get("n_values", 5)

    def r(test, process, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    def w(test, process, ctx):
        return {"type": "invoke", "f": "write",
                "value": ctx.rng.randrange(nv)}

    def cas(test, process, ctx):
        return {"type": "invoke", "f": "cas",
                "value": [ctx.rng.randrange(nv), ctx.rng.randrange(nv)]}

    generator = independent.concurrent_generator(
        tpk, itertools.count(1),
        lambda k: g.limit(per_key, g.stagger(
            1 / 50, g.delay_til(
                0.05, g.reserve(max(1, tpk // 2), g.mix([w, cas, cas]),
                                r)))))
    return {"generator": generator,
            "checker": independent.batch_checker(),
            "model": cas_register(ABSENT),
            "invariants": {"independent": True,
                           "threads_per_key": tpk,
                           "ops_per_key": per_key, "n_values": nv}}


def register_test(**opts) -> dict:
    from .etcd import EtcdClient
    opts.setdefault("threads_per_key", 4)
    return service_test("cockroach-register",
                        EtcdClient(timeout=opts.get("client_timeout", 0.5)),
                        register_workload(opts), **opts)


# ----------------------------------------------------------------- sets

class SetsClient(ServiceClient):
    """Blind adds + one final whole-set read over /set/jepsen
    (sets.clj:103-133's insert/select).

    The FINAL read (``op["final"]``) retries transport faults under
    the shared final-read deadline (local_common
    .final_read_deadline_s — scaled from the test's own cadence and
    timeout knobs, not a fixed sleep): it runs in the post-time-limit
    final phase, possibly right after a restart-nemesis kill, and the
    verdict hinges on it landing — one connection-refused during
    daemon startup must not turn the whole run into "Set was never
    read"."""

    def invoke(self, test, op):
        f = op["f"]

        def read_once():
            r = self._req("GET", "/set/jepsen")
            return {**op, "type": "ok",
                    "value": [int(v) for v in r["vs"]]}

        def body():
            if f == "add":
                self._req("POST", "/set/jepsen", {"v": op["value"]})
                return {**op, "type": "ok"}
            if f == "read":
                if not op.get("final"):
                    return read_once()
                return self.retrying(test, read_once)
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "add")


def sets_workload(opts: dict) -> dict:
    """Sequential-int adds, then a final read, checked by the cockroach
    sets fold (lost/unexpected/duplicate/revived, sets.clj:21-101).

    The final read rides the ``final_generator`` seam (local_common
    service_test / etcd._with_nemesis): it runs AFTER the time-limited
    main phase, outside the budget, so a slow host that stretches the
    add phase past the limit still reads the set — the checker's
    "Set was never read" unknown is reserved for genuinely read-less
    histories, not scheduler weather (the tier-1 deflake)."""
    from ..ops.folds import crdb_set_checker_tpu
    n_ops = opts.get("n_ops", 150)
    adds = g.seq({"type": "invoke", "f": "add", "value": i}
                 for i in itertools.count())
    main = g.limit(n_ops, g.stagger(1 / 100, adds))
    final = g.once({"type": "invoke", "f": "read", "value": None,
                    "final": True})
    return {"generator": main,
            "final_generator": final,
            "checker": crdb_set_checker_tpu(),
            "model": None}


def sets_test(**opts) -> dict:
    return service_test("cockroach-sets",
                        SetsClient(opts.get("client_timeout", 0.5)),
                        sets_workload(opts), **opts)


# ----------------------------------------------------------- sequential

SEQ_KEY_COUNT = 5


def subkeys(key_count: int, k) -> list:
    """The subkeys used for a given key, in write order
    (sequential.clj:43-46)."""
    return [f"{k}_{i}" for i in range(key_count)]


class SequentialClient(ServiceClient):
    """Writes insert a key's subkeys in order, each in its own request;
    reads fetch them in reverse order (sequential.clj:57-105). Client
    order vs store order: if a later subkey is visible, every earlier
    one must be too.

    Each subkey read retries transient transport faults (the
    reference's per-query with-txn-retry, sequential.clj:88-96): reads
    deliberately span multiple requests — NOT one transaction — so a
    read must survive a mid-read restart to witness the later-visible /
    earlier-missing state."""

    def __init__(self, timeout: float = 0.5,
                 key_count: int = SEQ_KEY_COUNT):
        super().__init__(timeout)
        self.key_count = key_count

    def setup(self, test, node):
        cl = super().setup(test, node)
        cl.key_count = self.key_count
        return cl

    def _get_retry(self, path: str, deadline: float):
        while True:
            try:
                return self._req("GET", path)
            except urllib.error.HTTPError:
                raise                       # a real server answer (404)
            except (ConnectionError, urllib.error.URLError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

    def invoke(self, test, op):
        f = op["f"]
        k = op["value"]
        ks = subkeys(self.key_count, k)

        def body():
            if f == "write":
                for sk in ks:
                    self._req("PUT", f"/v2/keys/seq-{sk}", {"value": sk})
                return {**op, "type": "ok"}
            if f == "read":
                out = []
                deadline = time.monotonic() + 2.0
                for sk in reversed(ks):
                    try:
                        r = self._get_retry(f"/v2/keys/seq-{sk}",
                                            deadline)
                        out.append(r["node"]["value"])
                    except urllib.error.HTTPError as e:
                        if e.code == 404:
                            out.append(None)
                        else:
                            raise
                return {**op, "type": "ok", "value": [k, out]}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "write")


def trailing_none(coll) -> bool:
    """A None anywhere after a non-None element (sequential.clj:150-153)
    — reads run newest-subkey-first, so this means a later write was
    visible without an earlier one."""
    it = iter(coll)
    for x in it:
        if x is not None:
            return any(y is None for y in it)
    return False


class SequentialChecker(Checker):
    """Counts all/some/none reads; trailing-None reads are the
    violations (sequential.clj:155-173)."""

    def __init__(self, key_count: int = SEQ_KEY_COUNT):
        self.key_count = key_count

    def check(self, test, model, history, opts=None) -> dict:
        reads = [op.value for op in history
                 if op.type == "ok" and op.f == "read"
                 and isinstance(op.value, list)]
        none = [r for r in reads if all(x is None for x in r[1])]
        some = [r for r in reads if any(x is None for x in r[1])]
        bad = [r for r in reads if trailing_none(r[1])]
        full = [r for r in reads
                if r[1] == list(reversed(subkeys(self.key_count, r[0])))]
        return {"valid": not bad,
                "all-count": len(full), "some-count": len(some),
                "none-count": len(none), "bad-count": len(bad),
                "bad": bad[:10]}


def sequential_workload(opts: dict) -> dict:
    """n writer threads emitting sequential keys; the rest read recently
    written keys (sequential.clj:107-137's writes/reads over a
    last-written buffer)."""
    n_writers = opts.get("n_writers", 2)
    n_ops = opts.get("n_ops", 120)
    key_count = opts.get("key_count", SEQ_KEY_COUNT)
    counter = itertools.count()
    last_written: list = []
    lock = threading.Lock()

    def writes(test, process, ctx):
        with lock:
            k = next(counter)
            last_written.append(k)
            del last_written[:-2 * n_writers]
        return {"type": "invoke", "f": "write", "value": k}

    def reads(test, process, ctx):
        with lock:
            if not last_written:
                k = 0
            else:
                k = ctx.rng.choice(last_written)
        return {"type": "invoke", "f": "read", "value": k}

    return {"generator": g.limit(n_ops, g.stagger(
                1 / 100, g.reserve(n_writers, writes, reads))),
            "checker": SequentialChecker(key_count),
            "model": None}


def sequential_test(**opts) -> dict:
    key_count = opts.get("key_count", SEQ_KEY_COUNT)
    return service_test(
        "cockroach-sequential",
        SequentialClient(opts.get("client_timeout", 0.5), key_count),
        sequential_workload(opts), **opts)


# ------------------------------------------------------------- comments

class CommentsClient(ServiceClient):
    """Blind inserts of globally-ordered ids per key; reads return every
    id visible for the key (comments.clj:42-86). Backed by a per-key
    casd set."""

    def invoke(self, test, op):
        f = op["f"]
        k, v = op["value"] if independent.is_kv(op["value"]) \
            else (None, op["value"])

        def done(typ, value=v, **extra):
            out = {**op, "type": typ, **extra}
            out["value"] = independent.tuple_(k, value) if k is not None \
                else value
            return out

        def body():
            if f == "write":
                self._req("POST", f"/set/comments-{k}", {"v": v})
                return done("ok")
            if f == "read":
                r = self._req("GET", f"/set/comments-{k}")
                return done("ok", sorted(int(x) for x in r["vs"]))
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "write")


class CommentsChecker(Checker):
    """Strict-serializability probe (comments.clj:88-147): replaying the
    history, every write's invoke records the set of writes already
    completed; a read that sees write w but misses a write completed
    before w's invoke witnesses T1 < T2 with only T2 visible."""

    def check(self, test, model, history, opts=None) -> dict:
        completed: set = set()
        expected: dict = {}
        errors = []
        for op in history:
            if op.f == "write":
                if op.type == "invoke":
                    expected[op.value] = frozenset(completed)
                elif op.type == "ok":
                    completed.add(op.value)
            elif op.f == "read" and op.type == "ok" \
                    and isinstance(op.value, list):
                seen = set(op.value)
                our_expected: set = set()
                for s in op.value:
                    our_expected |= expected.get(s, frozenset())
                missing = our_expected - seen
                if missing:
                    errors.append({"op": op.to_dict(),
                                   "missing": sorted(missing),
                                   "expected-count": len(our_expected)})
        return {"valid": not errors, "errors": errors[:10],
                "error-count": len(errors)}


def comments_workload(opts: dict) -> dict:
    n_threads = opts.get("threads_per_key", 2)
    per_key = opts.get("ops_per_key", 50)
    ids = itertools.count()
    lock = threading.Lock()

    def writes(test, process, ctx):
        with lock:
            i = next(ids)
        return {"type": "invoke", "f": "write", "value": i}

    def reads(test, process, ctx):
        return {"type": "invoke", "f": "read", "value": None}

    generator = independent.concurrent_generator(
        n_threads, itertools.count(1),
        lambda k: g.limit(per_key, g.stagger(1 / 100,
                                             g.mix([reads, writes]))))
    return {"generator": generator,
            "checker": independent.checker(CommentsChecker()),
            "model": None}


def comments_test(**opts) -> dict:
    opts.setdefault("threads_per_key", 2)
    return service_test("cockroach-comments",
                        CommentsClient(opts.get("client_timeout", 0.5)),
                        comments_workload(opts), **opts)


# ------------------------------------------------------ multitable bank

class MultiBankClient(ServiceClient):
    """One bank ("table") per account, single balance each
    (bank.clj:180-228 MultiBankClient): transfers move between banks
    atomically via casd's cross-bank op; reads snapshot every bank in
    one request."""

    def __init__(self, timeout: float = 0.5, accounts: int = 5,
                 balance: int = 10):
        super().__init__(timeout)
        self.accounts = accounts
        self.balance = balance

    def _bank(self, i) -> str:
        return f"acct{i}"

    def setup(self, test, node):
        cl = super().setup(test, node)
        cl.accounts = self.accounts
        cl.balance = self.balance
        for i in range(cl.accounts):
            cl._req("POST", f"/bank/{cl._bank(i)}",
                    {"op": "init", "accounts": 1, "balance": cl.balance})
        return cl

    def invoke(self, test, op):
        f = op["f"]

        def body():
            if f == "transfer":
                v = op["value"]
                try:
                    self._req("POST", "/bank/x",
                              {"op": "xtransfer",
                               "from": self._bank(v["from"]),
                               "to": self._bank(v["to"]),
                               "amount": v["amount"]})
                    return {**op, "type": "ok"}
                except urllib.error.HTTPError as e:
                    if e.code == 409:
                        return {**op, "type": "fail",
                                "error": "insufficient"}
                    if e.code == 404:
                        return {**op, "type": "fail",
                                "error": "no-such-bank"}
                    raise
            if f == "read":
                names = ",".join(self._bank(i)
                                 for i in range(self.accounts))
                r = self._req("POST", "/bank/x",
                              {"op": "xread", "banks": names})
                balances = {int(k[4:]): int(v)
                            for k, v in r["balances"].items()}
                return {**op, "type": "ok", "value": balances}
            raise ValueError(f"unknown op {f}")

        return self.guarded(op, body, mutating=f == "transfer")


def multibank_test(split_ms: int = 0, **opts) -> dict:
    """The multitable bank: same invariant and checker as bank, but
    every balance lives in its own bank object and transfers cross
    banks; ``split_ms`` seeds the cross-bank race."""
    daemon_args = (["--bank-split-ms", str(split_ms)] if split_ms else [])
    return service_test(
        "cockroach-multibank",
        MultiBankClient(opts.get("client_timeout", 0.5),
                        opts.get("accounts", 5), opts.get("balance", 10)),
        bank_workload(opts), daemon_args=daemon_args, **opts)


# ------------------------------------------------------------------- g2

class G2Client(ServiceClient):
    """The G2 anti-dependency-cycle client (cockroach/adya.clj:24-84):
    an insert first predicate-reads both of the key's tables; if either
    is nonempty the other transaction already committed (:fail
    too-late), else insert into table a or b per the id pair. The
    read-then-insert pair is NOT atomic — exactly the window a
    serializable store must close. ``serialized=True`` closes it with a
    per-key lock (the valid control)."""

    def __init__(self, timeout: float = 0.5, serialized: bool = False):
        super().__init__(timeout)
        self.serialized = serialized

    def setup(self, test, node):
        cl = super().setup(test, node)
        cl.serialized = self.serialized
        return cl

    def _vs(self, table, k) -> list:
        return self._req("GET", f"/set/g2{table}-{k}")["vs"]

    def invoke(self, test, op):
        k, v = op["value"]

        def txn():
            a_id, b_id = v
            if self._vs("a", k) or self._vs("b", k):
                return {**op, "type": "fail", "error": "too-late"}
            table, vid = ("a", a_id) if a_id is not None else ("b", b_id)
            self._req("POST", f"/set/g2{table}-{k}", {"v": vid})
            return {**op, "type": "ok"}

        def body():
            assert op["f"] == "insert"
            if not self.serialized:
                return txn()
            owner = f"p{op.get('process', '?')}"
            deadline = time.monotonic() + 2.0
            while True:
                try:
                    self._req("POST", f"/lock/g2-{k}",
                              {"op": "acquire", "owner": owner})
                    break
                except urllib.error.HTTPError as e:
                    # 409 held by OUR owner string: a lost acquire
                    # response — we do hold the lock; proceed.
                    if e.code == 409:
                        import json
                        try:
                            held = json.loads(
                                e.read().decode(errors="replace"))["held"]
                        except Exception:
                            held = None
                        if held == owner:
                            break
                        if time.monotonic() <= deadline:
                            time.sleep(0.002)
                            continue
                    raise
            try:
                return txn()
            finally:
                # Release must not starve later inserts on this key:
                # retry transport faults briefly; a committed insert's
                # verdict must not be downgraded by a flaky release.
                rel_deadline = time.monotonic() + 1.0
                while True:
                    try:
                        self._req("POST", f"/lock/g2-{k}",
                                  {"op": "release", "owner": owner})
                        break
                    except urllib.error.HTTPError:
                        break       # not holder: already released
                    except Exception:
                        if time.monotonic() > rel_deadline:
                            break
                        time.sleep(0.02)

        return self.guarded(op, body, mutating=True)


def g2_test(serialized: bool = False, **opts) -> dict:
    """Adya G2 over casd (jepsen/src/jepsen/adya.clj wired as
    cockroach/adya.clj does): pairs of concurrent inserts per key; at
    most one may commit. Unserialized inserts race between predicate
    read and insert — a REAL G2 anomaly the checker must catch;
    serialized=True is the anomaly-free control."""
    from ..adya import g2_checker, g2_gen
    opts.setdefault("threads_per_key", 2)
    workload = {"generator": g.limit(opts.get("n_ops", 60), g2_gen()),
                "checker": g2_checker(),
                "model": None}
    return service_test(
        "cockroach-g2",
        G2Client(opts.get("client_timeout", 0.5), serialized),
        workload, **opts)


# ------------------------------------------------------ workload registry

WORKLOADS = {
    "bank": bank_test,
    "multibank": multibank_test,
    "register": register_test,
    "sets": sets_test,
    "sequential": sequential_test,
    "comments": comments_test,
    "g2": g2_test,
    "monotonic": monotonic_test,
}


def cockroach_test(workload: str = "bank", **opts) -> dict:
    """Build one cockroach-family test by workload name — the suite's
    `--workload` dispatch (runner.clj:59-93's test-by-name routing over
    the seven reference workloads)."""
    builder = WORKLOADS.get(workload)
    if builder is None:
        raise ValueError(
            f"unknown cockroach workload {workload!r}; "
            f"one of {sorted(WORKLOADS)}")
    return builder(**opts)


def product_sweep(build_test, dimensions: dict, run_fn=None) -> dict:
    """Run one test per combination of named option lists and aggregate
    validity — the reference's nemesis-product runner
    (runner.clj:94-138), generalized to arbitrary option dimensions.

    ``build_test(**combo)`` must return a test map. Returns
    {"valid", "runs": {label: results}}; the label encodes the combo.
    """
    if run_fn is None:
        from ..runtime import run as run_fn
    keys = list(dimensions)
    runs = {}
    for combo in itertools.product(*(dimensions[k] for k in keys)):
        combo_opts = dict(zip(keys, combo))
        label = ",".join(f"{k}={v}" for k, v in combo_opts.items())
        runs[label] = run_fn(build_test(**combo_opts))["results"]
    return {
        "valid": merge_valid(r["valid"] for r in runs.values())
        if runs else True,
        "runs": runs,
    }
