"""Persistence: run directories, history/results files, latest symlinks.

Mirrors jepsen/src/jepsen/store.clj. Layout:

    store/<test-name>/<timestamp>/
        test.json       — the serializable slice of the test map
        history.txt     — human-readable tab-separated op log
        history.jsonl   — machine-readable history (codec.write_jsonl)
        results.json    — checker output
        jepsen.log      — per-run log file
        <node>/...      — snarfed db log files
    store/<test-name>/latest    → most recent run
    store/latest                → most recent run of any test

Persistence is two-phase like the reference's save-1!/save-2!
(store.clj:279-302): the history lands before analysis begins, so a
crashed checker still leaves a re-checkable run on disk; ``load``
rehydrates a stored run for re-analysis (store.clj:165-171) — the replay
seam the TPU batch checker consumes (load N histories, re-check on
device).
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from . import telemetry
from .history.codec import read_jsonl, write_jsonl, write_txt
from .history.ops import Op
from .history.wal import WAL_FILE

BASE = Path("store")

# Machine-form sidecar magic (history.cols.bin, Store._save_machine_form).
MACHINE_MAGIC = b"JTCOLS1\n"

# Chunk-journal header magic (ChunkJournal).
JOURNAL_MAGIC = "JTJRNL1"

# Test-map keys that are live objects, never serialized
# (store.clj:155-163 default-nonserializable-keys).
NONSERIALIZABLE_KEYS = {
    "db", "os", "net", "client", "nemesis", "checker", "model", "generator",
    "barrier", "clock", "rng", "sessions", "active_histories", "history",
    "results", "store_handle", "ssh", "wal",
}

# Campaign-checkpoint header magic (CampaignCheckpoint).
CAMPAIGN_MAGIC = "JTCAMP1"

# Online-checker namespace (jepsen_tpu.online): per-run artifacts the
# always-on daemon leaves beside the WAL. The journal gates interim
# prefix re-dispatch across daemon restarts; the verdict file is the
# durable final result (recheck-parity unit); the first-violation
# record is the "flag the first violating op seconds after it happens"
# artifact; the deferred mark makes an overload-paused tenant durable.
ONLINE_JOURNAL = "online.journal.jsonl"
ONLINE_VERDICT = "online-verdict.json"
ONLINE_DEFERRED = "online-deferred.json"
FIRST_VIOLATION = "first-violation.json"
# The live isolation monitor's durable downgrade record (txn tenants):
# which ladder level the run fell to and at what prefix — the
# first-violation pattern applied to the isolation plane.
ONLINE_ISO = "online-iso.json"

# Store-level tenant registry the daemon persists each tick (web /live
# reads it cross-process).
ONLINE_REGISTRY = "online-registry.json"

# Fleet-campaign namespace (jepsen_tpu.fleet): the orchestrator's work
# spec, lease files, and per-unit summaries live under
# store/<name>/fleet/ — coordination state, never a run (tests()
# excludes it the way it excludes the latest symlinks).
FLEET_DIR = "fleet"

# Federated checking-service namespace (jepsen_tpu.service): the
# cluster's coordination state lives under store/service/ — the budget
# ledger (cluster-wide admission limits), per-tenant lease files
# (which worker owns which live run), per-worker registry entries
# (heartbeat + usage + capability, the web control plane's source),
# and the durable SLO scale signal. Coordination state, never a test
# (tests() excludes the whole directory).
SERVICE_DIR = "service"
SERVICE_BUDGET = "budget.json"
SERVICE_ADVICE = "scale-advice.json"
SERVICE_TENANTS_DIR = "tenants"
SERVICE_WORKERS_DIR = "workers"

# Cluster observability namespace (jepsen_tpu.series / .alerts): the
# per-worker metrics time-series ring files and the durable alert log
# live under store/telemetry/ — diagnostics state, never a test
# (tests() excludes the whole directory, like service/).
TELEMETRY_DIR = "telemetry"


class CampaignMismatch(ValueError):
    """An explicit campaign resume named a checkpoint belonging to a
    DIFFERENT campaign (key mismatch) — refused rather than clobbered,
    because the checkpoint is the only resume point."""


def _scrub(x):
    try:
        json.dumps(x)
        return x
    except TypeError:
        return repr(x)


class StoreHandle:
    """One run's directory + file helpers.

    ``store``/``test_name`` (set by Store.create) let ``save_results``
    promote the run's ``latest`` symlinks — which move ONLY once
    results exist, so ``latest`` never points at a run directory a
    crash left without a verdict (the ``latest-incomplete`` link tracks
    those instead)."""

    def __init__(self, dir: Path, store: Optional["Store"] = None,
                 test_name: Optional[str] = None):
        self.dir = Path(dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.store = store
        self.test_name = test_name
        self._log_handler: Optional[logging.Handler] = None
        # Telemetry baseline: the registry is process-cumulative, so
        # save_results reports this RUN's counter deltas, not every
        # earlier run's traffic re-counted (handles are created at run
        # start — Store.create / salvage / rehydrate all come through
        # here).
        self._tel_base = telemetry.snapshot()
        self._tel_frozen: Optional[dict] = None

    def freeze_telemetry(self) -> None:
        """Capture this run's counter delta NOW. Pooled campaigns
        (runtime.run_seeds) defer every seed's save_results until
        after the whole campaign plus the shared pooled dispatch;
        freezing at the seed's execution boundary keeps seed k's
        results.json block from absorbing seeds k+1..N's traffic (the
        baseline alone only protects the backward direction)."""
        self._tel_frozen = telemetry.counters_delta(
            self._tel_base, telemetry.snapshot())

    # ---------------------------------------------------------- paths
    def path(self, *parts: str) -> str:
        """A path inside the run dir, parents created (store.clj path!)."""
        p = self.dir.joinpath(*[str(x) for x in parts])
        p.parent.mkdir(parents=True, exist_ok=True)
        return str(p)

    # ---------------------------------------------------------- files
    def write_json(self, parts, obj) -> None:
        # Durable-write discipline (JTL-H-DWRITE): results.json /
        # test.json / salvage.json are resume-path inputs — a torn
        # half-written artifact must be impossible, so they land via
        # the fsynced tmp + atomic-rename primitive.
        parts = [parts] if isinstance(parts, str) else list(parts)
        atomic_write_json(self.path(*parts), obj, indent=2,
                          default=_scrub)

    def read_json(self, *parts):
        with open(self.path(*parts)) as f:
            return json.load(f)

    def write_history(self, parts, history: Sequence[Op]) -> None:
        parts = [parts] if isinstance(parts, str) else list(parts)
        write_txt(self.path(*parts[:-1], parts[-1] + ".txt"), history)
        write_jsonl(self.path(*parts[:-1], parts[-1] + ".jsonl"), history)

    # ------------------------------------------------------ lifecycle
    def save_test(self, test: dict) -> None:
        clean = {k: _scrub(v) for k, v in test.items()
                 if k not in NONSERIALIZABLE_KEYS}
        self.write_json("test.json", clean)

    def save_history(self, history: Sequence[Op], model=None,
                     txt: bool = True) -> None:
        """Phase 1: history lands before analysis (save-1!,
        store.clj:279-290). With ``model``, additionally cache the
        MACHINE form — the exact columnar walk output the replay seam
        would recompute from jsonl text — the fressian-beside-
        history.txt discipline (store.clj's dual forms). The sidecar
        is best-effort: any failure (state-space explosion, kinds that
        don't survive the JSON round-trip) leaves only the text forms,
        and recheck falls back to parsing them."""
        if txt:
            write_txt(self.path("history.txt"), history)
        write_jsonl(self.path("history.jsonl"), history)
        if model is not None:
            try:
                self._save_machine_form(history, model)
            except Exception:
                logging.getLogger("jepsen.store").debug(
                    "machine-form sidecar skipped", exc_info=True)

    def _save_machine_form(self, history: Sequence[Op], model) -> None:
        import numpy as np

        from .history.columnar import ops_to_columnar
        cols = ops_to_columnar(model, [list(history)])
        kinds_json = json.dumps(cols.kinds)
        # Self-validation: only cache kinds that survive the JSON
        # round-trip bit-for-bit — anything exotic stays on the
        # parse-from-text path.
        if _kinds_from_json(kinds_json) != list(cols.kinds):
            return
        # Flat binary, not npz: the replay seam loads tens of
        # thousands of these per recheck, and per-file zip parsing
        # costs more than the whole text walk. Header json + raw
        # little-endian array bytes, one frombuffer view each on load.
        index = (cols.index if cols.index is not None
                 else np.full_like(cols.kind, -1))
        meta = json.dumps({
            "n": int(cols.type.shape[1]),
            "kinds": kinds_json,
            "model": repr(model),
        }).encode()
        # tmp + rename: a crash mid-write must never leave a torn
        # sidecar a later recheck would have to survive.
        target = self.path("history.cols.bin")
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(MACHINE_MAGIC)
            f.write(len(meta).to_bytes(4, "little"))
            f.write(meta)
            f.write(np.ascontiguousarray(cols.type[0], np.int8).tobytes())
            f.write(np.ascontiguousarray(cols.process[0],
                                         np.int16).tobytes())
            f.write(np.ascontiguousarray(cols.kind[0], np.int32).tobytes())
            f.write(np.ascontiguousarray(index[0], np.int32).tobytes())
        os.replace(tmp, target)

    def save_results(self, results: dict) -> None:
        """Phase 2: analysis output (save-2!, store.clj:292-302).
        Completing phase 2 is what promotes this run to ``latest``.

        The process-wide telemetry snapshot (scheduler/AOT/WAL/run
        counters — jepsen_tpu.telemetry) merges in as one canonical
        ``telemetry`` block when non-empty, tagged with its
        ``source``: ``salvaged`` for runs reconstructed from a crashed
        WAL (salvage.json present), else ``live`` — so crashed-run
        verdicts stay distinguishable downstream. Counters are deltas
        since this handle was created (the registry is
        process-cumulative; a campaign's seed N must not re-report
        seeds 0..N-1's traffic as its own), histograms stay cumulative
        distributions. A caller-provided block wins untouched."""
        snap = self._tel_frozen if self._tel_frozen is not None \
            else telemetry.counters_delta(self._tel_base,
                                          telemetry.snapshot())
        if snap and "telemetry" not in results:
            src = "salvaged" if (self.dir / "salvage.json").exists() \
                else "live"
            results["telemetry"] = {"source": src, **snap}
        self.write_json("results.json", results)
        if self.store is not None and self.test_name is not None:
            self.store.update_symlinks(self.test_name, self.dir)
            self.store.retire_incomplete_links(self.dir)

    # -------------------------------------------------------- logging
    def start_logging(self) -> None:
        """Attach a per-run jepsen.log file handler (store.clj:304-318)."""
        h = logging.FileHandler(self.path("jepsen.log"))
        h.setFormatter(logging.Formatter(
            "%(asctime)s{%(threadName)s} %(levelname)s %(name)s - "
            "%(message)s"))
        logging.getLogger().addHandler(h)
        self._log_handler = h

    def stop_logging(self) -> None:
        if self._log_handler is not None:
            logging.getLogger().removeHandler(self._log_handler)
            self._log_handler.close()
            self._log_handler = None


class Store:
    """The store root: creates run dirs, symlinks, loads past runs."""

    def __init__(self, base=BASE):
        self.base = Path(base)

    def create(self, test_name: str, ts: Optional[str] = None) -> StoreHandle:
        if ts is None:
            base = time.strftime("%Y%m%dT%H%M%S")
            ts, n = base, 0
            while (self.base / test_name / ts).exists():
                n += 1
                ts = f"{base}.{n}"
        h = StoreHandle(self.base / test_name / ts, store=self,
                        test_name=test_name)
        # A fresh run has no results yet: it is the newest INCOMPLETE
        # run. ``latest`` moves only when save_results lands, so a
        # crash here never leaves ``latest`` pointing at a verdictless
        # directory.
        self.update_symlinks(test_name, h.dir, kind="latest-incomplete")
        return h

    def update_symlinks(self, test_name: str, target: Path,
                        kind: str = "latest") -> None:
        """Maintain store/<name>/<kind> and store/<kind>
        (store.clj:235-247). ``kind`` is ``latest`` (completed runs —
        moved by save_results) or ``latest-incomplete`` (the newest
        crashed/salvageable run — moved at create time)."""
        for link in (self.base / test_name / kind, self.base / kind):
            link.parent.mkdir(parents=True, exist_ok=True)
            if link.is_symlink() or link.exists():
                link.unlink()
            link.symlink_to(os.path.relpath(target, link.parent))

    def retire_incomplete_links(self, target: Path) -> None:
        """Drop any ``latest-incomplete`` link pointing at a run that
        just completed — it is no longer incomplete."""
        target = Path(target).resolve()
        for link in (target.parent / "latest-incomplete",
                     self.base / "latest-incomplete"):
            try:
                if link.is_symlink() and link.resolve() == target:
                    link.unlink()
            except OSError:
                pass

    # ---------------------------------------------------------- browse
    def tests(self) -> Dict[str, List[str]]:
        """{test-name: [timestamps]} of stored runs (store.clj tests).
        Symlinks (latest, latest-incomplete) are never runs."""
        out: Dict[str, List[str]] = {}
        if not self.base.exists():
            return out
        for name_dir in sorted(self.base.iterdir()):
            if (not name_dir.is_dir() or name_dir.is_symlink()
                    or name_dir.name in ("latest", SERVICE_DIR,
                                         TELEMETRY_DIR)):
                continue
            runs = [d.name for d in sorted(name_dir.iterdir())
                    if d.is_dir() and not d.is_symlink()
                    and d.name not in ("latest", FLEET_DIR)]
            if runs:
                out[name_dir.name] = runs
        return out

    def incomplete(self, include_salvaged: bool = False) -> List[tuple]:
        """(test_name, ts) of crashed/salvageable runs: a live-WAL
        segment exists but no results.json — the run died (or is still
        running) somewhere between setup and analysis. Salvage
        materializes their checkable history; ``latest`` never points
        at them.

        Runs already salvaged (salvage.json at least as new as the
        WAL) are skipped so repeat sweeps converge instead of
        re-salvaging and re-checking the same crash forever;
        ``include_salvaged=True`` lists them anyway."""
        out = []
        for name, runs in self.tests().items():
            for ts in runs:
                d = self.base / name / ts
                if not (d / WAL_FILE).exists() or \
                        (d / "results.json").exists():
                    continue
                if not include_salvaged:
                    try:
                        sj = d / "salvage.json"
                        if sj.exists() and sj.stat().st_mtime >= \
                                (d / WAL_FILE).stat().st_mtime:
                            continue
                    except OSError:
                        pass
                out.append((name, ts))
        return out

    def salvage(self, test_name: str, ts: str, model=None) -> dict:
        """Salvage-to-verdict, step 1: reconstruct a checkable run from
        a (possibly torn) live WAL. Drops the torn tail, completes
        dangling invocations as ``:info``, and materializes the
        standard ``history.jsonl``/``history.txt`` (+ the machine-form
        sidecar when ``model`` is given, so the batched replay seam
        skips the text parse) — after which ``Store.recheck``, every
        checker family, and the web UI work on the crashed run
        unchanged. ``test.json`` is restored from the WAL header if the
        crash predated it. Returns the salvage stats (also persisted as
        ``salvage.json``)."""
        from .history.wal import read_wal, salvage_history

        d = self.run_dir(test_name, ts)
        wal_path = d / WAL_FILE
        if not wal_path.exists():
            raise FileNotFoundError(f"{wal_path}: no WAL to salvage")
        w = read_wal(wal_path)
        history, dangling = salvage_history(w["ops"])
        h = StoreHandle(d, store=self, test_name=test_name)
        h.save_history(history, model=model)
        if not (d / "test.json").exists():
            h.write_json("test.json", w["header"].get("test") or {})
        phases = [p for p, _ in w["phases"]]
        stats = {
            "salvaged": True,
            "ops": len(history),
            "wal_ops": len(w["ops"]),
            "dangling_completed": dangling,
            "torn_tail": w["torn"],
            "phase": phases[-1] if phases else
            w["header"].get("phase", "setup"),
            "seed": w["header"].get("seed"),
        }
        # A run that FAILED (harness exception) rather than being
        # killed left a marker; surface it so an empty salvaged
        # prefix is never mistaken for a clean recovery.
        he = d / "harness-error.json"
        if he.exists():
            try:
                stats["harness_error"] = json.loads(
                    he.read_text()).get("error")
            except Exception:
                stats["harness_error"] = "unreadable harness-error.json"
        h.write_json("salvage.json", stats)
        return stats

    def run_dir(self, test_name: str, ts: str = "latest") -> Path:
        return self.base / test_name / ts

    # ----------------------------------------------------------- online
    def online_registry_path(self) -> Path:
        return self.base / ONLINE_REGISTRY

    def load_online_registry(self) -> dict:
        """The online daemon's persisted tenant registry (status,
        verdict-so-far, SLO counters per tenant) — {} when no daemon
        ever watched this store or the file is unreadable (the
        registry is display/resume state, never a correctness gate)."""
        try:
            return json.loads(self.online_registry_path().read_text())
        except Exception:
            return {}

    def save_online_registry(self, reg: dict) -> None:
        self.base.mkdir(parents=True, exist_ok=True)
        atomic_write_json(self.online_registry_path(), reg)

    # ---------------------------------------------------------- service
    def service_dir(self) -> Path:
        """The federated checking service's cluster namespace
        (jepsen_tpu.service, doc/service.md): budget ledger, tenant
        leases, worker registry, scale advice — all shared-filesystem
        coordination, never runs."""
        return self.base / SERVICE_DIR

    def telemetry_dir(self) -> Path:
        """The cluster observability namespace (store/telemetry/):
        per-worker series ring files + the alert log (jepsen_tpu
        .series / .alerts, doc/observability.md)."""
        return self.base / TELEMETRY_DIR

    def service_budget_path(self) -> Path:
        return self.service_dir() / SERVICE_BUDGET

    def service_advice_path(self) -> Path:
        return self.service_dir() / SERVICE_ADVICE

    def service_tenant_lease_path(self, test_name: str, ts: str) -> Path:
        # Flat filenames: run keys never contain path separators, and
        # the payload carries the authoritative "run" key anyway.
        return (self.service_dir() / SERVICE_TENANTS_DIR
                / f"{test_name}__{ts}.json")

    def service_worker_path(self, worker_id: str) -> Path:
        return (self.service_dir() / SERVICE_WORKERS_DIR
                / f"{worker_id}.json")

    def service_workers(self) -> Dict[str, dict]:
        """{worker_id: registry entry} for every worker that ever
        published into this store's service namespace (the caller
        filters liveness by heartbeat age)."""
        out: Dict[str, dict] = {}
        wdir = self.service_dir() / SERVICE_WORKERS_DIR
        if not wdir.exists():
            return out
        for f in sorted(wdir.glob("*.json")):
            try:
                out[f.stem] = json.loads(f.read_text())
            except Exception:
                continue
        return out

    def _run_json(self, test_name: str, ts: str, name: str
                  ) -> Optional[dict]:
        try:
            f = self.run_dir(test_name, ts) / name
            return json.loads(f.read_text()) if f.exists() else None
        except Exception:
            return None

    def online_verdict(self, test_name: str, ts: str) -> Optional[dict]:
        """The daemon's durable final verdict for a run (the
        recheck-parity unit), or None while the run is still being
        tailed / was never watched."""
        return self._run_json(test_name, ts, ONLINE_VERDICT)

    def first_violation(self, test_name: str, ts: str) -> Optional[dict]:
        """The online first-violation record: which op first made the
        run invalid and at what prefix the daemon caught it."""
        return self._run_json(test_name, ts, FIRST_VIOLATION)

    def online_iso(self, test_name: str, ts: str) -> Optional[dict]:
        """The live isolation monitor's durable downgrade record
        (level, prefix, incarnation), or None while the run still
        holds serializability / was never watched / is not
        transactional."""
        return self._run_json(test_name, ts, ONLINE_ISO)

    def load(self, test_name: str, ts: str = "latest") -> dict:
        """Rehydrate a stored run: test map slice + history + results
        (store.clj:165-171)."""
        d = self.run_dir(test_name, ts)
        out: dict = {}
        tj = d / "test.json"
        if tj.exists():
            out.update(json.loads(tj.read_text()))
        hist = d / "history.jsonl"
        if hist.exists():
            out["history"] = read_jsonl(hist)
        res = d / "results.json"
        if res.exists():
            out["results"] = json.loads(res.read_text())
        return out

    def load_histories(self, test_name: str,
                       timestamps: Optional[Sequence[str]] = None
                       ) -> List[List[Op]]:
        """Every stored history for a test — the batch-recheck seam.
        Runs that crashed before writing a history are skipped."""
        ts = timestamps if timestamps is not None else \
            self.tests().get(test_name, [])
        out = []
        for t in ts:
            loaded = self.load(test_name, t)
            if "history" in loaded:
                out.append(loaded["history"])
        return out

    def recheck(self, test_name: str, model,
                timestamps: Optional[Sequence[str]] = None, *,
                independent: bool = False, resume: bool = False,
                faults=None) -> dict:
        """Re-analyze every stored history of a test on device in one
        batched dispatch — the replay seam (store.clj:165-171) riding
        the columnar fast path (ops.linearize.check_batch_columnar).

        ``independent=True`` strains each stored history into per-key
        subhistories first (KV-valued workloads) and pools ALL
        (run, key) units into the one batch. Returns
        {"valid", "runs": {ts: {"valid", "results"}}}.

        The columnar path journals retired chunk verdicts to
        ``store/<test>/recheck.journal.jsonl`` as it streams;
        ``resume=True`` reloads a prior interrupted run's journal and
        dispatches only the remaining rows (zero completed chunks
        re-dispatched — doc/resilience.md). The journal is deleted on
        successful completion. ``faults`` threads a checker-nemesis
        injector (ops.faults) into the pipeline — the testing seam.
        """
        from .ops.linearize import check_batch_columnar, check_columnar
        from .ops.statespace import StateSpaceExplosion

        ts = (list(timestamps) if timestamps is not None
              else self.tests().get(test_name, []))
        if not independent:
            # Fastest path: every run saved its machine-form sidecar
            # (the columnar walk cached at save time under this model)
            # — assemble the batch straight from npz arrays, no text
            # parse at all. Falls back to the native jsonl loader when
            # sidecars are absent/mismatched: no per-op Python objects
            # either way (the reference reads its machine form through
            # JVM-native fressian).
            from .history.columnar import jsonl_to_columnar

            machine = self._load_machine_forms(test_name, ts, model)
            texts, labels = [], []
            if machine is None:
                for t in ts:
                    f = self.run_dir(test_name, t) / "history.jsonl"
                    if f.exists():
                        texts.append(f.read_bytes())
                        labels.append((t, None))
                if not texts:
                    return {"valid": "unknown", "runs": {},
                            "error":
                            f"no stored histories for {test_name!r}"}
            try:
                if machine is not None:
                    cols, labels = machine
                else:
                    cols = jsonl_to_columnar(model, texts)
                # Lazy details: only invalid rows pay the Python replay
                # decode and the frontier transfer — valid rows stay at
                # tensor speed, matching the reference's
                # render-only-failures discipline (checker.clj:98-103).
                # Tiny tall-W buckets ride the native engine instead of
                # paying a latency-bound device round trip each.
                return self._journaled_recheck(
                    test_name,
                    {"model": repr(model), "rows": cols.batch,
                     "digest": columnar_digest(cols)},
                    resume, labels,
                    lambda journal: check_columnar(
                        model, cols, details="invalid",
                        min_device_batch=64, journal=journal,
                        faults=faults))
            except StateSpaceExplosion:
                # Vocabulary too rich for the packed table: degrade to
                # the Op-list path, whose batch checker falls back to
                # per-history engines (linearize.py's explosion route).
                units = [loaded["history"] for t in ts
                         if "history" in
                         (loaded := self.load(test_name, t))]
                rs = check_batch_columnar(model, units,
                                          details="invalid")
        else:
            units, labels = self.strain_units(test_name, ts,
                                              independent=True)
            if not units:
                # Nothing loadable is not a pass: distinguish
                # "re-checked and valid" from "found no stored
                # histories to check".
                return {"valid": "unknown", "runs": {},
                        "error": f"no stored histories for {test_name!r}"}
            # The strained (run, key) units are the batch rows: journal
            # them like the columnar path, so an interrupted
            # independent recheck resumes with zero decided
            # sub-histories re-dispatched (the partition/resume
            # contract, doc/scaling.md "Partition, then fuse").
            return self._journaled_recheck(
                test_name,
                {"model": repr(model), "rows": len(units),
                 "independent": True,
                 "digest": _units_digest(units, labels)},
                resume, labels,
                lambda journal: check_batch_columnar(
                    model, units, details="invalid", journal=journal,
                    faults=faults))
        out = group_unit_results(labels, rs)
        self._tag_recheck(out, test_name, ts)
        return out

    def recheck_isolation(self, test_name: str,
                          timestamps: Optional[Sequence[str]] = None, *,
                          faults=None) -> dict:
        """Post-mortem isolation certification of every stored
        transactional history of a test in one batched dispatch — the
        txn family's ``recheck`` twin and the online daemon's parity
        reference (the daemon's final check routes through the same
        ``isolation.certify_batch`` call). Returns
        {"valid", "runs": {ts: result}} where each result is an
        ops.txn_graph.txn_result dict carrying the certified level."""
        from .isolation import certify_batch

        ts = (list(timestamps) if timestamps is not None
              else self.tests().get(test_name, []))
        units, labels = [], []
        for t in ts:
            loaded = self.load(test_name, t)
            if "history" in loaded:
                units.append(loaded["history"])
                labels.append(t)
        if not units:
            return {"valid": "unknown", "runs": {},
                    "error": f"no stored histories for {test_name!r}"}
        rs = certify_batch(units, faults=faults)
        out = {"valid": all(r["valid"] for r in rs),
               "runs": dict(zip(labels, rs))}
        self._tag_recheck(out, test_name, labels)
        return out

    def _tag_recheck(self, out: dict, test_name: str, ts) -> None:
        """Stamp a recheck result with its telemetry source: verdicts
        here came from REPLAY, not a live run, and runs reconstructed
        from a crashed WAL (salvage.json present) are named — the
        downstream distinguishability contract."""
        salvaged = [t for t in ts
                    if (self.run_dir(test_name, t)
                        / "salvage.json").exists()]
        out["telemetry"] = {"source": "recheck",
                            **({"salvaged_runs": salvaged}
                               if salvaged else {})}

    def _journaled_recheck(self, test_name: str, header: dict,
                           resume: bool, labels, call):
        """One batched recheck under a durable chunk journal — the
        shared lifecycle of the columnar and independent-unit paths:
        retired verdicts land durably as the stream runs, keyed to the
        exact batch (``header``), so a crashed or killed recheck
        resumes from completed chunks. ``call(journal)`` runs the
        check; an interrupted run keeps the journal ON DISK (that is
        its whole purpose), while a StateSpaceExplosion drops it —
        the journal is keyed to the exploded form, useless to any
        later resume — before propagating to the caller's degradation
        route."""
        from .ops.statespace import StateSpaceExplosion

        journal = ChunkJournal(
            self.base / test_name / "recheck.journal.jsonl",
            header, resume=resume)
        try:
            rs = call(journal)
            resume_hits = journal.resume_hits
            journal.finish()
        except StateSpaceExplosion:
            journal.finish()
            raise
        except BaseException:
            journal.close()
            raise
        out = group_unit_results(labels, rs)
        if resume:
            out["resume_hits"] = resume_hits
        self._tag_recheck(out, test_name,
                          sorted({t for t, _ in labels}))
        return out

    def _load_machine_forms(self, test_name: str, ts, model):
        """(ColumnarOps, labels) assembled from every run's machine-form
        sidecar, or None when any run lacks one / was cached under a
        different model — all-or-nothing, so a mixed store degrades to
        the text path rather than silently dropping runs."""
        import numpy as np

        from .history.columnar import PAD, ColumnarOps

        fingerprint = repr(model)
        rows = []          # (ts, n_lines, kinds_json, body_bytes, off)
        for t in ts:
            f = self.run_dir(test_name, t) / "history.cols.bin"
            if not f.exists():
                return None
            # Any malformed sidecar (torn write survived somehow,
            # foreign file) sends the WHOLE batch to the text path —
            # the sidecar is an accelerator, never a failure mode.
            try:
                raw = f.read_bytes()
                if not raw.startswith(MACHINE_MAGIC):
                    return None
                hlen = int.from_bytes(raw[8:12], "little")
                meta = json.loads(raw[12:12 + hlen])
                n_lines = int(meta["n"])
                if len(raw) < 12 + hlen + n_lines * 11:
                    return None            # short body: torn file
                if meta["model"] != fingerprint:
                    return None
            except Exception:
                return None
            rows.append((t, n_lines, meta["kinds"], raw, 12 + hlen))
        if not rows:
            return None
        vocab: Dict[tuple, int] = {}
        kinds: List[tuple] = []
        # Kinds vocabularies repeat across runs of one test: memoize
        # the per-run LUT by the kinds json text.
        lut_cache: Dict[str, np.ndarray] = {}
        n = max(m for _, m, _, _, _ in rows)
        B = len(rows)
        type_ = np.full((B, n), PAD, np.int8)
        process = np.zeros((B, n), np.int16)
        kind = np.full((B, n), -1, np.int32)
        index = np.full((B, n), -1, np.int32)
        for r, (_, m, kjson, raw, off) in enumerate(rows):
            # Same all-or-nothing discipline as the header checks: a
            # sidecar that passes magic/length/model but carries a
            # corrupt kinds vocabulary or out-of-range kind indices
            # must send the batch to the text path, not crash recheck
            # (IndexError) or silently alias into wrong kinds (negative
            # indices in [-len(lut), -2]) — wrong verdicts.
            try:
                lut = lut_cache.get(kjson)
                if lut is None:
                    ks = _kinds_from_json(kjson)
                    # Slot -1 keeps non-invoke lines' -1 (negative
                    # indexing hits it).
                    lut = np.empty(len(ks) + 1, np.int32)
                    for i, k in enumerate(ks):
                        j = vocab.get(k)
                        if j is None:
                            j = vocab[k] = len(kinds)
                            kinds.append(k)
                        lut[i] = j
                    lut[-1] = -1
                    lut_cache[kjson] = lut
                type_[r, :m] = np.frombuffer(raw, np.int8, m, off)
                off += m
                process[r, :m] = np.frombuffer(raw, np.int16, m, off)
                off += 2 * m
                kraw = np.frombuffer(raw, np.int32, m, off)
                # Valid kind indices are exactly [-1, len(ks)): -1 is
                # the non-invoke sentinel (lut's last slot).
                if kraw.size and (int(kraw.min()) < -1
                                  or int(kraw.max()) >= lut.size - 1):
                    return None
                kind[r, :m] = lut[kraw]
                off += 4 * m
                index[r, :m] = np.frombuffer(raw, np.int32, m, off)
            except Exception:
                return None
        cols = ColumnarOps(type=type_, process=process, kind=kind,
                           kinds=kinds, index=index)
        return cols, [(t, None) for t, _, _, _, _ in rows]

    def strain_units(self, test_name: str, ts, *,
                     independent: bool) -> tuple:
        """(units, labels) over a test's stored runs: per-key
        subhistories when ``independent`` (falling back to the whole
        history for runs with no KV-keyed ops, so keyless runs are
        never silently excluded), else whole histories. Labels are
        (timestamp, key-or-None)."""
        from .independent import history_keys, subhistory

        units, labels = [], []
        for t in ts:
            loaded = self.load(test_name, t)
            h = loaded.get("history")
            if h is None:
                continue
            keys = history_keys(h) if independent else []
            if keys:
                for k in keys:
                    units.append(subhistory(k, h))
                    labels.append((t, k))
            else:
                units.append(h)
                labels.append((t, None))
        return units, labels

    def delete(self, test_name: str, ts: Optional[str] = None) -> None:
        """Remove a run, or all of a test's runs (store.clj:328-345)."""
        target = (self.base / test_name / ts) if ts else \
            (self.base / test_name)
        if target.exists():
            shutil.rmtree(target)


class ChunkJournal:
    """Durable chunk-verdict journal — the checker's write-ahead log.

    The streaming checkers (check_batch_tpu / check_columnar /
    Store.recheck) append one JSON line per retired chunk as verdicts
    land: ``{"rows": [...], "valid": [...], "bad": [...], "prov":
    [...]}`` with ``rows`` caller-level history indices, ``bad`` the
    final bad-op index (null for valid rows) and ``prov`` the
    provenance tag per row. Line 1 is a header binding the journal to
    one exact batch: ``{"journal": "JTJRNL1", "key": {...}}`` — the key
    carries the model fingerprint, row count, and a content digest, so
    a stale journal (different store state, different model) is
    discarded rather than trusted.

    Every record is flushed and fsynced: an interrupted process leaves
    every retired chunk on disk (a torn final line is tolerated and
    dropped on load). ``resume=True`` reloads decided rows so the next
    run dispatches only the remainder; ``record`` REFUSES a row decided
    twice — the journal is also the enforcement point for the
    no-chunk-redispatched invariant. ``finish()`` deletes the file: a
    journal only outlives an interrupted run.

    Besides chunk-verdict rows the journal accepts **frontier-
    checkpoint rows** (``{"frontier": {...}}``, record_frontier): the
    online daemon's carried WGL search state (ops.schedule
    .ResidentFrontier.export), bound to the same key — writer
    incarnation + segment inode — as every decided prefix. Latest row
    wins on load (``frontier()``); a restarted daemon or a takeover
    worker resumes the carry and re-dispatches only the undecided
    suffix (doc/online.md documents the format).
    """

    def __init__(self, path, key: dict, resume: bool = False):
        self.path = Path(path)
        self.key = dict(key)
        self.resume_hits = 0
        self._decided: Dict[int, tuple] = {}
        self._frontier: Optional[dict] = None
        self._stale_frontier_rows = 0
        self._good_end = 0     # byte offset past the last clean line
        if resume and self.path.exists():
            self._load()
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self._decided or self._frontier is not None:
            # Drop the torn tail BEFORE appending: writing after a
            # partial line would weld two records into one unparseable
            # line, and a later resume would silently discard
            # everything journaled past it.
            with open(self.path, "r+b") as f:
                f.truncate(self._good_end)
            self._f = open(self.path, "a")
        else:
            self._f = open(self.path, "w")
            self._f.write(json.dumps(
                {"journal": JOURNAL_MAGIC, "key": self.key}) + "\n")
            self._flush()

    def _load(self) -> None:
        try:
            data = self.path.read_bytes()
            pos = 0
            header_seen = False
            while pos < len(data):
                nl = data.find(b"\n", pos)
                if nl < 0:
                    break          # torn tail from the interruption
                try:
                    e = json.loads(data[pos:nl])
                    if not header_seen:
                        if e.get("journal") != JOURNAL_MAGIC or \
                                e.get("key") != self.key:
                            logging.getLogger("jepsen.store").warning(
                                "chunk journal %s belongs to a "
                                "different batch (key mismatch); "
                                "starting fresh", self.path)
                            return
                        header_seen = True
                    elif "frontier" in e:
                        # Frontier-checkpoint row: latest wins.
                        self._frontier = e["frontier"]
                    else:
                        for r, v, b, p in zip(e["rows"], e["valid"],
                                              e["bad"], e["prov"]):
                            self._decided[int(r)] = (
                                bool(v), None if b is None else int(b),
                                p)
                except Exception:
                    break          # malformed line: keep the prefix
                pos = nl + 1
                self._good_end = pos
        except Exception:
            self._decided = {}
            self._good_end = 0

    def _flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def decided(self) -> Dict[int, tuple]:
        """{row: (valid, bad-op-index-or-None, provenance)} recovered
        from a previous interrupted run."""
        self.resume_hits = len(self._decided)
        return dict(self._decided)

    def record(self, rows, valid, bad, prov) -> None:
        rows = [int(r) for r in rows]
        if not rows:
            return
        dup = [r for r in rows if r in self._decided]
        if dup:
            raise ValueError(
                f"chunk journal: rows decided twice (double dispatch): "
                f"{dup[:5]}")
        with telemetry.span("journal", rows=len(rows)):
            valid = [bool(v) for v in valid]
            bad = [None if b is None else int(b) for b in bad]
            prov = [str(p) for p in prov]
            for r, v, b, p in zip(rows, valid, bad, prov):
                self._decided[r] = (v, b, p)
            self._f.write(json.dumps(
                {"rows": rows, "valid": valid, "bad": bad,
                 "prov": prov}) + "\n")
            self._flush()
        telemetry.REGISTRY.counter("journal.rows").inc(len(rows))

    def frontier(self) -> Optional[dict]:
        """The latest frontier-checkpoint payload recovered on resume,
        or None when no checkpoint row survived."""
        return self._frontier

    #: Superseded frontier rows tolerated before the journal compacts
    #: in place: only the LATEST checkpoint is ever used, so a
    #: long-lived tenant must not grow the file by one dead bitset row
    #: per tick forever.
    FRONTIER_COMPACT_EVERY = 64

    def record_frontier(self, payload: dict) -> None:
        """Append one frontier-checkpoint row (fsynced, like every
        chunk verdict): the resumed carry is durable the moment the
        call returns — a SIGKILL between ticks loses at most the ticks
        since the last checkpoint, never a decided prefix. Every
        FRONTIER_COMPACT_EVERY rows the journal rewrites itself
        (atomic tmp+rename) down to the header, the decided rows, and
        this one checkpoint — dead rows never accumulate."""
        with telemetry.span("journal.frontier"):
            self._frontier = payload
            self._stale_frontier_rows += 1
            if self._stale_frontier_rows >= self.FRONTIER_COMPACT_EVERY:
                self._compact()
            else:
                self._f.write(json.dumps({"frontier": payload}) + "\n")
                self._flush()
        telemetry.REGISTRY.counter("journal.frontier_rows").inc()

    def _compact(self) -> None:
        """Rewrite the journal as header + one consolidated decided-
        rows record + the latest frontier row, atomically (a kill
        mid-compact leaves either the old file or the new one, never a
        torn hybrid)."""
        tmp = self.path.parent / (self.path.name + f".tmp{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(json.dumps(
                {"journal": JOURNAL_MAGIC, "key": self.key}) + "\n")
            if self._decided:
                rows = sorted(self._decided)
                f.write(json.dumps({
                    "rows": rows,
                    "valid": [self._decided[r][0] for r in rows],
                    "bad": [self._decided[r][1] for r in rows],
                    "prov": [self._decided[r][2] for r in rows],
                }) + "\n")
            if self._frontier is not None:
                f.write(json.dumps({"frontier": self._frontier}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "a")
        self._flush()
        self._stale_frontier_rows = 0

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass

    def finish(self) -> None:
        """The run completed: the journal has served its purpose."""
        self.close()
        try:
            self.path.unlink()
        except FileNotFoundError:
            pass


class CampaignCheckpoint:
    """Durable seed-campaign progress — run_seeds' write-ahead log.

    One JSON line per transition, fsynced: line 1 is a header binding
    the checkpoint to one campaign (``{"campaign": "JTCAMP1", "key":
    {...}}`` — test name + seed list; resuming against a mismatched
    checkpoint raises CampaignMismatch rather than clobbering the only
    resume point), then ``{"seed": s, "dir": ..., "status":
    "started"}`` when a seed's run dir is created and ``{"seed": s,
    "status": "done"}`` when its execution completes (history durably
    saved). A killed campaign resumes running only the remaining seeds:
    ``done`` seeds rehydrate their stored history, ``started`` seeds
    salvage their WAL prefix, absent seeds run fresh. Torn final lines
    are tolerated and truncated before appending (the ChunkJournal
    discipline). ``finish()`` deletes the file — a checkpoint only
    outlives an interrupted campaign.
    """

    def __init__(self, path, key: dict, resume: bool = False):
        self.path = Path(path)
        self.key = dict(key)
        self._runs: Dict[int, dict] = {}   # seed -> {"dir", "done"}
        self._good_end = 0
        if resume and self.path.exists():
            self._load()
        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self._runs:
            with open(self.path, "r+b") as f:
                f.truncate(self._good_end)
            self._f = open(self.path, "a")
        else:
            self._f = open(self.path, "w")
            self._f.write(json.dumps(
                {"campaign": CAMPAIGN_MAGIC, "key": self.key}) + "\n")
            self._flush()

    def _load(self) -> None:
        try:
            data = self.path.read_bytes()
            pos = 0
            header_seen = False
            while pos < len(data):
                nl = data.find(b"\n", pos)
                if nl < 0:
                    break
                try:
                    e = json.loads(data[pos:nl])
                    if not header_seen:
                        if e.get("campaign") != CAMPAIGN_MAGIC or \
                                e.get("key") != self.key:
                            # An EXPLICIT resume against the wrong
                            # campaign must refuse, not overwrite the
                            # only resume point (a mistyped --seeds
                            # would otherwise destroy all progress).
                            raise CampaignMismatch(
                                f"campaign checkpoint {self.path} "
                                f"belongs to a different campaign: "
                                f"stored key {e.get('key')!r} != "
                                f"{self.key!r}; start a fresh "
                                f"campaign (without --resume) to "
                                f"replace it")
                        header_seen = True
                    elif e.get("status") == "started":
                        self._runs[int(e["seed"])] = {
                            "dir": e["dir"], "done": False}
                    elif e.get("status") == "done":
                        r = self._runs.get(int(e["seed"]))
                        if r is not None:
                            r["done"] = True
                except CampaignMismatch:
                    raise
                except Exception:
                    break
                pos = nl + 1
                self._good_end = pos
        except CampaignMismatch:
            raise
        except Exception:
            self._runs = {}
            self._good_end = 0

    def _flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def seed_state(self, seed: int) -> Optional[dict]:
        """{"dir": ..., "done": bool} for a seed a prior campaign
        already touched, else None."""
        r = self._runs.get(int(seed))
        return dict(r) if r is not None else None

    def started(self, seed: int, dir) -> None:
        self._runs[int(seed)] = {"dir": str(dir), "done": False}
        self._f.write(json.dumps(
            {"seed": int(seed), "dir": str(dir), "status": "started"})
            + "\n")
        self._flush()

    def done(self, seed: int) -> None:
        r = self._runs.get(int(seed))
        if r is not None:
            r["done"] = True
        self._f.write(json.dumps(
            {"seed": int(seed), "status": "done"}) + "\n")
        self._flush()

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass

    def finish(self) -> None:
        """The campaign completed: every seed ran and analyzed."""
        self.close()
        try:
            self.path.unlink()
        except FileNotFoundError:
            pass


def _units_digest(units, labels) -> str:
    """Content fingerprint of strained (run, key) history units — the
    independent recheck's journal key component. Every op line feeds
    the hash: a re-salvage can flip a dangling MIDDLE invocation
    between ok and :info without touching counts or endpoints, and a
    journal keyed to the old contents must be discarded, never
    trusted."""
    import hashlib

    h = hashlib.sha256()
    for (ts, k), u in zip(labels, units):
        h.update(f"{ts}|{k!r}|{len(u)}".encode())
        for op in u:
            h.update(str(op).encode())
    return h.hexdigest()[:16]


def columnar_digest(cols) -> str:
    """Content fingerprint of a ColumnarOps batch — the chunk-journal
    key component that pins a journal to one exact row set/order."""
    import hashlib

    import numpy as np

    h = hashlib.sha256()
    for arr in (cols.type, cols.process, cols.kind):
        h.update(np.ascontiguousarray(arr).tobytes())
    if cols.index is not None:
        h.update(np.ascontiguousarray(cols.index).tobytes())
    # The key column determines the partitioned journal's entire
    # (history, key) sub-row namespace: two batches differing only in
    # key assignment must never share a journal.
    key = getattr(cols, "key", None)
    if key is not None:
        h.update(b"key")
        h.update(np.ascontiguousarray(key).tobytes())
    h.update(json.dumps(list(map(list, cols.kinds)), default=str)
             .encode())
    return h.hexdigest()[:16]


def atomic_write_json(path, obj, **dump_kwargs) -> None:
    """Durable small-JSON write: fsynced temp file + atomic rename, so
    a crash mid-write never leaves a torn artifact — the summary-file
    primitive the synth/fuzz campaigns persist per-unit progress
    through (their resume paths trust these files blindly). The temp
    name carries the pid (the _aot_store discipline): two concurrent
    writers of one path must not interleave into a shared tmp.
    ``dump_kwargs`` forward to json.dump (indent, default)."""
    path = Path(path)
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(obj, f, **dump_kwargs)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def spec_digest(spec, **extra) -> str:
    """Fingerprint of a deterministic generator spec (any dataclass,
    e.g. ops.synth_device.SynthSpec) plus labeling kwargs — the
    chunk-journal key for synthesized batches. A spec NAMES its batch
    completely ((spec, backend) ↦ histories), so journals for
    device-synthesized campaigns key on it without materializing a
    single row, where stored batches pay a content digest
    (columnar_digest)."""
    import dataclasses
    import hashlib

    d = dataclasses.asdict(spec) if dataclasses.is_dataclass(spec) \
        else dict(spec)
    d.update(extra)
    return hashlib.sha256(
        json.dumps(d, sort_keys=True, default=str).encode()
    ).hexdigest()[:16]


def _kinds_from_json(text: str) -> list:
    """Decode a kinds vocabulary from JSON, restoring the tuple
    structure JSON flattens to lists (kinds are (f, value) tuples whose
    values may themselves be tuples, e.g. cas pairs)."""
    def detuple(x):
        if isinstance(x, list):
            return tuple(detuple(v) for v in x)
        return x

    return [detuple(k) for k in json.loads(text)]


def group_unit_results(labels, rs) -> dict:
    """Fold per-unit results back into the recheck shape
    {"valid", "runs": {ts: {"valid", "results"}}} — one grouping
    invariant shared by every replay path (Store.recheck and
    jepsen_tpu.recheck's fold/bank families)."""
    from .checkers.core import merge_valid

    runs: Dict[str, dict] = {}
    for (t, k), r in zip(labels, rs):
        run = runs.setdefault(t, {"results": {}})
        run["results"][k if k is not None else "history"] = r
    for run in runs.values():
        run["valid"] = merge_valid(
            r["valid"] for r in run["results"].values())
    return {
        "valid": merge_valid(run["valid"] for run in runs.values())
        if runs else True,
        "runs": runs,
    }


DEFAULT = Store()


def attach(test: dict, store: Optional[Store] = None) -> dict:
    """Give a test map a store handle + logging for its run; returns the
    test (wired by the CLI and usable directly)."""
    store = store or DEFAULT
    h = store.create(test.get("name", "noname"))
    test["store_handle"] = h
    h.save_test(test)
    h.start_logging()
    return test
