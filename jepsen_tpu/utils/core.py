"""Small shared utilities (counterparts of jepsen/src/jepsen/util.clj)."""
from __future__ import annotations

import random
import threading
import time as _time
from fractions import Fraction
from typing import Callable, List, Optional, Sequence, Tuple

from ..history.ops import Op, OK, FAIL, INFO


def majority(n: int) -> int:
    """Smallest majority of n nodes (util.clj:57-60)."""
    return n // 2 + 1


def fraction(a: int, b: int):
    """a/b, but 1 when b is zero (util.clj fraction)."""
    if b == 0:
        return 1
    return Fraction(a, b)


def integer_interval_set_str(s) -> str:
    """Render a set of integers compactly as e.g. "#{1-5 7 9-11}"
    (util.clj:484-509). Non-integers are rendered individually."""
    if s is None:
        return "#{}"
    ints = sorted(x for x in s if isinstance(x, int))
    other = sorted((repr(x) for x in s if not isinstance(x, int)))
    parts: List[str] = []
    i = 0
    while i < len(ints):
        j = i
        while j + 1 < len(ints) and ints[j + 1] == ints[j] + 1:
            j += 1
        parts.append(str(ints[i]) if i == j else f"{ints[i]}-{ints[j]}")
        i = j + 1
    parts.extend(other)
    return "#{" + " ".join(parts) + "}"


def history_latencies(history: List[Op]) -> List[Tuple[Op, Optional[int]]]:
    """Pair each invocation with its completion latency in nanos
    (util.clj:554-588). Returns (invoke-op, latency-or-None)."""
    from ..history.core import pairs
    return [(inv,
             c.time - inv.time
             if c is not None and c.time is not None and inv.time is not None
             else None)
            for inv, c in pairs(history)]


def nemesis_intervals(history: List[Op],
                      start_fs=("start",), stop_fs=("stop",)):
    """[(start-op, stop-op-or-None)] intervals of nemesis activity.

    A nemesis usually goes start-invoke, start-ok, stop-invoke, stop-ok;
    starts queue up and each stop pairs with the oldest queued start, so
    the emitted pairs are (first, third), (second, fourth) — covering the
    window through the stop *completion* (util.clj:590-607)."""
    from collections import deque
    out = []
    starts: deque = deque()
    for op in history:
        if not op.is_nemesis:
            continue
        if op.f in start_fs:
            starts.append(op)
        elif op.f in stop_fs and starts:
            out.append((starts.popleft(), op))
    out.extend((s, None) for s in starts)
    return out


def rand_nth(rng: random.Random, xs: Sequence):
    return xs[rng.randrange(len(xs))]


def retry(f: Callable, retries: int = 5, backoff: float = 0.1,
          exceptions=(Exception,), on_retry: Optional[Callable] = None):
    """Call f, retrying on exception with linear backoff
    (util.clj:285-324)."""
    for attempt in range(retries + 1):
        try:
            return f()
        except exceptions:
            if attempt == retries:
                raise
            if on_retry:
                on_retry(attempt)
            _time.sleep(backoff)
    raise AssertionError("unreachable")


def timeout_call(seconds: float, default, f: Callable, *args, **kw):
    """Run f in a thread; if it exceeds the deadline return default
    (util.clj:272-283). The thread is left to finish in the background —
    like the reference, which interrupts but cannot guarantee death.
    Exceptions raised by f before the deadline propagate to the caller
    (the reference rethrows on deref); after the deadline they are lost,
    as in the reference."""
    result = {}
    done = threading.Event()

    def run():
        try:
            result["v"] = f(*args, **kw)
        except BaseException as e:  # noqa: BLE001 — rethrown on the caller
            result["e"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    if done.wait(seconds):
        if "e" in result:
            raise result["e"]
        return result["v"]
    return default


class Relatime:
    """Test-relative monotonic nanosecond clock (util.clj:235-252).

    The origin is bound once at the start of a run so every op timestamp
    is comparable across workers and the nemesis.
    """

    def __init__(self):
        self.origin = _time.monotonic_ns()

    def nanos(self) -> int:
        return _time.monotonic_ns() - self.origin

    def sleep_until(self, t_nanos: int) -> None:
        while True:
            dt = t_nanos - self.nanos()
            if dt <= 0:
                return
            _time.sleep(dt / 1e9)
