from .core import (
    majority,
    fraction,
    integer_interval_set_str,
    history_latencies,
    nemesis_intervals,
    rand_nth,
    retry,
    timeout_call,
    Relatime,
)
