"""Federated online checking service: leasable live tenants across N
workers, dead-worker takeover, cluster-wide admission, SLO-guarded
degradation.

PR 9's ``watch`` daemon checks many live WALs but dies with its single
process; PR 10's fleet leases shard only *batch* campaigns. This module
fuses them (ROADMAP item 4, the federated-dispatch framing of arXiv
2606.02019 applied to OmniLink-style live validation, arXiv
2601.11836): every live run (tenant) becomes a **leasable unit** in the
shared store, so N ``watch``-style workers on N hosts split the tenant
population with nothing but the filesystem coordinating them.

Model
-----
``ServiceWorker`` IS an ``online.OnlineDaemon`` whose admission is
lease-governed: ``discover()`` claims per-tenant lease files
(``store/service/tenants/<name>__<ts>.json``) through the fleet layer's
``O_CREAT|O_EXCL`` + heartbeat protocol (fleet.claim_lease — skew-safe,
atomic, generation-bumping), renews them every TTL/3, and abandons a
tenant the moment its on-disk lease names someone else. Everything
below the admission layer — live tailing, rolling prefix checks,
decided-prefix journals, the overload ladder, parity-exact finalization
— is the PR-9 machinery untouched, which is exactly what makes takeover
cheap: a SIGKILLed worker's leases lapse after ``JT_LEASE_TTL_S``,
survivors re-claim at generation+1, and the new owner resumes the
tenant's inode-bound online ChunkJournal with **zero re-dispatched
decided prefixes** (``ChunkJournal.record`` structurally refuses a
double-decide) and no gap in first-violation detection.

Cluster-wide robustness ladder
------------------------------
  * **admission ledger** — ``store/service/budget.json`` holds the
    CLUSTER's limits (total tenants, wide-tenant count by W class,
    total ingest ops/s, the TTFV SLO); each worker publishes its usage
    in its registry entry (``service/workers/<id>.json``, heartbeat +
    usage + capability) and admits new tenants only while the summed
    live usage fits the ledger. Enforcement is optimistic (usage
    propagates at heartbeat cadence; transient overshoot of one
    heartbeat window is possible and documented) but cluster-scoped:
    no single process's view bounds the fleet.
  * **cost-routed placement** — each candidate tenant is priced per
    worker from a cheap bounded WAL probe (``wal.estimate_peak_w``)
    and the workers' advertised rates (the PR-10 CostRouter
    arithmetic): wide tenants steer to host-oracle-rich workers, long
    ones to event-chunk-capable ones. A worker defers claiming a
    tenant a live peer prices meaningfully cheaper — bounded by a
    patience window so nothing starves — and re-evaluates ownership
    only at lease RENEWAL (release_lease hands the unit over with all
    durable progress intact), so placement never thrashes mid-check.
  * **SLO scale signal** — a cluster-merged ``online.ttfv_s`` p99
    breach (telemetry.merge_histogram_snapshots over every worker's
    published slice) writes a durable ``service/scale-advice.json``;
    the local pool spawner (fleet.LocalPool.apply_scale_advice) widens
    the worker pool toward ``want_workers``, bounded by the host's
    core cap.
  * **takeover-storm breaker** — when a worker dies owning many
    tenants, survivors re-claim with a per-worker per-tick claim
    budget (``JT_SERVICE_CLAIM_BUDGET``), jittered candidate order,
    and a deterministic per-(worker, tenant) takeover stagger
    (``JT_SERVICE_STAGGER_S``), so one death costs bounded takeover
    latency instead of stampeding every survivor into overload — and
    when the inherited backlog IS overload, the PR-9 ladder (widen →
    shed → defer, now with the ``JT_DEFER_MAX_S`` starvation rescue)
    degrades and recovers without dropping a verdict.

``jepsen-tpu serve`` (cli.py) is the operator surface: the default
form orchestrates a local pool plus the web control plane (web.py's
``/service`` view renders every worker's tenants from the shared
registry); ``--join DIR --worker-id W`` runs one worker against an
existing store — the multi-host entry. doc/service.md documents the
formats and protocols; the bench ``service`` section measures
tenants-per-SLO vs workers and kill-a-worker takeover latency
(MULTICHIP_r08).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import random
import threading
import time
from collections import deque
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from . import telemetry
from .fleet import (LocalPool, _read_json, claim_lease, lease_skew_s,
                    lease_ttl_s, mark_lease_done, release_lease,
                    renew_lease)
from .history.wal import WAL_FILE, estimate_peak_w
from .online import OnlineConfig, OnlineDaemon, OnlineTenant
from .store import DEFAULT, Store, atomic_write_json

log = logging.getLogger("jepsen.service")

SERVICE_MAGIC = "JTSVC1"

#: The cluster budget ledger's defaults (store/service/budget.json).
#: 0 = unlimited / disabled. ``wide_w`` is the W class past which a
#: tenant counts against the wide budget (exponential device cost —
#: the scarce resource the ledger rations cluster-wide).
DEFAULT_BUDGET = {
    "max_tenants": 256,
    "wide_w": 14,
    "max_wide_tenants": 0,
    "max_ingest_ops_s": 0.0,
    "slo_ttfv_s": 0.0,
}


def claim_budget_default() -> int:
    """$JT_SERVICE_CLAIM_BUDGET: lease claims one worker attempts per
    tick — the takeover-storm breaker's rate limit. Default 2: a dead
    worker's tenants redistribute over a few ticks instead of landing
    on one survivor in one burst."""
    try:
        return max(1, int(os.environ.get("JT_SERVICE_CLAIM_BUDGET",
                                         "2")))
    except ValueError:
        return 2


def stagger_s_default() -> float:
    """$JT_SERVICE_STAGGER_S: upper bound of the deterministic
    per-(worker, tenant) takeover jitter — expired leases are
    re-claimed staggered across the window so survivors don't
    stampede. Default 0.5 s (well under the lease TTL; tests set 0)."""
    try:
        return max(0.0, float(os.environ.get("JT_SERVICE_STAGGER_S",
                                             "0.5")))
    except ValueError:
        return 0.5


def load_budget(store: Store) -> dict:
    """The cluster admission ledger, defaults filled in. Unreadable or
    absent → pure defaults (a single-worker store needs no ledger)."""
    out = dict(DEFAULT_BUDGET)
    try:
        out.update(json.loads(store.service_budget_path().read_text()))
    except Exception:
        pass
    return out


def save_budget(store: Store, budget: Optional[dict] = None) -> dict:
    merged = {**DEFAULT_BUDGET, **(budget or {}),
              "service": SERVICE_MAGIC}
    store.service_budget_path().parent.mkdir(parents=True,
                                             exist_ok=True)
    atomic_write_json(store.service_budget_path(), merged)
    return merged


def tenant_price(est_w: int, est_ops: int, caps: dict) -> float:
    """Estimated cost (s) of serving one tenant's interim checks on a
    worker advertising ``caps`` — the PR-10 CostRouter arithmetic
    applied to placement: the device scan pays 2^W lanes per event
    (un-chunked long dispatches penalized on workers without the
    event-chunked resume kernel), the host oracle is near-W-flat, and
    a W past the worker's admission bound rides the host there
    regardless of price.

    A worker advertising ``incremental`` (the resident-frontier delta
    path, $JT_ONLINE_INCREMENTAL) charges its device interim checks
    against the DELTA — ``delta_ops``, its check interval — not the
    whole prefix: on such workers a long tenant's per-tick cost is
    flat in prefix length (fleet.CostRouter.price_online_tick is the
    same arithmetic), so long tenants steer toward frontier-capable
    workers exactly as wide ones steer toward host-oracle-rich ones."""
    from .fleet import online_tick_costs
    rates = caps.get("rates") or {}
    ev = max(int(est_ops), 1)
    inc = bool(caps.get("incremental"))
    delta = min(ev, max(int(caps.get("delta_ops") or 0), 1))
    costs = online_tick_costs(
        est_w, ev, delta, incremental=inc,
        lane_ops_per_s=float(rates.get("lane_ops_per_s") or 1e8),
        host_s_per_event=float(rates.get("host_s_per_event") or 4e-4))
    dev, host = costs["wgl-device"], costs["host-oracle"]
    if not inc and not caps.get("event_route") and ev >= int(
            caps.get("event_route_events") or 8192):
        # No resume kernel: a long prefix re-dispatches monolithically.
        dev *= 4.0
    if est_w > int(caps.get("max_w", 1 << 30)):
        return host             # device not admitted on this worker
    return min(dev, host)


def cluster_idle(store: Store) -> bool:
    """Every incomplete run in the store carries a durable online
    verdict for its CURRENT segment — the whole cluster's work is
    done. (The inode check mirrors OnlineTenant._verdict_stale: a WAL
    rotated after finalization is new work, not idleness.)"""
    for name, ts in store.incomplete(include_salvaged=True):
        v = store.online_verdict(name, ts)
        if v is None:
            return False
        ino = v.get("ino")
        if ino is not None:
            try:
                if os.stat(store.run_dir(name, ts)
                           / WAL_FILE).st_ino != ino:
                    return False
            except OSError:
                pass
    return True


class ServiceWorker(OnlineDaemon):
    """One federated checking worker: an OnlineDaemon whose tenant set
    is governed by per-tenant leases in the shared store. Everything
    the base daemon proves (journal-gated restart, ladder behavior,
    parity-exact finalization) holds per tenant; this layer adds WHO
    serves it, cluster-wide admission, placement, and the storm
    breaker."""

    def __init__(self, store: Optional[Store] = None,
                 config: Optional[OnlineConfig] = None, *,
                 worker_id: str = "w0",
                 lease_ttl: Optional[float] = None,
                 claim_budget: Optional[int] = None,
                 stagger_s: Optional[float] = None,
                 placement_patience_s: Optional[float] = None,
                 rebalance_factor: float = 0.5,
                 rates: Optional[dict] = None,
                 faults=None):
        super().__init__(store=store, config=config, faults=faults)
        self.worker_id = worker_id
        self.ttl = float(lease_ttl if lease_ttl is not None
                         else lease_ttl_s())
        self.claim_budget = int(claim_budget if claim_budget is not None
                                else claim_budget_default())
        self.stagger_s = float(stagger_s if stagger_s is not None
                               else stagger_s_default())
        self.placement_patience_s = float(
            placement_patience_s if placement_patience_s is not None
            else _env_f("JT_SERVICE_PLACEMENT_PATIENCE_S", 2 * self.ttl))
        self.rebalance_factor = float(rebalance_factor)
        self._rates = dict(rates) if rates else None
        # Lease bookkeeping: {key: {"gen", "path", "renewed"}}.
        self.owned: Dict[Tuple[str, str], dict] = {}
        # Heartbeats run on their own daemon thread (started by
        # ``run()``), decoupled from tick latency: a first-check
        # kernel compile or a long drain must not stall renewals past
        # the TTL and lose the lease to a takeover of a live worker.
        # Tests that drive tick() directly (and simulate death by NOT
        # ticking) get no thread — determinism over liveness there.
        self._hb_lock = threading.Lock()
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_lost: set = set()
        for k in ("claims", "takeovers", "handoffs", "lease_lost",
                  "released", "claim_budget_deferred",
                  "takeover_staggered", "placement_deferred",
                  "cluster_refused", "wclass_refused",
                  "ingest_refused", "scale_advised"):
            self.stats.setdefault(k, 0)
        self._cluster_refused: set = set()
        self._wclass_refused: set = set()
        self._ingest_refused: set = set()
        self._first_seen: Dict[tuple, float] = {}
        # Release hold-down: a tenant we just handed back must not be
        # re-claimed by US before a peer had a whole TTL to take it —
        # otherwise release→re-claim thrashes inside one tick.
        self._released_at: Dict[tuple, float] = {}
        self._est_cache: Dict[tuple, tuple] = {}
        # Bounded: the full distribution lives on the
        # ``service.takeover_s`` histogram; this is the recent window
        # the registry/bench report — an always-on worker must not
        # grow its per-tick publish payload forever.
        self.takeover_latencies: deque = deque(maxlen=256)
        self._ingest_samples: deque = deque(maxlen=64)
        self._ingest_samples.append((time.monotonic(), 0))
        self._advice_cooldown_s = max(self.ttl, 5.0)
        self._budget: dict = load_budget(self.store)
        self._peers: Dict[str, dict] = {}
        # The base daemon's alert evaluator gains the cluster budget
        # (the ttfv SLO rule's threshold) and this worker's name.
        if self._alerts is not None:
            self._alerts.budget_fn = lambda: self._budget
            self._alerts.log.worker_id = worker_id

    # ------------------------------------------------------ capabilities
    def _caps(self) -> dict:
        """What this worker advertises in its registry entry — the
        inputs to every peer's placement pricing of a tenant on us."""
        if self._rates is not None:
            rates = dict(self._rates)
        else:
            from .fleet import router_rates
            rates = {k: router_rates()[k]
                     for k in ("lane_ops_per_s", "host_s_per_event")}
        from .ops.schedule import event_route_min_events
        ev_route = event_route_min_events()
        return {"max_tenants": self.cfg.max_tenants,
                "max_w": self.cfg.max_w,
                "rates": rates,
                "event_route": ev_route > 0,
                "event_route_events": ev_route or 8192,
                # The resident-frontier delta path: peers price this
                # worker's interim checks against the delta, not the
                # prefix (tenant_price).
                "incremental": bool(self.cfg.incremental),
                "delta_ops": max(self.cfg.check_interval_ops, 1)}

    def _svc_count(self, key: str, n: int = 1) -> None:
        self.stats[key] = self.stats.get(key, 0) + n
        telemetry.REGISTRY.counter(f"service.{key}").inc(n)

    def ingest_rate(self) -> float:
        """This worker's recent ingest rate (ops/s over a sliding
        window) — its contribution to the cluster ingest budget."""
        now = time.monotonic()
        cum = self.stats.get("ingested_ops", 0)
        self._ingest_samples.append((now, cum))
        while len(self._ingest_samples) > 2 and \
                now - self._ingest_samples[0][0] > 10.0:
            self._ingest_samples.popleft()
        t0, c0 = self._ingest_samples[0]
        return max(0.0, (cum - c0) / max(now - t0, 1.0))

    def live_peers(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Other workers whose registry heartbeat is fresh (within TTL
        + skew) — the admission and placement peer set."""
        now = time.time() if now is None else now
        out = {}
        for wid, rec in self.store.service_workers().items():
            if wid == self.worker_id:
                continue
            hb = float(rec.get("hb") or 0.0)
            if now - hb < self.ttl + lease_skew_s():
                out[wid] = rec
        return out

    # -------------------------------------------------------- admission
    def _estimate(self, name: str, ts: str) -> Tuple[int, int]:
        """(peak_w, n_ops) estimate for a candidate tenant, cached
        with a TTL-bounded refresh: a run discovered right after its
        header flush (estimate (0, 0)) must not price as free forever
        — the WAL grows, and wide-budget admission prices the CURRENT
        shape, one bounded probe per lease-TTL at most."""
        key = (name, ts)
        now = time.monotonic()
        cached = self._est_cache.get(key)
        if cached is not None and now - cached[1] < self.ttl:
            return cached[0]
        est = estimate_peak_w(self.store.run_dir(name, ts) / WAL_FILE)
        est = est if est is not None else (0, 0)
        self._est_cache[key] = (est, now)
        return est

    def _tenant_corr(self, name: str, ts: str) -> str:
        """The tenant's correlation id — run key + WAL segment inode
        (OnlineTenant.corr_id's formula, computed before the tenant
        object exists): stamped into the lease record and onto the
        takeover span, so a killed owner's tenant spans and the
        survivor's takeover connect in a merged trace."""
        try:
            ino = os.stat(self.store.run_dir(name, ts)
                          / WAL_FILE).st_ino
            return f"{name}/{ts}#{ino}"
        except OSError:
            return f"{name}/{ts}"

    def _jitter(self, key: tuple) -> float:
        """Deterministic per-(worker, tenant) takeover stagger in
        [0, stagger_s): every survivor computes a different delay for
        the same orphaned tenant, spreading the re-claims."""
        if self.stagger_s <= 0:
            return 0.0
        h = hashlib.sha256(
            f"{self.worker_id}:{key[0]}/{key[1]}".encode()).digest()
        return (h[0] / 255.0) * self.stagger_s

    def discover(self) -> None:
        """Lease-governed admission — the override that turns the
        single-process daemon into a federated worker. Walks the
        store's incomplete runs in jittered order and claims what the
        cluster budget, the claim budget, placement pricing, and the
        stagger allow."""
        cfg = self.cfg
        now = time.time()
        self._budget = budget = load_budget(self.store)
        self._peers = peers = self.live_peers(now)
        wide_w = int(budget.get("wide_w") or 0)
        own_active = sum(1 for t in self.tenants.values()
                        if t.status != "done")
        own_wide = sum(1 for t in self.tenants.values()
                       if t.status != "done" and t.peak_w > wide_w)
        cl_tenants = own_active + sum(
            int((p.get("usage") or {}).get("tenants") or 0)
            for p in peers.values())
        cl_wide = own_wide + sum(
            int((p.get("usage") or {}).get("wide") or 0)
            for p in peers.values())
        cl_ingest = self.ingest_rate() + sum(
            float((p.get("usage") or {}).get("ingest_ops_s") or 0.0)
            for p in peers.values())
        claims_left = self.claim_budget
        my_caps = self._caps()

        cands = [(name, ts) for name, ts
                 in self.store.incomplete(include_salvaged=True)
                 if (name, ts) not in self.tenants]
        # Jittered claim order: each worker walks the candidates in its
        # own per-tick shuffle, so two survivors racing for a dead
        # worker's tenants spread their first claims instead of
        # colliding on the same file.
        rng = random.Random(f"{self.worker_id}:{self.stats['ticks']}")
        rng.shuffle(cands)
        for key in cands:
            name, ts = key
            self._first_seen.setdefault(key, now)
            if now - self._released_at.get(key, -1e18) \
                    < max(self.ttl, 1.0):
                continue                    # just released: peers first
            v = self.store.online_verdict(name, ts)
            if v is not None and not self._verdict_current(key, v):
                v = None
            if v is not None:
                continue                    # finalized: nothing to own
            lpath = self.store.service_tenant_lease_path(name, ts)
            cur = _read_json(lpath)
            hb = float((cur or {}).get("hb") or 0.0)
            if cur is not None:
                if cur.get("done"):
                    continue
                if cur.get("worker") != self.worker_id and \
                        not cur.get("released") and \
                        (hb > now + lease_skew_s()
                         or now - hb < self.ttl + lease_skew_s()):
                    continue                # live somewhere else
            if own_active >= cfg.max_tenants:
                continue                    # this worker is full
            max_t = int(budget.get("max_tenants") or 0)
            if max_t and cl_tenants >= max_t:
                if key not in self._cluster_refused:
                    self._cluster_refused.add(key)
                    self._svc_count("cluster_refused")
                continue
            self._cluster_refused.discard(key)
            est_w, est_ops = self._estimate(name, ts)
            wide = est_w > wide_w
            max_wide = int(budget.get("max_wide_tenants") or 0)
            if wide and max_wide and cl_wide >= max_wide:
                if key not in self._wclass_refused:
                    self._wclass_refused.add(key)
                    self._svc_count("wclass_refused")
                continue
            self._wclass_refused.discard(key)
            max_ingest = float(budget.get("max_ingest_ops_s") or 0.0)
            if max_ingest and cl_ingest >= max_ingest:
                # One refusal EVENT per run (the sibling counters'
                # rule): a steadily saturated ingest budget must not
                # grow the SLO signal at tick rate.
                if key not in self._ingest_refused:
                    self._ingest_refused.add(key)
                    self._svc_count("ingest_refused")
                continue
            self._ingest_refused.discard(key)
            if peers and now - self._first_seen[key] \
                    < self.placement_patience_s:
                mine = tenant_price(est_w, est_ops, my_caps)
                best = self._best_peer_price(est_w, est_ops, peers)
                if best is not None and \
                        best < mine * self.rebalance_factor:
                    # A live peer is meaningfully cheaper and has
                    # capacity: leave the tenant for it (bounded by
                    # the patience window — nothing starves).
                    self._svc_count("placement_deferred")
                    continue
            if cur is not None and not cur.get("released") and \
                    cur.get("worker") != self.worker_id and hb > 0:
                # Stagger from the moment the lease became CLAIMABLE
                # (expiry + skew — the same instant every survivor
                # first sees it), not from bare expiry, which the
                # liveness check above has already aged past. Our OWN
                # lease (same-id restart) re-enters immediately — no
                # peer is racing us for it.
                age = now - (hb + self.ttl + lease_skew_s())
                if age < self._jitter(key):
                    self._svc_count("takeover_staggered")
                    continue
            if claims_left <= 0:
                # Storm breaker: this tick's claim budget is spent —
                # the remaining orphans wait for the next tick (or a
                # peer).
                self._svc_count("claim_budget_deferred")
                continue
            corr = self._tenant_corr(name, ts)
            gen = claim_lease(lpath, {"run": f"{name}/{ts}",
                                      "corr": corr},
                              self.worker_id, self.ttl)
            if gen is None:
                continue
            claims_left -= 1
            t = OnlineTenant(self, name, ts,
                             self.store.run_dir(name, ts))
            t.lease_gen = gen
            self.tenants[key] = t
            with self._hb_lock:
                # corr cached at claim time: renewals re-stamp it
                # into the lease record without re-statting the WAL
                # every sweep (the segment identity is fixed for the
                # lease's whole life — a rotation drops the tenant
                # through the verdict/journal staleness paths anyway).
                self.owned[key] = {"gen": gen, "path": lpath,
                                   "renewed": time.monotonic(),
                                   "corr": corr}
            self._svc_count("claims")
            if t.status != "done":
                self._count("admitted")
                own_active += 1
                cl_tenants += 1
                if wide:
                    own_wide += 1
                    cl_wide += 1
            if gen > 0 and cur is not None and cur.get("released"):
                # A voluntary rebalance handoff, not a failure: the
                # generation bumps (journal resume semantics are the
                # same) but the r08 dead-worker takeover figure must
                # not count it.
                self._svc_count("handoffs")
            elif gen > 0:
                self._svc_count("takeovers")
                lat = None
                if hb > 0:
                    # Orphan latency: how long the tenant sat between
                    # its old owner's lease expiring and this re-claim
                    # — the MULTICHIP_r08 takeover figure.
                    lat = max(0.0, now - (hb + self.ttl))
                    self.takeover_latencies.append(round(lat, 4))
                    telemetry.REGISTRY.histogram(
                        "service.takeover_s").observe(lat)
                # The takeover SPAN carries the tenant's correlation
                # id: in a merged cluster trace the dead owner's
                # check spans for this tenant and this survivor's
                # takeover share one id (the r13 acceptance artifact).
                with telemetry.correlation_scope(corr), \
                        telemetry.span("service.takeover",
                                       tenant=f"{name}/{ts}",
                                       gen=gen,
                                       worker=self.worker_id,
                                       orphan_s=lat):
                    telemetry.event("service.takeover.claimed",
                                    tenant=f"{name}/{ts}", gen=gen)
                log.info("worker %s took over tenant %s/%s at "
                         "generation %d", self.worker_id, name, ts,
                         gen)
        # Prune per-run bookkeeping for runs that left the incomplete
        # set (finalized with results.json, deleted...): an always-on
        # worker must not leak an entry per run it ever saw.
        alive = set(cands) | set(self.tenants)
        for d in (self._first_seen, self._released_at,
                  self._est_cache):
            for k in [k for k in d if k not in alive]:
                del d[k]
        for s in (self._cluster_refused, self._wclass_refused,
                  self._ingest_refused):
            s.intersection_update(alive)

    def _verdict_current(self, key: tuple, v: dict) -> bool:
        ino = v.get("ino")
        if ino is None:
            return True
        try:
            return os.stat(self.store.run_dir(*key)
                           / WAL_FILE).st_ino == ino
        except OSError:
            return True

    def _best_peer_price(self, est_w: int, est_ops: int,
                         peers: Dict[str, dict]) -> Optional[float]:
        best = None
        for p in peers.values():
            caps = p.get("caps") or {}
            usage = p.get("usage") or {}
            if int(usage.get("tenants") or 0) >= \
                    int(caps.get("max_tenants") or 1 << 30):
                continue                    # peer is full
            price = tenant_price(est_w, est_ops, caps)
            if best is None or price < best:
                best = price
        return best

    # ---------------------------------------------------------- leases
    def start_heartbeat(self) -> None:
        """Start the background lease-renewal thread (idempotent) —
        the serving loop's liveness guarantee: heartbeats land every
        TTL/3 even while a tick is stalled in a kernel compile or a
        long finalize drain."""
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()
        self._hb_thread = threading.Thread(
            target=self._hb_run, daemon=True,
            name=f"service-hb-{self.worker_id}")
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None

    def _hb_run(self) -> None:
        period = max(0.1, self.ttl / 3.0)
        while not self._hb_stop.wait(period):
            try:
                with self._hb_lock:
                    for key, lease in list(self.owned.items()):
                        if key in self._hb_lost:
                            continue
                        if renew_lease(lease["path"],
                                       {"run": f"{key[0]}/{key[1]}",
                                        "corr": lease.get("corr")},
                                       self.worker_id, lease["gen"],
                                       ttl=self.ttl):
                            lease["renewed"] = time.monotonic()
                        else:
                            self._hb_lost.add(key)
            except Exception:
                # The heartbeat is the worker's liveness — it must
                # survive any single renewal hiccup (transient I/O,
                # a racing mutation) and try again next period.
                log.warning("lease heartbeat sweep failed; retrying",
                            exc_info=True)

    def _renew_leases(self) -> None:
        nowm = time.monotonic()
        with self._hb_lock:
            for key, lease in list(self.owned.items()):
                t = self.tenants.get(key)
                extra = {"run": f"{key[0]}/{key[1]}",
                         "corr": lease.get("corr")}
                due = nowm - lease["renewed"] >= self.ttl / 3.0
                lost = key in self._hb_lost
                if not lost and due:
                    if renew_lease(lease["path"], extra,
                                   self.worker_id, lease["gen"],
                                   ttl=self.ttl):
                        lease["renewed"] = nowm
                    else:
                        lost = True
                if lost:
                    # The on-disk record names someone else: we
                    # stalled past the TTL and were taken over.
                    # Abandon cleanly — the usurper already resumed
                    # the journal.
                    self._hb_lost.discard(key)
                    self._svc_count("lease_lost")
                    log.warning("worker %s lost tenant %s/%s's "
                                "lease; abandoning it",
                                self.worker_id, *key)
                    if t is not None:
                        t.close()
                        self.tenants.pop(key, None)
                    del self.owned[key]
                    continue
                if due and t is not None and t.status == "tailing" \
                        and self._should_release(t):
                    # Rebalance ONLY at renewal cadence (anti-thrash).
                    if release_lease(lease["path"], extra,
                                     self.worker_id, lease["gen"]):
                        self._svc_count("released")
                        self._released_at[key] = time.time()
                        log.info("worker %s releasing tenant %s/%s "
                                 "to a cheaper-capable peer "
                                 "(rebalance at renewal)",
                                 self.worker_id, *key)
                        t.close()
                        del self.tenants[key]
                        del self.owned[key]

    def _should_release(self, t: OnlineTenant) -> bool:
        """Rebalance decision, evaluated ONLY at renewal cadence: hand
        a wide or long tenant to a live peer that prices it
        meaningfully cheaper. Conservative by construction — a factor-
        of-two advantage, capacity checked, never mid-finalize."""
        peers = self._peers
        if not peers:
            return False
        budget = self._budget
        est_w = t.peak_w
        est_ops = max(len(t.ops), t.checked_ops)
        caps = self._caps()
        wide = est_w > int(budget.get("wide_w") or 0) or \
            est_w > caps["max_w"]
        long_ = est_ops >= int(caps.get("event_route_events") or 8192) \
            and not caps.get("event_route")
        if not (wide or long_):
            return False
        mine = tenant_price(est_w, est_ops, caps)
        best = self._best_peer_price(est_w, est_ops, peers)
        return best is not None and best < mine * self.rebalance_factor

    def _retire_done(self) -> None:
        with self._hb_lock:
            for key, lease in list(self.owned.items()):
                t = self.tenants.get(key)
                if t is not None and t.status == "done":
                    mark_lease_done(lease["path"],
                                    {"run": f"{key[0]}/{key[1]}",
                                     "corr": lease.get("corr")},
                                    self.worker_id, lease["gen"])
                    del self.owned[key]

    # ------------------------------------------------------- registry
    def _ttfv_slice(self) -> Optional[dict]:
        snap = telemetry.snapshot()
        return (snap.get("histograms") or {}).get("online.ttfv_s")

    def publish(self) -> None:
        """This worker's registry entry — heartbeat, usage (the
        cluster-admission inputs), capability (the placement inputs),
        tenants (the web control plane's rows), and the per-worker
        TTFV slice (the cluster SLO merge's input)."""
        usage = {
            "tenants": sum(1 for t in self.tenants.values()
                           if t.status != "done"),
            "wide": sum(1 for t in self.tenants.values()
                        if t.status != "done" and t.peak_w
                        > int(self._budget.get("wide_w") or 0)),
            "ingest_ops_s": round(self.ingest_rate(), 3),
            # Wire-fed tenants admitted like file tenants: the count
            # is the only place the distinction surfaces (admission,
            # leases, takeover, SLOs are all transport-blind).
            "wire_tenants": sum(
                1 for t in self.tenants.values()
                if t.status != "done"
                and t.summary().get("wire")),
        }
        rec = {
            "service": SERVICE_MAGIC, "worker": self.worker_id,
            "pid": os.getpid(), "hb": time.time(),
            "usage": usage, "caps": self._caps(),
            "stats": dict(self.stats),
            "takeover_latency_s": list(self.takeover_latencies),
            "slo": self._ttfv_slice(),
            "tenants": {f"{k[0]}/{k[1]}":
                        {**t.summary(),
                         "gen": getattr(t, "lease_gen", None)}
                        for k, t in self.tenants.items()},
        }
        try:
            path = self.store.service_worker_path(self.worker_id)
            path.parent.mkdir(parents=True, exist_ok=True)
            atomic_write_json(path, rec)
        except Exception:
            log.debug("service registry publish failed", exc_info=True)

    def _maybe_scale_advice(self) -> None:
        slo = float(self._budget.get("slo_ttfv_s") or 0.0)
        if slo <= 0:
            return
        slices = [self._ttfv_slice()] + [
            p.get("slo") for p in self._peers.values()]
        merged = telemetry.merge_histogram_snapshots(
            {"histograms": {"online.ttfv_s": s}}
            for s in slices if s)
        p99 = (merged.get("online.ttfv_s") or {}).get("p99")
        if p99 is None or p99 <= slo:
            return
        backlog = any(t.pending for t in self._active()) or any(
            (name, ts) not in self.tenants
            and self.store.online_verdict(name, ts) is None
            for name, ts
            in self.store.incomplete(include_salvaged=True))
        if not backlog:
            return
        path = self.store.service_advice_path()
        cur = _read_json(path)
        now = time.time()
        if cur and now - float(cur.get("at") or 0.0) \
                < self._advice_cooldown_s:
            return
        want = len(self._peers) + 2       # live peers + me + one more
        atomic_write_json(path, {
            "service": SERVICE_MAGIC, "want_workers": want,
            "reason": f"online.ttfv_s p99 {p99:.3f}s > SLO {slo:.3f}s "
                      f"with backlog", "ttfv_p99_s": p99,
            "slo_ttfv_s": slo, "by": self.worker_id, "at": now})
        self._svc_count("scale_advised")
        log.warning("SLO breach: cluster ttfv p99 %.3fs > %.3fs; "
                    "published scale advice (want %d workers)", p99,
                    slo, want)

    # ------------------------------------------------------------- tick
    def tick(self) -> int:
        self._renew_leases()
        level = super().tick()
        self._retire_done()
        self.publish()
        self._maybe_scale_advice()
        return level

    def run(self, *, stop=None, ticks=None,
            until_idle: bool = False) -> dict:
        """The serving loop, with the lease heartbeat thread alive for
        its whole duration — tick latency (a first-check compile, a
        finalize drain) never costs a live worker its leases."""
        self.start_heartbeat()
        try:
            return super().run(stop=stop, ticks=ticks,
                               until_idle=until_idle)
        finally:
            self.stop_heartbeat()

    def idle(self) -> bool:
        """A federated worker is idle only when the CLUSTER is: its
        own tenants are done and every incomplete run in the store has
        a current durable verdict (a peer may still be working its
        share — --until-idle waits for the fleet, not the process)."""
        return super().idle() and cluster_idle(self.store)

    def summary(self) -> dict:
        return {"worker": self.worker_id,
                "stats": dict(self.stats),
                "takeover_latency_s": list(self.takeover_latencies),
                "tenants": {f"{k[0]}/{k[1]}": t.summary()
                            for k, t in self.tenants.items()}}

    def close(self) -> None:
        self.stop_heartbeat()
        super().close()
        self.publish()


def _env_f(name: str, dflt: float) -> float:
    try:
        return float(os.environ.get(name, dflt))
    except (TypeError, ValueError):
        return float(dflt)


# --------------------------------------------------------- orchestrator

def _spawn_service_worker(store: Store, worker_id: str,
                          args: List[str]):
    """One local service-worker subprocess — the same entry a remote
    host runs by hand (``jepsen-tpu serve --join BASE --worker-id W``).
    One virtual device per worker: service parallelism is across
    processes, exactly like the fleet."""
    import subprocess
    import sys

    from .provision import virtual_cpu_env

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(repo) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    try:
        devs = int(os.environ.get("JT_FLEET_WORKER_DEVICES", "1"))
    except ValueError:
        devs = 1
    if devs > 0:
        virtual_cpu_env(devs, env=env)
    wdir = store.service_dir() / "workers"
    wdir.mkdir(parents=True, exist_ok=True)
    logf = open(wdir / f"{worker_id}.log", "ab")
    p = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.cli", "serve",
         "--join", str(store.base), "--worker-id", worker_id] + args,
        env=env, stdout=logf, stderr=subprocess.STDOUT)
    p._jt_log = logf
    return p


def service_summary(store: Store,
                    workers: Optional[Dict[str, dict]] = None) -> dict:
    """Fold the shared namespace into one cluster view — what the
    orchestrator returns and the web ``/service`` control plane
    renders: per-worker registry entries, lease ledger, verdict roll-
    up, merged SLO histograms, scale advice. ``workers`` lets a caller
    that already read the registry (the web handler renders tenant
    rows from the same records) avoid a second directory scan."""
    workers = store.service_workers() if workers is None else workers
    # "takeovers" are dead-worker recoveries as the WORKERS counted
    # them; the raw lease-generation sum ("gen_bumps") also includes
    # voluntary rebalance handoffs and same-id re-claims.
    leases = {"tenants": 0, "done": 0, "gen_bumps": 0}
    tdir = store.service_dir() / "tenants"
    if tdir.exists():
        for f in sorted(tdir.glob("*.json")):
            le = _read_json(f) or {}
            leases["tenants"] += 1
            leases["done"] += bool(le.get("done"))
            leases["gen_bumps"] += max(0, int(le.get("gen") or 0))
    leases["takeovers"] = sum(
        int((w.get("stats") or {}).get("takeovers") or 0)
        for w in workers.values())
    verdicts: Dict[str, object] = {}
    invalid = 0
    for name, ts in store.incomplete(include_salvaged=True):
        v = store.online_verdict(name, ts)
        if v is not None:
            verdicts[f"{name}/{ts}"] = v.get("valid")
            if v.get("valid") is False:
                invalid += 1
    slo = telemetry.merge_histogram_snapshots(
        {"histograms": {"online.ttfv_s": w.get("slo")}}
        for w in workers.values() if w.get("slo"))
    takeover_lat = sorted(
        x for w in workers.values()
        for x in (w.get("takeover_latency_s") or []))
    return {
        "service": SERVICE_MAGIC,
        "budget": load_budget(store),
        "workers": {wid: {"hb": w.get("hb"),
                          "usage": w.get("usage"),
                          "stats": {k: (w.get("stats") or {}).get(k, 0)
                                    for k in ("ticks", "checks",
                                              "finalized", "claims",
                                              "takeovers", "handoffs",
                                              "lease_lost",
                                              "released")}}
                    for wid, w in workers.items()},
        "leases": leases,
        "verdicts": verdicts,
        "invalid": invalid,
        "valid": invalid == 0,
        "slo": slo.get("online.ttfv_s"),
        "takeover_latency_s": takeover_lat,
        "scale_advice": _read_json(store.service_advice_path()),
    }


def serve_store(store: Optional[Store] = None, *, workers: int = 2,
                model=None, budget: Optional[dict] = None,
                until_idle: bool = False, ticks: Optional[int] = None,
                stop=None, poll_s: float = 0.5,
                lease_ttl: Optional[float] = None,
                claim_budget: Optional[int] = None,
                worker_args: Optional[List[str]] = None,
                max_respawns: Optional[int] = None,
                **cfg_kw) -> dict:
    """The ``jepsen-tpu serve`` body: write the cluster budget ledger,
    run the worker pool (N local subprocesses via fleet.LocalPool —
    0 = one worker inline, the test/bench seam), babysit it (dead
    workers respawn bounded; lease expiry already redistributes their
    tenants either way), act on durable SLO scale advice, and return
    the merged cluster summary."""
    root = store if store is not None else DEFAULT
    root.service_dir().mkdir(parents=True, exist_ok=True)
    save_budget(root, budget)
    sp = telemetry.begin("service.serve", workers=workers)
    try:
        if workers <= 0:
            # The inline worker ticks at the caller's poll cadence —
            # one --poll knob, honored on every path (join /
            # subprocess / inline).
            cfg_kw.setdefault("poll_s", poll_s)
            cfg = OnlineConfig(model=model, **cfg_kw)
            w = ServiceWorker(store=root, config=cfg,
                              worker_id="w0", lease_ttl=lease_ttl,
                              claim_budget=claim_budget)
            try:
                w.run(stop=stop, ticks=ticks, until_idle=until_idle)
            finally:
                w.close()
        else:
            args = list(worker_args or [])
            if until_idle:
                args.append("--until-idle")
            bounded = bool(ticks)
            pool = LocalPool(
                lambda wid: _spawn_service_worker(root, wid, args),
                workers, max_respawns=max_respawns).start()
            babysit_s = min(poll_s, 0.5)
            try:
                while True:
                    if stop is not None and stop.is_set():
                        break
                    idle = cluster_idle(root)
                    # Tick-bounded workers (--ticks rides through to
                    # them) exit naturally: don't respawn, and follow
                    # them out once the pool drains.
                    pool.reap(respawn=not idle and not bounded)
                    pool.apply_scale_advice(root.service_advice_path())
                    if not pool.procs:
                        if bounded or (until_idle and idle):
                            break
                        # Pool drained with work remaining (workers
                        # crashed, or a run landed right after an
                        # idle drain): revive within the respawn
                        # budget, never spin an empty pool forever.
                        if not pool.revive():
                            raise RuntimeError(
                                "every service worker exited with "
                                "work remaining and the respawn "
                                "budget exhausted; see "
                                f"{root.service_dir()}/workers/*.log")
                    time.sleep(babysit_s)
            finally:
                pool.shutdown(timeout=max(
                    15.0, 3 * float(lease_ttl or lease_ttl_s())))
    finally:
        sp.end()
    return service_summary(root)
