"""Fleet campaign orchestrator: sharded multi-worker dispatch with
cost-routed backends.

Five PRs built single-process machinery — the streaming bucket
scheduler, the resilience ladder, durable checkpoints, on-device
synthesis, the online daemon. This layer turns them into a fleet:

  * **Sharding.** A campaign (synth seed sweep, blind-sweep recheck of
    a stored test, fuzz rounds) becomes a WORK SPEC file plus a lease
    directory under ``store/<name>/fleet/``. Worker processes — local
    subprocesses spawned by the orchestrator, or processes started by
    hand on other hosts against the same (shared) store — claim seed
    ranges by lease, heartbeat while working, and write one durable
    summary per unit. Nothing but the filesystem coordinates them,
    which is exactly what makes the same spec multi-host-ready.

  * **Leases (the cluster-wide checkpoint).** The PR-5 durability
    format is extended, not replaced: per-unit summaries
    (``seed-<s>.json``, the exact ``run_synth_seeds`` artifact) and
    per-seed ChunkJournals stay the completed-work record; the lease
    files add WHO may produce them. A lease is claimed by exclusive
    create, renewed by heartbeat, and expires when its heartbeat goes
    stale (``JT_LEASE_TTL_S``) — a SIGKILLed worker's leases lapse and
    survivors take them over at a bumped generation, skipping every
    unit whose summary already landed: ZERO completed seeds re-run,
    and the in-flight seed resumes its journal with zero re-dispatched
    histories.

  * **Cost-based routing.** Each checkable unit is priced against the
    measured dispatch-overhead/op-model numbers the scheduler already
    owns (ops/schedule.py): the fused device WGL scan at
    ``2^W``/lane-rate, the MXU graph closure at ``mxu_op_model`` MACs,
    the host oracle at its near-W-flat per-event rate. The router
    sends each unit to the cheapest CAPABLE backend instead of the
    fixed per-family path — wide sub-histories stop paying exponential
    device frontiers when the host is cheaper, graph workloads stop
    paying DFS when the MXU isn't, and long histories ride the
    event-chunked kernel by the same arithmetic
    (``BucketScheduler.event_route_events``).

  * **Aggregation.** Workers write ordinary per-unit stores +
    journals; ``merge_campaign`` folds them into one campaign-level
    ``fleet/results.json`` and the orchestrator publishes a standard
    run dir (``store/<name>/<ts>/results.json``) so the web index
    renders the whole fleet as a single row with a ``fleet`` badge.

``jepsen-tpu fleet`` (cli.py) is the operator surface; ``--join DIR
--worker-id W`` runs one worker against an existing campaign dir (the
multi-host entry). doc/fleet.md documents the model.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import socket
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from . import telemetry
from .store import (FLEET_DIR, CampaignMismatch, ChunkJournal, Store,
                    DEFAULT, atomic_write_json, spec_digest)

log = logging.getLogger("jepsen.fleet")

FLEET_MAGIC = "JTFLEET1"
SPEC_FILE = "fleet.json"
LEASES_DIR = "leases"
RESULTS_FILE = "results.json"

# Spec fields that IDENTIFY a campaign: a --resume against a dir whose
# spec differs in any of these is a different campaign (refused, the
# CampaignCheckpoint discipline). Worker count / TTLs may differ.
IDENTITY_KEYS = ("fleet", "name", "kind", "model", "synth", "units",
                 "spec", "test", "timestamps")


def max_local_workers() -> int:
    """$JT_FLEET_MAX_LOCAL_WORKERS: cap on worker processes spawned on
    THIS host (0 = uncapped). Default: the host's core count — local
    workers are CPU-bound jax processes, and oversubscribing them
    regresses outright (the 2-core MULTICHIP_r07 probe measured 4
    local workers at 0.92x of one); width beyond the cores belongs on
    more hosts (``fleet --join``), not more processes."""
    env = os.environ.get("JT_FLEET_MAX_LOCAL_WORKERS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return os.cpu_count() or 1


def lease_ttl_s() -> float:
    """$JT_LEASE_TTL_S: heartbeat staleness past which a worker's
    lease is forfeit and its seeds redistribute. Default 15 s — many
    heartbeat periods (ttl/3), few enough that a killed worker's share
    of a campaign restarts within seconds."""
    try:
        return max(0.5, float(os.environ.get("JT_LEASE_TTL_S", "15")))
    except ValueError:
        return 15.0


def lease_skew_s() -> float:
    """$JT_LEASE_SKEW_S: wall-clock skew allowance between lease
    writers on a shared store. Expiry compares the local clock against
    the lease file's own stamped wall time, so two hosts whose clocks
    disagree by up to this much cannot double-own a unit: the live
    window extends by the allowance, and a lease stamped in the local
    FUTURE is never stolen at all (refused + counted —
    ``lease_skew_rejects``). Default 2 s; NFS-grade skew deployments
    raise it."""
    try:
        return max(0.0, float(os.environ.get("JT_LEASE_SKEW_S", "2")))
    except ValueError:
        return 2.0


# ------------------------------------------------------ cost-based router

# Process-wide probe-measured rate overlay (probe_and_persist /
# set_measured_rates): defaults < measured < explicit env pins.
_MEASURED_RATES: Dict[str, float] = {}

# Rate keys and the env pins that override them (env beats probe: a
# deployment that pins its crossover means it, exactly like
# $JT_DISPATCH_OVERHEAD_US).
_RATE_ENV = (("lane_ops_per_s", "JT_DISPATCH_COST_LANE_OPS_PER_S"),
             ("host_s_per_event", "JT_HOST_S_PER_EVENT"),
             ("macs_per_s", "JT_GRAPH_MACS_PER_S"),
             ("graph_host_s_per_edge", "JT_GRAPH_HOST_S_PER_EDGE"),
             ("pallas_lane_ops_per_s", "JT_PALLAS_LANE_OPS_PER_S"),
             ("dc_events_per_s", "JT_DC_EVENTS_PER_S"),
             ("ingest", "JT_INGEST_OPS_PER_S"))


def set_measured_rates(rates: Optional[Dict[str, float]]) -> None:
    """Install probe-measured per-backend rates as the process-wide
    overlay every fresh CostRouter prices from (None/{} clears). Only
    known rate keys with truthy values apply — a failed probe never
    zeroes a working default."""
    _MEASURED_RATES.clear()
    if rates:
        known = {k for k, _ in _RATE_ENV}
        _MEASURED_RATES.update({k: float(v) for k, v in rates.items()
                                if k in known and v})


def router_rates() -> Dict[str, float]:
    """The measured/assumed backend rates the router prices against.
    ``lane_ops_per_s`` is the scheduler's dispatch-cost rate (the same
    pessimism class the W-class DP and watchdog use);
    ``host_s_per_event`` calibrates the host oracle's near-W-flat
    per-event cost from the measured W15/W16 device/native crossover
    (ops/linearize.py's wide-tail comment: ~0.4 s per ~1k-event row);
    ``macs_per_s`` prices the MXU closure; ``graph_host_s_per_edge``
    the host DFS; ``pallas_lane_ops_per_s`` the Pallas WGL megakernel
    (0 = unprobed/unavailable, which prices it out of every route);
    ``dc_events_per_s`` the decrease-and-conquer peel loop's W-flat
    near-linear event rate (same 0 = priced-out convention, so an
    unprobed process routes bit-identically to the pre-dc tree).
    Precedence: defaults < probe-measured overlay (set_measured_rates
    / probe_and_persist / persisted store rates) < explicit env pins —
    a deployment that measures its own crossover pins it, exactly like
    $JT_DISPATCH_OVERHEAD_US."""
    from .ops.schedule import DISPATCH_COST_LANE_OPS_PER_S

    out = {
        "lane_ops_per_s": DISPATCH_COST_LANE_OPS_PER_S,
        "host_s_per_event": 4e-4,
        "macs_per_s": 1e12,
        "graph_host_s_per_edge": 2e-6,
        "pallas_lane_ops_per_s": 0.0,
        "dc_events_per_s": 0.0,
        # Wire-ingest landing rate (ops/s) — not a checker backend but
        # the same pricing surface: the ingest plane's Retry-After is
        # priced off it when positive ($JT_INGEST_OPS_PER_S, or a
        # measured overlay); 0 falls back to the fixed
        # $JT_INGEST_RETRY_AFTER_S.
        "ingest": 0.0,
    }
    out.update(_MEASURED_RATES)
    for key, env in _RATE_ENV:
        v = os.environ.get(env)
        if v is not None:
            try:
                out[key] = float(v)
            except ValueError:
                pass
    return out


# ------------------------------ probe-refreshed, store-persisted rates

ROUTER_RATES_DIR = "router-rates"

_PROBED_RATES: Optional[Dict[str, float]] = None


def rates_path(store_dir, host: Optional[str] = None) -> Path:
    """This host's rate file: one file PER HOST (never a shared
    read-modify-write document — concurrent workers on different
    hosts must not race each other's calibration, the same reason
    the lease protocol claims with O_EXCL)."""
    host = host or socket.gethostname()
    safe = "".join(c if c.isalnum() or c in "-._" else "_"
                   for c in host) or "unknown-host"
    return Path(store_dir) / ROUTER_RATES_DIR / f"{safe}.json"


def persist_rates(store_dir, rates: Dict[str, float],
                  host: Optional[str] = None) -> Path:
    """Record this host's measured backend rates in the shared store
    (one JSON file per hostname) so fleet workers on heterogeneous
    hosts route from measurements, not defaults. Only known rate keys
    persist; each host owns its own file outright, so workers never
    clobber each other's calibration."""
    path = rates_path(store_dir, host)
    path.parent.mkdir(parents=True, exist_ok=True)
    known = {k for k, _ in _RATE_ENV}
    atomic_write_json(path, {
        "host": host or socket.gethostname(),
        "rates": {k: float(v) for k, v in rates.items()
                  if k in known and v},
        "ts": time.time(),
    })
    return path


def load_persisted_rates(store_dir,
                         host: Optional[str] = None) -> Dict[str, float]:
    """This host's persisted rate entry (empty when it never probed —
    another host's calibration is wrong by definition on a
    heterogeneous fleet, so there is no cross-host fallback)."""
    ent = _read_json(rates_path(store_dir, host))
    if not isinstance(ent, dict):
        return {}
    known = {k for k, _ in _RATE_ENV}
    return {k: float(v) for k, v in (ent.get("rates") or {}).items()
            if k in known and v}


def probe_and_persist(store_dir=None, *, force: bool = False
                      ) -> Dict[str, float]:
    """The startup rate probe: measure the WGL device backends
    (lax.scan and Pallas, ops.pallas_wgl.probe_rates; the
    decrease-and-conquer peel loop, ops.dc_monitor.probe_rates) plus
    the host oracle's per-event cost on one tiny workload, install the result
    as the process-wide overlay (set_measured_rates), and persist it
    under this host's key when a store dir is given. Memoized per
    process — the probe pays two tiny kernel compiles once."""
    global _PROBED_RATES
    if _PROBED_RATES is None or force:
        from .ops.pallas_wgl import probe_rates
        out = probe_rates()
        rates = {"lane_ops_per_s": out.get("lane_ops_per_s") or 0.0,
                 "pallas_lane_ops_per_s":
                     out.get("pallas_lane_ops_per_s") or 0.0}
        try:
            from .ops.dc_monitor import probe_rates as dc_probe
            rates["dc_events_per_s"] = (
                dc_probe().get("dc_events_per_s") or 0.0)
        except Exception:
            rates["dc_events_per_s"] = 0.0
        try:
            from .checkers.linearizable import wgl_check
            from .workloads.synth import synth_cas_history
            hs = [synth_cas_history(7 + i, n_procs=3, n_ops=40)
                  for i in range(3)]
            t0 = time.perf_counter()
            for h in hs:
                wgl_check(cas_register_model(), h)
            dt = time.perf_counter() - t0
            ev = sum(len(h) for h in hs)
            if ev and dt > 0:
                rates["host_s_per_event"] = dt / ev
        except Exception:
            pass
        _PROBED_RATES = rates
    set_measured_rates(_PROBED_RATES)
    if store_dir is not None:
        try:
            persist_rates(store_dir, _PROBED_RATES)
        except Exception:
            log.warning("could not persist router rates", exc_info=True)
    return dict(_PROBED_RATES)


def cas_register_model():
    from .models.core import cas_register
    return cas_register()


def pending_window(history) -> int:
    """A history's peak pending window — the encoder's ``max_live``
    rule (invokes allocate a slot, only ok-completions free it) as one
    cheap host scan, no encode."""
    from .history.ops import INVOKE, OK

    live = peak = 0
    for op in history:
        if not op.is_client:
            continue
        if op.type == INVOKE:
            live += 1
            peak = max(peak, live)
        elif op.type == OK:
            live = max(0, live - 1)
    return peak


def estimate_w(history) -> int:
    """The unit's POST-PARTITION W class: KV-valued histories strain
    per key before encoding (ops.partition), so what the device will
    actually pay is the widest per-key window, not the merged one."""
    from .independent import history_keys, subhistory

    keys = history_keys(history)
    if not keys:
        return pending_window(history)
    return max(pending_window(subhistory(k, history)) for k in keys)


def classify_history(history) -> str:
    """Which checker family decides a unit: ``txn`` for transactional
    histories (the isolation-ladder certifier), ``graph`` for
    histories whose vocabulary lowers to dependency graphs
    (list-append / adya-g2 — ops.graph.extract_graph's own sniff
    rule), ``wgl`` for everything the linearizable frontier scan
    owns."""
    fs = {op.f for op in history if op.is_client}
    if "txn" in fs:
        return "txn"
    return "graph" if ("append" in fs or "insert" in fs) else "wgl"


def online_tick_costs(w: int, prefix_events: int, delta_events: int, *,
                      lane_ops_per_s: float, host_s_per_event: float,
                      overhead_s: float = 0.0,
                      incremental: bool = True) -> Dict[str, float]:
    """THE delta-path pricing arithmetic, shared by
    CostRouter.price_online_tick (router surface) and
    service.tenant_price (placement over peer caps) so the two
    surfaces cannot drift: the device term charges the delta when the
    worker carries a resident frontier, the whole prefix otherwise;
    the host oracle always pays the prefix."""
    dev_ev = (max(int(delta_events), 1) if incremental
              else max(int(prefix_events), 1))
    dev = (dev_ev * float(1 << min(max(int(w), 0), 30))
           / lane_ops_per_s + overhead_s)
    host = max(int(prefix_events), 1) * host_s_per_event
    return {"wgl-device": dev, "host-oracle": host}


class CostRouter:
    """Prices each checkable unit per backend and picks the cheapest
    CAPABLE one. Units are (family, W-or-vertex-bucket, length); the
    device terms amortize the measured per-dispatch overhead
    (ops.schedule.measure_dispatch_overhead_us) over the rows that
    would share the dispatch. Records every choice for the campaign's
    router summary."""

    #: W past which the packed frontier no longer fits any device
    #: route (beyond the frontier-sharded mask axis) — the host oracle
    #: is the only capable backend. $JT_ROUTER_MAX_W overrides.
    MAX_DEVICE_W = 22

    def __init__(self, rates: Optional[dict] = None,
                 max_device_w: Optional[int] = None,
                 store_dir=None):
        base = router_rates()
        if store_dir is not None:
            # Heterogeneous-fleet calibration: this host's persisted
            # probe measurements (probe_and_persist) beat defaults;
            # explicit ``rates`` beat everything.
            base.update(load_persisted_rates(store_dir))
        self.rates = {**base, **(rates or {})}
        if max_device_w is not None:
            self.max_device_w = int(max_device_w)
        else:
            try:
                self.max_device_w = int(
                    os.environ.get("JT_ROUTER_MAX_W", ""))
            except ValueError:
                self.max_device_w = self.MAX_DEVICE_W
        self.chosen: Dict[str, int] = {}
        self.est_cost_s: Dict[str, float] = {}

    def _overhead_s(self) -> float:
        from .ops.schedule import measure_dispatch_overhead_us
        return measure_dispatch_overhead_us() * 1e-6

    # ---------------------------------------------------------- pricing
    def price_wgl(self, w: int, n_events: int,
                  rows: int = 1, *, dc: bool = False) -> Dict[str, float]:
        """Per-unit cost of a linearizable unit at post-partition
        window ``w`` and ``n_events`` history lines: the device scan
        pays 2^w frontier lanes per event plus its amortized dispatch
        overhead; the host oracle's per-event cost is near W-flat.
        The Pallas megakernel (``wgl-pallas``) prices only when it is
        CAPABLE (narrow window, kernel available) and PROBED (a
        measured rate exists — startup probe, persisted store entry,
        or env pin); absent either, the cost dict is bit-identical to
        the pre-pallas router. The decrease-and-conquer peel loop
        (``wgl-dc``) prices under the same contract — capable
        (``dc=True``: the caller sniffed a register-class unit,
        ops.dc_monitor.dc_capable_history), available
        ($JT_ROUTER_DC), probed (``dc_events_per_s``) — and is the
        only device term FLAT in W: events/rate, no 2^w factor."""
        dev = (n_events * float(1 << min(int(w), 30))
               / self.rates["lane_ops_per_s"]
               + self._overhead_s() / max(int(rows), 1))
        host = n_events * self.rates["host_s_per_event"]
        costs = {"wgl-device": dev, "host-oracle": host}
        pr = float(self.rates.get("pallas_lane_ops_per_s") or 0.0)
        if pr > 0:
            from .ops.pallas_wgl import pallas_available, pallas_supports
            if pallas_available() and pallas_supports(1, w):
                costs["wgl-pallas"] = (
                    n_events * float(1 << min(int(w), 30)) / pr
                    + self._overhead_s() / max(int(rows), 1))
        if dc:
            dr = float(self.rates.get("dc_events_per_s") or 0.0)
            if dr > 0:
                from .ops.dc_monitor import dc_available
                if dc_available():
                    costs["wgl-dc"] = (
                        n_events / dr
                        + self._overhead_s() / max(int(rows), 1))
        return costs

    def price_online_tick(self, w: int, prefix_events: int,
                          delta_events: int, *,
                          incremental: bool = True) -> Dict[str, float]:
        """Per-tick cost of one ONLINE interim check (the daemon's
        rolling prefix check): the resident-frontier delta path
        (ops.schedule.ResidentFrontier, $JT_ONLINE_INCREMENTAL)
        charges the device scan only for the events that arrived since
        the last decided prefix — per-tick cost flat in prefix length
        — while full-recheck mode re-pays the whole prefix every tick.
        The host oracle has no carried state, so it always pays the
        prefix. Carried dispatch rides the lax.scan resume kernel
        exclusively (the Pallas megakernel's VMEM-resident frontier
        never round-trips between launches — pallas_wgl
        .pallas_supports_resume), so no pallas term appears here.
        service.tenant_price prices placement through the same shared
        arithmetic (online_tick_costs)."""
        return online_tick_costs(
            w, prefix_events, delta_events, incremental=incremental,
            lane_ops_per_s=self.rates["lane_ops_per_s"],
            host_s_per_event=self.rates["host_s_per_event"],
            overhead_s=self._overhead_s())

    def price_graph(self, n_vertices: int, n_edges: int,
                    rows: int = 1) -> Dict[str, float]:
        """Per-unit cost of a dependency-graph unit: the MXU closure
        pays mxu_op_model MACs at the padded vertex bucket; the host
        DFS is linear in vertices + edges."""
        from .ops.graph import bucket_v, mxu_op_model
        m = mxu_op_model(bucket_v(max(int(n_vertices), 1)))
        dev = (m["macs"] / self.rates["macs_per_s"]
               + self._overhead_s() / max(int(rows), 1))
        host = ((n_vertices + n_edges)
                * self.rates["graph_host_s_per_edge"])
        return {"graph-device": dev, "graph-host": host}

    def price_txn(self, n_vertices: int, n_edges: int,
                  rows: int = 1) -> Dict[str, float]:
        """Per-unit cost of a transactional (isolation-ladder) unit:
        the MXU ladder closure pays txn_op_model MACs (5 planes + the
        SI composition matmul) at the padded vertex bucket; the host
        DFS oracle is linear in vertices + edges per plane."""
        from .ops.graph import bucket_v
        from .ops.txn_graph import N_CYC_PLANES, txn_op_model
        m = txn_op_model(bucket_v(max(int(n_vertices), 1)))
        dev = (m["macs"] / self.rates["macs_per_s"]
               + self._overhead_s() / max(int(rows), 1))
        host = (N_CYC_PLANES * (n_vertices + n_edges)
                * self.rates["graph_host_s_per_edge"])
        return {"txn-device": dev, "txn-host": host}

    def _record(self, backend: str, costs: Dict[str, float]) -> None:
        self.chosen[backend] = self.chosen.get(backend, 0) + 1
        self.est_cost_s[backend] = (self.est_cost_s.get(backend, 0.0)
                                    + costs[backend])

    def choose_wgl(self, w: int, n_events: int, rows: int = 1, *,
                   dc: bool = False) -> Tuple[str, Dict[str, float]]:
        costs = self.price_wgl(w, n_events, rows, dc=dc)
        if w > self.max_device_w:
            # Past the frontier-sharded mask axis no 2^w backend is
            # capable — but the peel loop carries no frontier at all,
            # so it stays eligible at ANY width.
            elig = {k: v for k, v in costs.items()
                    if k in ("host-oracle", "wgl-dc")}
            backend = min(elig, key=elig.get)
        else:
            backend = min(costs, key=costs.get)
        self._record(backend, costs)
        return backend, costs

    def choose_graph(self, n_vertices: int, n_edges: int,
                     rows: int = 1) -> Tuple[str, Dict[str, float]]:
        costs = self.price_graph(n_vertices, n_edges, rows)
        backend = min(costs, key=costs.get)
        self._record(backend, costs)
        return backend, costs

    def choose_txn(self, n_vertices: int, n_edges: int,
                   rows: int = 1) -> Tuple[str, Dict[str, float]]:
        costs = self.price_txn(n_vertices, n_edges, rows)
        backend = min(costs, key=costs.get)
        self._record(backend, costs)
        return backend, costs

    def wgl_check_kwargs(self, spec) -> dict:
        """Scheduler knobs for a synth seed batch, cost-derived: the
        wide-tail host crossover (``min_device_batch`` — rows below
        which a W>=16 bucket's amortized dispatch overhead makes the
        native host engine cheaper) comes out of the same arithmetic
        instead of a caller-fixed constant. Post-partition, a cas
        spec's per-key window is bounded by its process count and its
        per-key event count by 2*n_ops/n_keys."""
        from .ops.linearize import DATA_MAX_SLOTS
        ev = max(1, 2 * spec.n_ops // max(spec.n_keys, 1))
        w = min(spec.n_procs, spec.n_ops, self.max_device_w)
        host_row = ev * self.rates["host_s_per_event"]
        dev_row = (ev * float(1 << max(int(w), DATA_MAX_SLOTS))
                   / self.rates["lane_ops_per_s"])
        if dev_row >= host_row:
            mdb = 4096                   # host beats the scan outright
        else:
            mdb = min(4096, max(1, int(self._overhead_s()
                                       / max(host_row - dev_row, 1e-12))
                                + 1))
        return {"min_device_batch": mdb}

    def table(self, ws=(4, 8, 12, 16, 18, 20),
              events: int = 1000) -> List[dict]:
        """The router cost table (doc/fleet.md, bench): per W, both
        backends' prices and the winner — the crossover made visible."""
        out = []
        for w in ws:
            costs = self.price_wgl(w, events, dc=True)
            if w > self.max_device_w:
                elig = {k: v for k, v in costs.items()
                        if k in ("host-oracle", "wgl-dc")}
                backend = min(elig, key=elig.get)
            else:
                backend = min(costs, key=costs.get)
            out.append({"W": w, "events": events, "backend": backend,
                        **{k: round(v, 6) for k, v in costs.items()}})
        return out

    def summary(self) -> dict:
        return {"chosen": dict(self.chosen),
                "est_cost_s": {k: round(v, 6)
                               for k, v in self.est_cost_s.items()},
                "max_device_w": self.max_device_w,
                "rates": self.rates}


def route_check(model, histories: Sequence, *, router: Optional[
        CostRouter] = None, details: str = "invalid") -> Tuple[
            List[dict], dict]:
    """Check a mixed corpus with every unit cost-routed: classify each
    history (wgl vs graph family), price it, and dispatch each backend
    group as one batch — fused device WGL
    (ops.linearize.check_batch_columnar), MXU graph closure
    (checkers.cycle.check_graphs_batch), or the host oracles. Returns
    (per-history result dicts in input order, each tagged with its
    ``backend``, and the routing summary). This is the fleet recheck
    path's engine and the router-parity test seam."""
    router = router if router is not None else CostRouter()
    n = len(histories)
    plan: List[Tuple[int, str]] = []
    graphs: Dict[int, object] = {}
    for i, h in enumerate(histories):
        fam = classify_history(h)
        if fam == "txn":
            from .ops.txn_graph import extract_txn_graph
            g = extract_txn_graph(h)
            graphs[i] = g
            edges = sum(int(e.shape[0]) for e in g.edges.values())
            backend, _ = router.choose_txn(g.n, edges)
        elif fam == "graph":
            from .ops.graph import extract_graph
            g = extract_graph(h)
            graphs[i] = g
            edges = sum(int(e.shape[0]) for e in g.edges.values())
            backend, _ = router.choose_graph(g.n, edges)
        else:
            from .ops.dc_monitor import dc_capable_history
            backend, _ = router.choose_wgl(estimate_w(h), len(h),
                                           dc=dc_capable_history(h))
        plan.append((i, backend))
    groups: Dict[str, List[int]] = {}
    for i, backend in plan:
        groups.setdefault(backend, []).append(i)
    results: List[Optional[dict]] = [None] * n

    # The WGL device groups ride the same fused columnar pipeline
    # with the scheduler's per-chunk backend PINNED to the router's
    # group decision (the router already decided the crossover;
    # letting the scheduler re-price per chunk — or pick up a stray
    # JT_WGL_BACKEND force — would let dispatches disagree with the
    # plan and with the ``backend`` tag on the results).
    for group, forced in (("wgl-device", "xla"),
                          ("wgl-pallas", "pallas"),
                          ("wgl-dc", "dc")):
        if not groups.get(group):
            continue
        from .ops.linearize import check_batch_columnar
        idx = groups[group]
        rs = check_batch_columnar(
            model, [histories[i] for i in idx], details=details,
            scheduler_opts={"wgl_backend": forced})
        for i, r in zip(idx, rs):
            results[i] = r
    if groups.get("host-oracle"):
        idx = groups["host-oracle"]
        hs = [histories[i] for i in idx]
        rs = None
        try:
            from .native import check_batch_native
            rs = check_batch_native(model, hs)
        except Exception:
            rs = None
        if rs is None:
            from .checkers.linearizable import wgl_check
            rs = [wgl_check(model, h) for h in hs]
        for i, r in zip(idx, rs):
            r.setdefault("provenance", "host-oracle")
            results[i] = r
    if groups.get("graph-device"):
        from .checkers.cycle import check_graphs_batch
        idx = groups["graph-device"]
        rs = check_graphs_batch([graphs[i] for i in idx])
        for i, r in zip(idx, rs):
            results[i] = r
    if groups.get("graph-host"):
        from .ops.graph import check_graph_host
        for i in groups["graph-host"]:
            results[i] = check_graph_host(graphs[i],
                                          provenance="host-oracle")
    if groups.get("txn-device"):
        from .isolation import certify_batch
        idx = groups["txn-device"]
        rs = certify_batch([graphs[i] for i in idx])
        for i, r in zip(idx, rs):
            results[i] = r
    if groups.get("txn-host"):
        from .ops.txn_graph import check_txn_host
        for i in groups["txn-host"]:
            results[i] = check_txn_host(graphs[i],
                                        provenance="host-oracle")
    for (i, backend) in plan:
        results[i]["backend"] = backend
    routing = {"units": n,
               "backends": {b: len(ix) for b, ix in groups.items()},
               **router.summary()}
    return results, routing  # type: ignore[return-value]


# ------------------------------------------------------------- leases

def _read_json(path) -> Optional[dict]:
    try:
        return json.loads(Path(path).read_text())
    except Exception:
        return None


def _lease_path(cdir: Path, chunk_id: int) -> Path:
    return cdir / LEASES_DIR / f"chunk-{chunk_id}.json"


def lease_payload(extra: dict, worker: str, gen: int,
                  done: bool = False, hb: Optional[float] = None) -> dict:
    """The generic lease record: WHO (worker/pid/host), WHEN (the
    stamped wall-time heartbeat every expiry decision compares
    against), the takeover generation, and caller fields (``extra``) —
    fleet chunks carry their unit list, service tenants their run
    key."""
    return {**extra, "worker": worker, "pid": os.getpid(),
            "host": socket.gethostname(),
            "hb": time.time() if hb is None else float(hb),
            "gen": int(gen), "done": bool(done)}


def claim_lease(path: Path, extra: dict, worker: str,
                ttl: float) -> Optional[int]:
    """Try to claim one lease file — the shared ownership primitive of
    the fleet campaign (seed chunks) and the checking service (live
    tenants). Returns the claimed generation (0 = first owner, >0 =
    takeover of an expired lease) or None when the unit is done or
    someone else holds a live lease.

    First claim is an atomic hard-link of a fully-written payload (two
    fresh workers cannot both win, and no reader ever sees an empty or
    partial lease file); takeover is atomic-replace at generation+1
    with a read-back — the loser of a takeover race sees the other
    worker's record and walks away. Expiry compares the local wall
    clock against the lease's OWN stamped time with a
    ``$JT_LEASE_SKEW_S`` allowance, and a lease stamped in the local
    future is refused outright (``lease_skew_rejects``): clock-skewed
    hosts on a shared store cannot double-own a unit."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = lease_payload(extra, worker, 0)
    # Worker id in the temp name: pids alone can collide across hosts
    # on a shared store.
    tmp = path.with_name(f"{path.name}.claim.{worker}.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, path)
        return 0
    except FileExistsError:
        pass
    finally:
        try:
            tmp.unlink()
        except FileNotFoundError:
            pass
    cur = _read_json(path)
    if cur is None:
        # Unreadable lease: only a crashed writer of a bygone era can
        # leave one (claims land atomically above, renew/takeover use
        # atomic_write_json) — but stay conservative and treat a
        # RECENT file as live rather than stealing it.
        try:
            if time.time() - path.stat().st_mtime < ttl:
                return None
        except OSError:
            return None
        cur = {"gen": -1, "hb": 0.0}
    if cur.get("done"):
        return None
    if cur.get("worker") == worker and not cur.get("released"):
        # Already ours (re-entry: same worker id after a restart, or
        # a crashed campaign resumed under deterministic ids). Refresh
        # the heartbeat as part of the re-claim — a stale own stamp
        # would otherwise trip renew_lease's lapsed-owner guard and
        # churn claim→renew-refused→lost forever.
        gen = int(cur.get("gen", 0))
        atomic_write_json(path, lease_payload(extra, worker, gen))
        return gen
    if cur.get("worker") == worker:
        # Our own RELEASED lease (the peer we released to never came):
        # re-claim like a takeover — generation bumps and the released
        # flag clears, so renewals work again instead of churning
        # claim→renew-refused→lost forever.
        gen = int(cur.get("gen", 0)) + 1
        atomic_write_json(path, lease_payload(extra, worker, gen))
        back = _read_json(path)
        if back and back.get("worker") == worker and \
                int(back.get("gen", -1)) == gen:
            return gen
        return None
    now = time.time()
    hb = float(cur.get("hb", 0.0))
    skew = lease_skew_s()
    if hb > now + skew:
        # A heartbeat from our future: the other host's clock (or
        # ours) is off by more than the allowance. Stealing now could
        # double-own the unit — refuse, loudly, and let the operator
        # (or a raised JT_LEASE_SKEW_S) resolve it.
        telemetry.REGISTRY.counter("fleet.lease_skew_rejects").inc()
        log.warning(
            "lease %s is stamped %.1fs in the local future (skew "
            "allowance %.1fs); refusing takeover — check host clocks "
            "or raise JT_LEASE_SKEW_S", path, hb - now, skew)
        return None
    if now - hb < ttl + skew:
        return None                          # live somewhere else
    gen = int(cur.get("gen", 0)) + 1
    atomic_write_json(path, lease_payload(extra, worker, gen))
    back = _read_json(path)
    if back and back.get("worker") == worker and \
            int(back.get("gen", -1)) == gen:
        telemetry.event("fleet.takeover", path=str(path), gen=gen)
        return gen
    return None


def renew_lease(path: Path, extra: dict, worker: str,
                gen: int, ttl: Optional[float] = None) -> bool:
    """Refresh a held lease's heartbeat. False (nothing written) when
    the on-disk record names someone else — the owner's signal to
    abandon the unit instead of double-writing. A lease we already
    marked done is left untouched (True): a heartbeat racing the
    done-mark must never resurrect it as live.

    With ``ttl``, an owner whose OWN last stamp has already lapsed
    past ttl+skew also refuses (False): the unit is legally claimable
    by a peer at that point, and a renewal racing the peer's takeover
    write could resurrect the old ownership on top of it — the
    stalled owner must abandon and re-claim through the front door
    instead. (Both times compare on this host's clock, so host skew
    doesn't enter.)"""
    cur = _read_json(path)
    if cur is None or cur.get("worker") != worker or \
            int(cur.get("gen", -1)) != int(gen):
        return False
    if cur.get("done"):
        return True
    if cur.get("released"):
        return False                  # we handed it back: stay away
    if ttl is not None and time.time() - float(cur.get("hb", 0.0)) \
            >= float(ttl) + lease_skew_s():
        return False                  # lapsed: a takeover may be live
    atomic_write_json(path, lease_payload(extra, worker, gen))
    return True


def release_lease(path: Path, extra: dict, worker: str,
                  gen: int) -> bool:
    """Voluntarily hand a held lease back (cost-routed rebalancing):
    the record keeps its generation but its heartbeat is zeroed, so
    any peer's next claim takes over immediately at generation+1 —
    with all the unit's durable progress (summaries, journals) intact
    for the new owner to resume."""
    cur = _read_json(path)
    if cur is None or cur.get("worker") != worker or \
            int(cur.get("gen", -1)) != int(gen):
        return False
    atomic_write_json(path, {**lease_payload(extra, worker, gen,
                                             hb=0.0),
                             "released": True})
    return True


def mark_lease_done(path: Path, extra: dict, worker: str,
                    gen: int) -> None:
    """Retire a completed unit's lease — done leases never expire, so
    no survivor wastes a takeover on finished work."""
    cur = _read_json(path)
    if cur and cur.get("worker") == worker and \
            int(cur.get("gen", -1)) == int(gen):
        atomic_write_json(path, lease_payload(extra, worker, gen,
                                              done=True))


def _chunk_extra(chunk_id: int, units) -> dict:
    return {"chunk": int(chunk_id), "units": [int(u) for u in units]}


def claim_chunk(cdir: Path, chunk_id: int, units, worker: str,
                ttl: float) -> Optional[int]:
    """The fleet campaign's lease claim: one seed-range chunk
    (claim_lease over ``leases/chunk-<k>.json``)."""
    return claim_lease(_lease_path(cdir, chunk_id),
                       _chunk_extra(chunk_id, units), worker, ttl)


def mark_done(cdir: Path, chunk_id: int, units, worker: str,
              gen: int) -> None:
    mark_lease_done(_lease_path(cdir, chunk_id),
                    _chunk_extra(chunk_id, units), worker, gen)


class LeaseHeartbeat:
    """Renews a held lease every ttl/3 on a daemon thread; flips
    ``lost`` (and stops renewing) the moment the on-disk record names
    someone else — the worker's signal to abandon the unit at the
    next boundary instead of double-writing."""

    def __init__(self, cdir: Path, chunk_id: int, units, worker: str,
                 gen: int, ttl: float):
        self.path = _lease_path(cdir, chunk_id)
        self.extra = _chunk_extra(chunk_id, units)
        self.worker, self.gen, self.ttl = worker, int(gen), float(ttl)
        self.lost = threading.Event()
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name=f"fleet-hb-{chunk_id}")

    def start(self) -> "LeaseHeartbeat":
        self._t.start()
        return self

    def _run(self) -> None:
        period = max(0.1, self.ttl / 3.0)
        while not self._stop.wait(period):
            if not renew_lease(self.path, self.extra, self.worker,
                               self.gen, ttl=self.ttl):
                self.lost.set()
                return

    def stop(self) -> None:
        self._stop.set()


# ----------------------------------------------------------- work spec

def _chunk_map(ws: dict) -> Dict[int, List[int]]:
    units = [int(u) for u in ws["units"]]
    size = max(1, int(ws.get("lease_chunk", 1)))
    return {k: units[i:i + size]
            for k, i in enumerate(range(0, len(units), size))}


def _summary_path(cdir: Path, unit: int) -> Path:
    return cdir / f"seed-{int(unit)}.json"


def _load_spec(cdir: Path) -> dict:
    ws = _read_json(Path(cdir) / SPEC_FILE)
    if ws is None:
        raise FileNotFoundError(
            f"{Path(cdir) / SPEC_FILE}: no fleet work spec — not a "
            f"campaign dir (orchestrate one with `jepsen-tpu fleet`)")
    if ws.get("fleet") != FLEET_MAGIC:
        raise CampaignMismatch(
            f"{Path(cdir) / SPEC_FILE}: not a {FLEET_MAGIC} work spec")
    return ws


def _resolve_model(name: str):
    from .recheck import registry
    spec = registry()[name]
    assert spec["kind"] == "linear", \
        f"fleet campaigns check linearizable families, not {name!r}"
    return spec["model"]()


def campaign_complete(cdir: Path, ws: Optional[dict] = None,
                      seen: Optional[set] = None) -> bool:
    """Every unit durably summarized. ``seen`` memoizes units already
    observed complete — a summary never disappears once written, so
    pollers (the worker claim loop, the orchestrator's babysit loop)
    pass a per-process set and only re-stat the shrinking remainder
    instead of O(units) metadata round trips per poll (a real cost on
    the multi-host shared-store path)."""
    ws = ws if ws is not None else _load_spec(cdir)
    cdir = Path(cdir)
    for u in ws["units"]:
        u = int(u)
        if seen is not None and u in seen:
            continue
        if _summary_path(cdir, u).exists():
            if seen is not None:
                seen.add(u)
            continue
        return False
    return True


# -------------------------------------------------------------- worker

def fleet_worker(campaign_dir, worker_id: str, *,
                 stop: Optional[threading.Event] = None) -> dict:
    """One worker's whole life against a campaign dir: claim leases,
    process their units (skipping any unit whose summary already
    landed — the zero-re-run invariant at the finest grain), heartbeat
    while working, take over expired leases, and exit once the
    campaign is complete. Writes ``worker-<id>.json`` (unit counts,
    router summary, telemetry counter deltas) on the way out."""
    cdir = Path(campaign_dir)
    ws = _load_spec(cdir)
    ttl = float(ws.get("lease_ttl_s") or lease_ttl_s())
    chunks = _chunk_map(ws)
    # The campaign id from the WORK SPEC becomes this worker process's
    # correlation default: every unit span (and everything dispatched
    # under it) names the campaign in a merged cluster trace.
    prev_corr = telemetry.set_correlation(
        ws.get("corr") or f"fleet:{ws.get('name', '?')}")
    # Periodic registry frames into the shared STORE's series
    # namespace (the campaign dir is store/<name>/fleet — frames
    # belong to the store root, one ring per worker process).
    from . import series as series_mod
    swriter = series_mod.SeriesWriter(ws.get("store_base") or cdir) \
        if series_mod.enabled() else None
    # Heterogeneous-host routing: JT_ROUTER_PROBE=1 measures this
    # host's backend rates once and persists them under its hostname
    # in the campaign dir; with or without the probe, the router
    # prices from THIS host's persisted measurements when they exist
    # (another worker's calibration is wrong by definition).
    if os.environ.get("JT_ROUTER_PROBE", "0") == "1":
        probe_and_persist(cdir)
    router = CostRouter(store_dir=cdir)
    tel_base = telemetry.snapshot()
    seen: set = set()           # units observed complete (memoized)
    stats = {"worker": worker_id, "chunks": 0, "units": 0,
             "rehydrated": 0, "takeovers": 0, "abandoned": 0,
             "errors": 0}
    test_sleep = 0.0
    try:
        test_sleep = float(os.environ.get("JT_FLEET_TEST_SLEEP_S", "0"))
    except ValueError:
        pass

    try:
        with telemetry.span("fleet.worker", worker=worker_id):
            _fleet_worker_loop(cdir, ws, chunks, worker_id, ttl,
                               router, stats, stop, test_sleep, seen,
                               swriter)
    finally:
        telemetry.set_correlation(prev_corr)
        if swriter is not None:
            swriter.close(final_frame=True)
    summary = {**stats, "router": router.summary(),
               "telemetry": telemetry.counters_delta(
                   tel_base, telemetry.snapshot())}
    atomic_write_json(cdir / f"worker-{worker_id}.json", summary)
    return summary


def _fleet_worker_loop(cdir, ws, chunks, worker_id, ttl, router,
                       stats, stop, test_sleep, seen, swriter) -> None:
    def chunk_done(units) -> bool:
        for u in units:
            u = int(u)
            if u in seen:
                continue
            if _summary_path(cdir, u).exists():
                seen.add(u)
                continue
            return False
        return True

    while not (stop is not None and stop.is_set()):
        if swriter is not None:
            swriter.maybe_append()
        claimed_any = False
        for k, units in chunks.items():
            if stop is not None and stop.is_set():
                break
            if chunk_done(units):
                continue
            gen = claim_chunk(cdir, k, units, worker_id, ttl)
            if gen is None:
                continue
            claimed_any = True
            stats["chunks"] += 1
            if gen > 0:
                stats["takeovers"] += 1
                log.info("worker %s took over chunk %d at "
                         "generation %d (previous lease expired)",
                         worker_id, k, gen)
            hb = LeaseHeartbeat(cdir, k, units, worker_id, gen,
                                ttl).start()
            try:
                finished = _process_chunk(
                    cdir, ws, units, worker_id, hb, router, stats,
                    stop, test_sleep)
            finally:
                hb.stop()
            if finished and not hb.lost.is_set():
                mark_done(cdir, k, units, worker_id, gen)
            elif hb.lost.is_set():
                stats["abandoned"] += 1
                log.warning("worker %s lost chunk %d's lease "
                            "mid-flight; abandoning it cleanly",
                            worker_id, k)
        if campaign_complete(cdir, ws, seen=seen):
            break
        if not claimed_any:
            # Everything left is leased to live workers: wait for
            # them to finish — or for their heartbeats to lapse.
            if stop is not None and stop.wait(min(1.0, ttl / 3.0)):
                break
            if stop is None:
                time.sleep(min(1.0, ttl / 3.0))


def _process_chunk(cdir: Path, ws: dict, units, worker_id: str,
                   hb: LeaseHeartbeat, router: CostRouter, stats: dict,
                   stop, test_sleep: float) -> bool:
    """Run one leased chunk's units. Returns True iff every unit ended
    summarized; ownership is re-checked before each unit so a stolen
    lease abandons at the next boundary."""
    for u in units:
        if (stop is not None and stop.is_set()) or hb.lost.is_set():
            return False
        spath = _summary_path(cdir, u)
        if spath.exists():
            stats["rehydrated"] += 1
            continue
        finish = None
        try:
            summ, finish = _run_unit(cdir, ws, int(u), router)
        except Exception as e:          # noqa: BLE001 — unit-scoped
            # One failing unit must not wedge the whole fleet in a
            # claim/crash loop: the error IS the unit's durable result
            # (valid: unknown), visible in the merge.
            log.warning("fleet unit %s failed: %s", u, e,
                        exc_info=True)
            stats["errors"] += 1
            summ = {"error": f"{type(e).__name__}: {e}"}
        summ["worker"] = worker_id
        atomic_write_json(spath, summ)
        if finish is not None:
            # Journal cleanup strictly AFTER the summary lands (the
            # run_synth_seeds order): a kill between finish and the
            # summary write would leave neither, and the takeover
            # would re-dispatch the whole seed.
            finish()
        stats["units"] += 1
        telemetry.REGISTRY.counter("fleet.units").inc()
        if test_sleep:
            time.sleep(test_sleep)
    return True


def _run_unit(cdir: Path, ws: dict, unit: int,
              router: CostRouter) -> tuple:
    """Execute one work unit by campaign kind; returns (summary,
    cleanup-or-None) — the cleanup (journal finish) runs only AFTER
    the caller lands the summary durably, preserving the
    zero-re-dispatch window. ``synth`` re-uses the exact per-seed
    engine run_synth_seeds runs (runtime.synth_seed_summary under a
    spec-keyed ChunkJournal) — fleet and single-process campaigns
    produce field-for-field identical summaries by construction.
    ``recheck`` cost-routes the stored run's history; ``fuzz`` runs
    one witness-guided round."""
    kind = ws["kind"]
    with telemetry.span("fleet.unit", kind=kind, unit=unit):
        if kind == "synth":
            from .ops.synth_device import SynthSpec
            from .runtime import synth_seed_summary
            spec = SynthSpec(**ws["spec"])
            sspec = dataclasses.replace(spec, seed=int(unit))
            model = _resolve_model(ws["model"])
            # Record the batch-level routing decision (post-partition
            # W bound, per-key event count, the whole batch amortizing
            # one dispatch) alongside the knobs it derives.
            router.choose_wgl(min(spec.n_procs, spec.n_ops),
                              max(1, 2 * spec.n_ops
                                  // max(spec.n_keys, 1)),
                              rows=spec.n)
            journal = ChunkJournal(
                cdir / f"seed-{unit}.journal.jsonl",
                {"spec": spec_digest(sspec, synth=ws["synth"])},
                resume=True)
            check_kwargs = router.wgl_check_kwargs(sspec)
            try:
                summ = synth_seed_summary(
                    model, sspec, synth=ws["synth"], journal=journal,
                    check_kwargs=check_kwargs)
            finally:
                journal.close()
            summ["router"] = check_kwargs
            return summ, journal.finish
        if kind == "fuzz":
            from .fuzz import fuzz_campaign
            from .ops.synth_device import SynthSpec
            spec = SynthSpec(**ws["spec"])
            # Units ARE absolute seeds (the synth-kind contract);
            # fold the unit in as THE round seed, not an offset on
            # top of spec.seed.
            rspec = dataclasses.replace(spec, seed=int(unit))
            out = fuzz_campaign(
                rspec, rounds=1, synth=ws["synth"],
                neighborhood=int(ws.get("neighborhood", 4)),
                max_witnesses=int(ws.get("max_witnesses", 8)),
                name=None)
            return {k: out[k] for k in
                    ("checked", "invalid", "neighborhoods",
                     "neighborhood_invalid", "disagreements")}, None
        if kind == "recheck":
            ts = ws["timestamps"][int(unit)]
            root = Store(Path(ws["store_base"]))
            loaded = root.load(ws["test"], ts)
            h = loaded.get("history")
            if h is None:
                return {"ts": ts, "valid": "unknown",
                        "error": "no stored history"}, None
            model = _resolve_model(ws["model"])
            rs, routing = route_check(model, [h], router=router)
            return {"ts": ts, "valid": rs[0].get("valid"),
                    "backend": rs[0].get("backend"),
                    "backends": routing["backends"]}, None
        raise ValueError(f"unknown fleet kind {kind!r}")


# --------------------------------------------------------- aggregation

def _unit_valid(kind: str, summ: dict):
    if "error" in summ:
        return "unknown"
    if kind == "synth":
        return summ.get("invalid", 0) == 0
    if kind == "fuzz":
        # Finding invalid histories is the fuzz working; a checker
        # DISAGREEMENT is the alarm (the fuzz_cmd exit contract).
        return summ.get("disagreements", 0) == 0
    return summ.get("valid")


def merge_campaign(campaign_dir) -> dict:
    """Fold every worker's durable artifacts into the one campaign
    verdict: per-unit summaries, worker summaries (router choices +
    telemetry counter deltas, summed), and the lease ledger (chunks,
    takeover generations). Persisted as ``fleet/results.json``; the
    orchestrator additionally publishes it as a standard run dir so
    the web index renders the fleet as a single view."""
    from .checkers.core import merge_valid

    cdir = Path(campaign_dir)
    ws = _load_spec(cdir)
    kind = ws["kind"]
    units, missing, invalid = {}, [], 0
    for u in ws["units"]:
        summ = _read_json(_summary_path(cdir, u))
        if summ is None:
            missing.append(int(u))
            continue
        summ["valid"] = _unit_valid(kind, summ)
        units[str(u)] = summ
        if "invalid" in summ:
            # synth/fuzz: invalid HISTORIES found (workload signal).
            invalid += int(summ["invalid"] or 0)
        elif summ["valid"] is False:
            # recheck: one invalid stored run per failing unit — the
            # counter must agree with the merged verdict.
            invalid += 1
    workers, chosen, est = {}, {}, {}
    wsums = []
    for wf in sorted(cdir.glob("worker-*.json")):
        wsum = _read_json(wf) or {}
        wsums.append(wsum)
        wid = wsum.get("worker", wf.stem)
        workers[wid] = {k: wsum.get(k, 0) for k in
                        ("chunks", "units", "rehydrated", "takeovers",
                         "abandoned", "errors")}
        r = wsum.get("router") or {}
        for k, v in (r.get("chosen") or {}).items():
            chosen[k] = chosen.get(k, 0) + v
        for k, v in (r.get("est_cost_s") or {}).items():
            est[k] = round(est.get(k, 0.0) + v, 6)
    leases = {"chunks": 0, "done": 0, "takeovers": 0}
    for lf in sorted((cdir / LEASES_DIR).glob("chunk-*.json")) \
            if (cdir / LEASES_DIR).exists() else []:
        le = _read_json(lf) or {}
        leases["chunks"] += 1
        leases["done"] += bool(le.get("done"))
        leases["takeovers"] += max(0, int(le.get("gen", 0)))
    complete = not missing
    valid = merge_valid(u["valid"] for u in units.values()) \
        if units else True
    if not complete:
        valid = "unknown" if valid is True else valid
    out = {"name": ws["name"], "kind": kind, "valid": valid,
           "created": ws.get("created"),
           "complete": complete, "units": len(ws["units"]),
           "missing": missing, "invalid": invalid, "seeds": units,
           "router": {"chosen": chosen, "est_cost_s": est,
                      "table": CostRouter(store_dir=cdir).table()},
           "workers": workers, "leases": leases,
           "telemetry": {"source": "fleet",
                         "counters": telemetry.merge_counter_snapshots(
                             w.get("telemetry") for w in wsums)}}
    atomic_write_json(cdir / RESULTS_FILE, out)
    return out


def publish_campaign(root: Store, name: str, merged: dict) -> Path:
    """One campaign-level run dir (``store/<name>/<ts>/``) carrying
    the merged verdict: the web index renders the whole fleet as a
    single row (with a ``fleet`` badge) exactly like any other run.
    Idempotent per campaign: a re-merge (e.g. ``--resume`` on a
    completed campaign) refreshes the run dir already published for
    this campaign's ``created`` stamp instead of adding a duplicate
    row."""
    from .store import StoreHandle

    h = None
    for ts in root.tests().get(name, []):
        prior = root._run_json(name, ts, "results.json") or {}
        if (prior.get("fleet") or {}).get("created") is not None and \
                prior["fleet"]["created"] == merged.get("created"):
            h = StoreHandle(root.run_dir(name, ts), store=root,
                            test_name=name)
            break
    if h is None:
        h = root.create(name)
    h.write_json("test.json", {
        "name": name, "fleet": True, "kind": merged["kind"],
        "units": merged["units"]})
    h.save_results({"valid": merged["valid"], "fleet": merged})
    return h.dir


# --------------------------------------------------------- orchestrator

def _spawn_worker(campaign_dir: Path, worker_id: str):
    """One local worker subprocess against the campaign dir — the
    same entry a remote host would run by hand (``jepsen-tpu fleet
    --join DIR --worker-id W``). Workers get their own (small) virtual
    device env: $JT_FLEET_WORKER_DEVICES, default 1 — fleet
    parallelism is across processes, not within them."""
    import subprocess
    import sys

    from .provision import virtual_cpu_env

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["PYTHONPATH"] = (str(repo) + os.pathsep
                         + env.get("PYTHONPATH", ""))
    try:
        devs = int(os.environ.get("JT_FLEET_WORKER_DEVICES", "1"))
    except ValueError:
        devs = 1
    if devs > 0:
        virtual_cpu_env(devs, env=env)
    logf = open(Path(campaign_dir) / f"worker-{worker_id}.log", "ab")
    p = subprocess.Popen(
        [sys.executable, "-m", "jepsen_tpu.cli", "fleet",
         "--join", str(campaign_dir), "--worker-id", worker_id],
        env=env, stdout=logf, stderr=subprocess.STDOUT)
    p._jt_log = logf        # closed on reap
    return p


class LocalPool:
    """Spawn + babysit a pool of local worker subprocesses — the fleet
    driver's spawner, reusable by the checking service
    (jepsen_tpu.service). Owns worker-id allocation, dead-worker reap
    + bounded respawn, and SLO-advice-driven scale-up
    (``apply_scale_advice``); it knows nothing about what the workers
    do — the spawn callback does."""

    def __init__(self, spawn_fn, n: int, *,
                 max_respawns: Optional[int] = None,
                 cap: Optional[int] = None):
        self.spawn_fn = spawn_fn               # worker_id -> Popen
        self.cap = max_local_workers() if cap is None else int(cap)
        self.target = min(int(n), self.cap) if self.cap else int(n)
        self.procs: Dict[str, object] = {}
        self.spawned = 0
        self.budget = (self.target if max_respawns is None
                       else int(max_respawns))

    def start(self) -> "LocalPool":
        while len(self.procs) < self.target:
            self._spawn_one()
        return self

    def _spawn_one(self) -> str:
        wid = f"w{self.spawned}"
        self.spawned += 1
        self.procs[wid] = self.spawn_fn(wid)
        return wid

    def reap(self, respawn: bool = True) -> List[str]:
        """Collect exited workers; respawn (bounded) when the caller
        says the pool still has work. Returns the reaped ids."""
        dead = [wid for wid, p in self.procs.items()
                if p.poll() is not None]
        for wid in dead:
            p = self.procs.pop(wid)
            getattr(p, "_jt_log", None) and p._jt_log.close()
            if p.returncode != 0:
                log.warning("local worker %s exited rc=%s", wid,
                            p.returncode)
            if respawn and self.budget > 0:
                self.budget -= 1
                nid = self._spawn_one()
                log.info("respawning local worker (%s -> %s)", wid, nid)
        return dead

    def revive(self) -> bool:
        """Budgeted single respawn for a pool found EMPTY with work
        remaining (reap only replaces processes it catches dying —
        a caller that drained to zero between polls uses this).
        False when the respawn budget is spent."""
        if self.budget <= 0:
            return False
        self.budget -= 1
        wid = self._spawn_one()
        log.info("reviving empty worker pool (-> %s)", wid)
        return True

    def scale_to(self, n: int) -> int:
        """Widen the pool to ``n`` workers (bounded by the local core
        cap — width beyond the cores belongs on more hosts). Returns
        how many were spawned."""
        n = min(int(n), self.cap) if self.cap else int(n)
        added = 0
        while len(self.procs) < n:
            self._spawn_one()
            added += 1
        self.target = max(self.target, min(n, len(self.procs)))
        return added

    def apply_scale_advice(self, path, max_age_s: float = 300.0) -> int:
        """Act on a durable ``service/scale-advice.json`` (the SLO
        breach signal the checking service publishes): widen the pool
        toward ``want_workers``, then CONSUME the file — advice is a
        one-shot signal, not standing configuration, and a breach that
        subsided must not over-provision every future pool. Advice
        stamped more than ``max_age_s`` ago is discarded unacted (a
        days-old file found by a fresh serve session describes a
        days-old breach). Returns workers spawned (0 when the advice
        is absent, stale, or already satisfied)."""
        adv = _read_json(path)
        if not adv:
            return 0
        try:
            want = int(adv.get("want_workers", 0))
            age = time.time() - float(adv.get("at") or 0.0)
        except (TypeError, ValueError):
            return 0
        if age > max_age_s:
            try:
                Path(path).unlink()
            except OSError:
                pass
            return 0
        if want <= len(self.procs):
            return 0
        added = self.scale_to(want)
        if added:
            log.info("scale advice %s: spawned %d worker(s) (pool now "
                     "%d; reason: %s)", path, added, len(self.procs),
                     adv.get("reason"))
            telemetry.REGISTRY.counter("service.scaled_workers").inc(
                added)
            try:
                Path(path).unlink()      # consumed
            except OSError:
                pass
        return added

    def shutdown(self, timeout: float = 15.0,
                 terminate: bool = True) -> None:
        """Stop the pool: SIGTERM every worker first (their own
        GracefulShutdown finishes in-flight work and exits clean),
        wait out ``timeout`` each, SIGKILL stragglers. ``terminate=
        False`` waits for natural exit first — the --until-idle path,
        where workers are already draining."""
        if terminate:
            for p in self.procs.values():
                if p.poll() is None:
                    try:
                        p.terminate()
                    except Exception:
                        pass
        for p in self.procs.values():
            try:
                p.wait(timeout=timeout)
            except Exception:
                p.kill()
                p.wait()
            getattr(p, "_jt_log", None) and p._jt_log.close()
        self.procs.clear()


def fleet_campaign(*, name: str = "fleet", kind: str = "synth",
                   seeds: Optional[Sequence[int]] = None, spec=None,
                   model: str = "cas", synth: str = "device",
                   test: Optional[str] = None,
                   timestamps: Optional[Sequence[str]] = None,
                   workers: int = 2, store_root: Optional[Store] = None,
                   resume: bool = False,
                   lease_chunk: Optional[int] = None,
                   lease_ttl: Optional[float] = None,
                   neighborhood: int = 4, max_witnesses: int = 8,
                   poll_s: float = 0.25,
                   max_respawns: Optional[int] = None,
                   stop: Optional[threading.Event] = None) -> dict:
    """Orchestrate one fleet campaign end to end: write the work spec,
    spawn ``workers`` local worker processes (0 = run one worker
    inline, no subprocess), monitor them — a worker that dies while
    units remain is respawned (bounded), and a killed worker's leases
    expire under the survivors anyway — then merge every durable
    artifact into the campaign verdict and publish it as a standard
    run dir. ``resume=True`` continues a killed campaign: completed
    units rehydrate from their summaries (zero re-run), in-flight
    seeds resume their journals (zero re-dispatched histories).

    ``kind``: ``synth`` shards a run_synth_seeds-shaped seed campaign;
    ``recheck`` shards a store-wide blind-sweep recheck of ``test``'s
    stored runs (units are timestamp indices, each cost-routed);
    ``fuzz`` shards witness-guided fuzz rounds."""
    root = store_root if store_root is not None else DEFAULT
    base = Path(root.base).resolve()
    cdir = base / name / FLEET_DIR

    if kind == "recheck":
        assert test, "recheck campaigns name a stored --test"
        if timestamps is None:
            timestamps = root.tests().get(test, [])
        units = list(range(len(timestamps)))
    else:
        assert spec is not None or resume, \
            f"{kind} campaigns need a SynthSpec"
        units = [int(s) for s in seeds] if seeds is not None else None

    existing = _read_json(cdir / SPEC_FILE)
    if resume:
        if existing is None or existing.get("fleet") != FLEET_MAGIC:
            raise FileNotFoundError(
                f"--resume: no fleet campaign at {cdir}")
        if units is not None or spec is not None:
            fresh = _work_spec(name, kind, units, spec, model, synth,
                               test, timestamps, base, lease_chunk,
                               lease_ttl, neighborhood, max_witnesses,
                               workers)
            bad = [k for k in IDENTITY_KEYS
                   if k in fresh and fresh.get(k) != existing.get(k)]
            if bad:
                raise CampaignMismatch(
                    f"fleet campaign {cdir} differs on {bad}; start a "
                    f"fresh campaign (without --resume) to replace it")
        ws = existing
    else:
        if cdir.exists():
            shutil.rmtree(cdir)
        cdir.mkdir(parents=True, exist_ok=True)
        (cdir / LEASES_DIR).mkdir(exist_ok=True)
        ws = _work_spec(name, kind, units, spec, model, synth, test,
                        timestamps, base, lease_chunk, lease_ttl,
                        neighborhood, max_witnesses, workers)
        atomic_write_json(cdir / SPEC_FILE, ws)

    # Local pool width: capped at the host's cores by default
    # (JT_FLEET_MAX_LOCAL_WORKERS) — oversubscribed local jax workers
    # REGRESS; width beyond the cores belongs on more hosts (--join).
    cap = max_local_workers()
    spawn_n = min(workers, cap) if (workers > 0 and cap) else workers
    if 0 < spawn_n < workers:
        log.info("capping local fleet pool at %d worker(s) "
                 "(%d requested, %s cores; join more hosts for more "
                 "width, or set JT_FLEET_MAX_LOCAL_WORKERS=0)",
                 spawn_n, workers, os.cpu_count())
    sp = telemetry.begin("fleet.campaign", name=name, kind=kind,
                         units=len(ws["units"]), workers=spawn_n)
    try:
        if not campaign_complete(cdir, ws):
            if spawn_n <= 0:
                fleet_worker(cdir, "w0", stop=stop)
            else:
                _drive_workers(cdir, ws, spawn_n, poll_s,
                               max_respawns, stop)
    finally:
        sp.end()
    merged = merge_campaign(cdir)
    merged["requested_workers"] = workers
    merged["spawned_workers"] = spawn_n if spawn_n > 0 else 1
    merged["dir"] = str(publish_campaign(root, name, merged))
    return merged


def _work_spec(name, kind, units, spec, model, synth, test, timestamps,
               base, lease_chunk, lease_ttl, neighborhood,
               max_witnesses, workers) -> dict:
    if lease_chunk is None:
        # Several chunks per worker: takeover granularity (what a dead
        # worker forfeits) vs lease traffic.
        lease_chunk = max(1, len(units or ())
                          // max(4 * max(workers, 1), 1))
    created = time.time()
    return {
        "fleet": FLEET_MAGIC, "name": name, "kind": kind,
        # The campaign's correlation id: every worker that joins this
        # spec stamps its spans with it (telemetry.set_correlation),
        # so a merged cluster trace groups all workers' unit spans
        # under one flow (doc/observability.md).
        "corr": f"fleet:{name}:{int(created)}",
        "model": model, "synth": synth,
        "units": [int(u) for u in (units or ())],
        "spec": (dataclasses.asdict(spec) if spec is not None
                 else None),
        "test": test,
        "timestamps": list(timestamps) if timestamps else None,
        "store_base": str(base),
        "lease_chunk": int(lease_chunk),
        "lease_ttl_s": float(lease_ttl if lease_ttl is not None
                             else lease_ttl_s()),
        "neighborhood": int(neighborhood),
        "max_witnesses": int(max_witnesses),
        "created": created,
    }


def _drive_workers(cdir: Path, ws: dict, workers: int, poll_s: float,
                   max_respawns: Optional[int], stop) -> None:
    """Spawn + babysit the local worker pool until the campaign
    completes (LocalPool). Lease expiry already redistributes a dead
    worker's units to survivors; respawning (bounded) just restores
    pool width — and is the only recovery when EVERY worker died."""
    seen: set = set()            # memoized completed units (per poll)
    # cap=0: fleet_campaign already applied the local-core cap when it
    # sized the pool.
    pool = LocalPool(lambda wid: _spawn_worker(cdir, wid), workers,
                     max_respawns=max_respawns, cap=0).start()
    try:
        while True:
            if campaign_complete(cdir, ws, seen=seen):
                break
            if stop is not None and stop.is_set():
                break
            pool.reap(respawn=not campaign_complete(cdir, ws,
                                                    seen=seen))
            if not pool.procs:
                if campaign_complete(cdir, ws, seen=seen):
                    break
                raise RuntimeError(
                    "every fleet worker exited with units remaining "
                    "and the respawn budget exhausted; see "
                    f"{cdir}/worker-*.log")
            time.sleep(poll_s)
    finally:
        # No SIGTERM here: fleet workers have no graceful-shutdown
        # handler — a terminate would kill them mid-unit and lose
        # their worker-<id>.json summaries; they exit on their own
        # once the campaign completes.
        pool.shutdown(timeout=max(5.0, 3 * float(
            ws.get("lease_ttl_s", 15.0))), terminate=False)
