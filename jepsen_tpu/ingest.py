"""Network-native ingest plane — crash-tolerant op streaming over the
wire with exactly-once WAL landing (doc/ingest.md).

Every scale-out layer so far (fleet leases, the federated service, the
online daemon) assumes tenants arrive as WAL files on a shared
filesystem. This module is the L0 that removes that assumption: a
length-prefixed, CRC-framed socket protocol (plus the HTTP/chunked
endpoint web.py mounts at ``/ingest/``) accepts per-tenant op streams
and lands them in ORDINARY per-tenant ``history.wal.jsonl`` segments
behind the existing group-commit discipline — so salvage, frontier
checkpoints, takeover, and finalization parity are untouched
downstream: the online daemon cannot tell a wire-fed tenant from a
filesystem one (beyond the ``ingest: wire`` header tag).

Robustness contract:

* **Exactly-once landing.** The wire sequence number of an op IS its
  history index; the server's resume point is the count of ops durably
  landed in the WAL (recovered through ``HistoryWAL(resume=True)``'s
  whole-lines-only parse after any crash). Frames at or below the
  resume point are duplicates — acked, counted, never re-landed; a
  frame past it is a gap — refused with the acked offset so the client
  rewinds. Duplicated, reordered, and replayed frames all converge to
  one copy of each op, in order.

* **Acked = durable.** The server fsyncs the frame's ops (one group
  commit per frame — the frame is the batch) BEFORE acking, so an ack
  the client saw can never be lost to a server SIGKILL, and anything
  unacked is safe to replay.

* **Resume-from-acked-offset reconnect.** ``stream_ops`` retransmits
  from the last acked offset after any transport failure, with
  jittered exponential backoff (``control.core.backoff_delay`` — the
  ``with_retry`` discipline) and bounded attempts ($JT_INGEST_RETRIES).

* **Backpressure, never silent drop.** Admission ties to the online
  daemon's overload ladder when one is coupled (``overload=`` a
  callable returning the 0-3 level) and to $JT_INGEST_MAX_TENANTS
  always; a refused stream gets a counted BUSY / HTTP 429 with a
  Retry-After priced off the router's wire-ingest rate
  (fleet.router_rates, $JT_INGEST_OPS_PER_S) when available.

* **Wire nemesis.** ``IngestFaultPlan`` ($JT_INGEST_FAULT_PLAN,
  ``stage:kind[:nth]`` — the DaemonFaultPlan syntax) injects
  disconnects, torn frames, duplicate deliveries, stalls, and mid-ack
  SIGKILLs at every protocol boundary; ``ingest_fault_schedules()`` is
  the canonical single-fault matrix the parity tests sweep.

A minimal Jepsen EDN adapter (``parse_edn_history``) converts a
foreign ``history.edn`` trace into ops at the same boundary, so
unmodified Jepsen runs can stream into the checker.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import socket
import socketserver
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import telemetry
from .control.core import backoff_delay
from .history.codec import dumps_op, loads_op
from .history.ops import Op
from .history.wal import PHASES, WAL_FILE, HistoryWAL
from .store import DEFAULT, Store

log = logging.getLogger("jepsen.ingest")

#: Wire frame: 4-byte big-endian payload length, 4-byte CRC32 of the
#: payload, then the JSON payload. The CRC catches torn/corrupted
#: frames the length prefix alone would mis-parse as the next frame.
FRAME_HEADER = struct.Struct(">II")
MAX_FRAME_BYTES = 8 << 20

#: Protocol boundaries the wire nemesis can fire at.
INGEST_STAGES = ("accept", "frame", "land", "ack")
#: Fault kinds: disconnect (close the connection), torn (truncate the
#: in-flight frame), dup (deliver the frame twice), stall (sleep),
#: kill (SIGKILL this process — the mid-ack crash).
INGEST_KINDS = ("disconnect", "torn", "dup", "stall", "kill")

#: Counters pre-registered on the telemetry registry so /metrics
#: exposes the series the moment an ingest plane exists.
INGEST_COUNTERS = ("ingest.frames", "ingest.ops", "ingest.dups",
                   "ingest.retries", "ingest.shed", "ingest.torn",
                   "ingest.streams")


# ----------------------------------------------------------------- knobs

def max_tenants() -> int:
    """$JT_INGEST_MAX_TENANTS: active wire streams admitted before the
    plane sheds (counted BUSY / 429, never a silent drop)."""
    try:
        return int(os.environ.get("JT_INGEST_MAX_TENANTS", "64"))
    except ValueError:
        return 64


def retry_after_default_s() -> float:
    """$JT_INGEST_RETRY_AFTER_S: the Retry-After a shed advertises when
    the router has no wire-ingest rate to price one with."""
    try:
        return float(os.environ.get("JT_INGEST_RETRY_AFTER_S", "1"))
    except ValueError:
        return 1.0


def batch_ops() -> int:
    """$JT_INGEST_BATCH_OPS: client ops per frame — the wire
    group-commit unit (one fsync + one ack per frame)."""
    try:
        return max(1, int(os.environ.get("JT_INGEST_BATCH_OPS",
                                         "256")))
    except ValueError:
        return 256


def client_retries() -> int:
    """$JT_INGEST_RETRIES: reconnect attempts beyond the first in the
    resume-from-acked-offset loop (the with_retry convention)."""
    try:
        return max(0, int(os.environ.get("JT_INGEST_RETRIES", "5")))
    except ValueError:
        return 5


# ------------------------------------------------------------ exceptions

class FrameError(Exception):
    """A frame failed to parse: short read, CRC mismatch, oversized
    length, or malformed payload. Transport-level — the client's
    reconnect loop retries it."""


class IngestBusy(Exception):
    """Admission refused — the counted shed. Carries the advertised
    Retry-After so clients back off for a priced interval instead of
    stampeding."""

    def __init__(self, retry_after: float):
        self.retry_after = float(retry_after)
        super().__init__(f"ingest shed; retry after "
                         f"{self.retry_after:.3f}s")


class IngestError(Exception):
    """The client exhausted its reconnect budget."""


class IngestFault(RuntimeError):
    """An injected wire fault fired (the nemesis engaging, not a
    bug)."""

    def __init__(self, stage: str, ordinal: int, kind: str):
        self.stage, self.ordinal, self.kind = stage, ordinal, kind
        super().__init__(f"injected ingest fault {kind} at {stage} "
                         f"ordinal {ordinal}")


# ---------------------------------------------------------- wire nemesis

@dataclass(frozen=True)
class IngestFaultSpec:
    """``kind`` at ``stage``, firing on that stage's Nth crossing
    (``nth`` None = sticky)."""

    stage: str
    kind: str
    nth: Optional[int] = 0

    def __post_init__(self):
        assert self.stage in INGEST_STAGES, self.stage
        assert self.kind in INGEST_KINDS, self.kind

    def matches(self, stage: str, ordinal: int) -> bool:
        return self.stage == stage and (self.nth is None
                                        or self.nth == ordinal)


class IngestFaultPlan:
    """Deterministic wire fault schedule — the DaemonFaultPlan idiom
    lifted to the ingest protocol's boundaries. ``stall_s`` is what a
    ``stall`` fault sleeps (test-scale by default)."""

    def __init__(self, specs: List[IngestFaultSpec], *,
                 stall_s: float = 0.05):
        self.specs = list(specs)
        self.stall_s = stall_s

    @classmethod
    def single(cls, stage: str, kind: str, nth: int = 0,
               **kw) -> "IngestFaultPlan":
        return cls([IngestFaultSpec(stage, kind, nth)], **kw)

    @classmethod
    def parse(cls, text: str, **kw) -> "IngestFaultPlan":
        """``"stage:kind[:nth]"`` comma/semicolon-separated; nth ``*``
        = sticky (the $JT_INGEST_FAULT_PLAN syntax)."""
        specs = []
        for part in text.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            nth: Optional[int] = 0
            if len(bits) > 2:
                nth = None if bits[2] == "*" else int(bits[2])
            specs.append(IngestFaultSpec(bits[0], bits[1], nth))
        return cls(specs, **kw)

    def match(self, stage: str, ordinal: int
              ) -> Optional[IngestFaultSpec]:
        for s in self.specs:
            if s.matches(stage, ordinal):
                return s
        return None


def ingest_fault_schedules() -> List[Tuple[str, IngestFaultPlan]]:
    """The canonical single-fault matrix the wire parity tests sweep:
    a disconnect at every protocol boundary, a torn frame and a torn
    ack, a duplicate delivery, and stalls on the hot stages — each
    fired exactly once, on the first crossing of its stage. The
    mid-ack SIGKILL (``ack:kill``) is deliberately NOT here: it kills
    the process, so its parity gate runs the server in a subprocess."""
    out = [(f"disconnect@{s}", IngestFaultPlan.single(s, "disconnect"))
           for s in INGEST_STAGES]
    out += [
        ("torn@frame", IngestFaultPlan.single("frame", "torn")),
        ("torn@ack", IngestFaultPlan.single("ack", "torn")),
        ("dup@frame", IngestFaultPlan.single("frame", "dup")),
        ("stall@frame", IngestFaultPlan.single("frame", "stall")),
        ("stall@land", IngestFaultPlan.single("land", "stall")),
    ]
    return out


class IngestFaultInjector:
    """Executes an IngestFaultPlan at the server's protocol crossings.
    ``fire(stage)`` sleeps through ``stall``, SIGKILLs this process
    for ``kill`` (the ops it acked are fsynced; the ack in flight is
    lost — exactly the case the client's replay must absorb), and
    RETURNS the kind for faults the call site must enact on the wire
    (disconnect / torn / dup). ``log`` records every firing so tests
    can assert the schedule actually engaged."""

    def __init__(self, plan: IngestFaultPlan):
        self.plan = plan
        self.log: List[Tuple[str, int, str]] = []
        self._lock = threading.Lock()
        self._ordinal: Dict[str, int] = {s: 0 for s in INGEST_STAGES}

    def fire(self, stage: str) -> Optional[str]:
        with self._lock:
            n = self._ordinal[stage]
            self._ordinal[stage] = n + 1
            spec = self.plan.match(stage, n)
            if spec is None:
                return None
            self.log.append((stage, n, spec.kind))
        if spec.kind == "stall":
            time.sleep(self.plan.stall_s)
            return None
        if spec.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        return spec.kind

    @classmethod
    def from_env(cls) -> Optional["IngestFaultInjector"]:
        text = os.environ.get("JT_INGEST_FAULT_PLAN")
        if not text:
            return None
        return cls(IngestFaultPlan.parse(text))


# ----------------------------------------------------------- frame codec

def encode_frame(obj: dict) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameError(f"frame over {MAX_FRAME_BYTES} bytes")
    return FRAME_HEADER.pack(len(payload),
                             zlib.crc32(payload)) + payload


def write_frame(sock, obj: dict, *, torn: bool = False) -> None:
    """THE framed write primitive (JTL-H-SOCK: raw socket sends live
    here and nowhere else). ``torn=True`` is the nemesis enactment —
    send a strict prefix of the frame, so the peer's CRC/length check
    must catch it."""
    data = encode_frame(obj)
    if torn:
        sock.sendall(data[:max(1, len(data) // 2)])
        return
    sock.sendall(data)


def _read_exact(f, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a frame boundary,
    FrameError on a mid-frame truncation (the torn case)."""
    buf = b""
    while len(buf) < n:
        chunk = f.read(n - len(buf))
        if not chunk:
            if not buf:
                return None
            raise FrameError(f"torn frame: {len(buf)}/{n} bytes")
        buf += chunk
    return buf


def read_frame(f) -> Optional[dict]:
    """Read one frame from a file-like (socket makefile). None on a
    clean close between frames; FrameError on torn/corrupt frames."""
    head = _read_exact(f, FRAME_HEADER.size)
    if head is None:
        return None
    length, crc = FRAME_HEADER.unpack(head)
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} over bound")
    payload = _read_exact(f, length)
    if payload is None:
        raise FrameError("torn frame: missing payload")
    if zlib.crc32(payload) != crc:
        raise FrameError("frame CRC mismatch")
    try:
        return json.loads(payload)
    except ValueError as e:
        raise FrameError(f"unparseable frame payload: {e}") from e


def encode_ops(ops: Sequence[Op]) -> List[dict]:
    """Wire form of a history: each op through the ONE codec the WAL
    and history files use (dumps_op — KV/set/bytes round-trip), with
    the op's history index pinned to its wire sequence number. An op
    with a conflicting pre-assigned index is refused — seq==index is
    the invariant the exactly-once audit checks."""
    out = []
    for seq, op in enumerate(ops):
        if op.index is None:
            op = op.with_(index=seq)
        elif op.index != seq:
            raise ValueError(
                f"op index {op.index} != wire seq {seq}: the stream "
                f"must be a dense indexed history prefix")
        out.append(json.loads(dumps_op(op)))
    return out


def decode_op(d: dict) -> Op:
    return loads_op(json.dumps(d, separators=(",", ":")))


# ------------------------------------------------------------- sequencer

class IngestTenant:
    """One wire-fed run: a resumable JTWAL1 segment plus the monotone
    sequence cursor (``next_seq`` == ops durably landed) that makes
    landing exactly-once."""

    def __init__(self, core: "IngestCore", name: str, ts: str,
                 header: Optional[dict] = None):
        self.core = core
        self.name, self.ts = name, ts
        self.key = f"{name}/{ts}"
        self.lock = threading.Lock()
        run_dir = core.store.run_dir(name, ts)
        run_dir.mkdir(parents=True, exist_ok=True)
        # resume=True is the exactly-once seam: after ANY server crash
        # the durable op count recovers from the segment itself — no
        # sidecar to drift from the WAL.
        self.wal = HistoryWAL(run_dir / WAL_FILE,
                              header={"test": {"name": name},
                                      "ingest": "wire",
                                      **(header or {})},
                              resume=True)
        if self.wal.ops_appended == 0 and self.wal.phase == "setup":
            self.wal.stamp_phase("run")
        self.done = False

    @property
    def next_seq(self) -> int:
        return self.wal.ops_appended

    def _stamp_forward(self, phase: str) -> None:
        """Idempotent phase advance: a replayed END frame must not
        double-stamp the segment."""
        if PHASES.index(phase) > PHASES.index(self.wal.phase):
            self.wal.stamp_phase(phase)

    def land(self, start_seq: int, op_dicts: Sequence[dict]) -> dict:
        """Land one frame exactly-once and group-commit it. Returns
        the ack (or gap-error) reply dict; ops at seq < next_seq are
        duplicates (skipped, counted), a start past next_seq is a gap
        (refused with the acked offset so the client rewinds)."""
        with self.lock:
            t0 = time.monotonic()
            if start_seq > self.next_seq:
                return {"t": "error", "err": "gap",
                        "acked": self.next_seq}
            skip = self.next_seq - start_seq
            dups = min(skip, len(op_dicts))
            landed = 0
            for i, d in enumerate(op_dicts[skip:]):
                seq = self.next_seq
                op = decode_op(d)
                if op.index != seq:
                    return {"t": "error", "err": "index",
                            "acked": self.next_seq,
                            "msg": f"op index {op.index} != seq "
                                   f"{seq}"}
                self.wal.append_op(op)
                landed += 1
            # The frame IS the group-commit batch: everything acked is
            # fsynced, so a post-ack SIGKILL can never lose acked ops.
            self.wal.sync()
            if dups:
                telemetry.REGISTRY.counter("ingest.dups").inc(dups)
            if landed:
                telemetry.REGISTRY.counter("ingest.ops").inc(landed)
            telemetry.REGISTRY.histogram("ingest.ack_ms").observe(
                (time.monotonic() - t0) * 1e3)
            return {"t": "ack", "acked": self.next_seq}

    def end(self, count: int) -> dict:
        """The stream is complete: verify the full sequence landed,
        stamp ``analyzed`` (idempotently — replayed ENDs are no-ops)
        and close the segment. The online daemon finalizes an
        ``analyzed`` tenant immediately, writer liveness regardless —
        wire completion behaves exactly like a run that analyzed."""
        with self.lock:
            if count != self.next_seq:
                return {"t": "error", "err": "gap",
                        "acked": self.next_seq}
            self._stamp_forward("analyzed")
            self.wal.close()
            self.done = True
            return {"t": "ack", "acked": self.next_seq, "done": True}

    def close(self) -> None:
        with self.lock:
            self.wal.close()


class IngestCore:
    """The landing engine both transports share: admission, per-tenant
    sequencing, and the fault injector. The socket server owns one;
    web.py's ``/ingest/`` endpoint binds one per store — either way
    the WAL itself is the source of truth for the resume point, so
    separate cores (even separate processes) still land exactly-once.
    """

    def __init__(self, store: Optional[Store] = None, *,
                 overload: Optional[Callable[[], int]] = None,
                 faults: Optional[IngestFaultInjector] = None,
                 tenant_bound: Optional[int] = None):
        self.store = store or DEFAULT
        self.overload = overload
        self.faults = faults if faults is not None \
            else IngestFaultInjector.from_env()
        self.tenant_bound = tenant_bound
        self.tenants: Dict[Tuple[str, str], IngestTenant] = {}
        self.lock = threading.Lock()
        telemetry.preregister(INGEST_COUNTERS)

    # ------------------------------------------------------ admission
    def _active(self) -> int:
        return sum(1 for t in self.tenants.values() if not t.done)

    def retry_after(self) -> float:
        """Price the shed's Retry-After off the router's wire-ingest
        rate when one is configured ($JT_INGEST_OPS_PER_S via
        fleet.router_rates) — the backlog of one batch per active
        stream — else the fixed $JT_INGEST_RETRY_AFTER_S."""
        from .fleet import router_rates
        rate = float(router_rates().get("ingest") or 0.0)
        if rate > 0:
            backlog = max(1, self._active()) * batch_ops()
            return max(0.05, backlog / rate)
        return retry_after_default_s()

    def attach(self, name: str, ts: str,
               header: Optional[dict] = None
               ) -> Tuple[IngestTenant, int]:
        """Admit (or re-attach) a stream; returns (tenant, acked
        offset). Refusal is a counted IngestBusy with Retry-After —
        backpressure, never a silent drop: past the tenant bound, or
        when the coupled online daemon's overload ladder is at
        shed-or-worse (level >= 2)."""
        with self.lock:
            t = self.tenants.get((name, ts))
            if t is not None and not t.done:
                return t, t.next_seq
            shed = self._active() >= (self.tenant_bound
                                      if self.tenant_bound is not None
                                      else max_tenants())
            if not shed and self.overload is not None:
                shed = self.overload() >= 2
            if shed:
                telemetry.REGISTRY.counter("ingest.shed").inc()
                raise IngestBusy(self.retry_after())
            t = IngestTenant(self, name, ts, header)
            self.tenants[(name, ts)] = t
            telemetry.REGISTRY.counter("ingest.streams").inc()
            return t, t.next_seq

    def close(self) -> None:
        with self.lock:
            for t in self.tenants.values():
                t.close()
            self.tenants.clear()


# ---------------------------------------------------------- socket plane

class _IngestHandler(socketserver.BaseRequestHandler):
    """One client connection: HELLO -> ACK(acked offset), then OPS
    frames each acked after their group commit, then END. Every
    protocol boundary crosses the wire nemesis."""

    def handle(self):
        core: IngestCore = self.server.core
        faults = core.faults
        if faults is not None and \
                faults.fire("accept") == "disconnect":
            return
        rfile = self.request.makefile("rb")
        tenant: Optional[IngestTenant] = None
        try:
            while True:
                try:
                    msg = read_frame(rfile)
                except FrameError as e:
                    telemetry.REGISTRY.counter("ingest.torn").inc()
                    self._reply(faults, {"t": "error", "err": "torn",
                                         "msg": str(e)})
                    return
                if msg is None:
                    return
                telemetry.REGISTRY.counter("ingest.frames").inc()
                deliveries = 1
                if faults is not None:
                    kind = faults.fire("frame")
                    if kind == "disconnect":
                        return
                    if kind == "torn":
                        # The nemesis tore this frame in flight: the
                        # server must treat it as never received.
                        telemetry.REGISTRY.counter(
                            "ingest.torn").inc()
                        self._reply(faults,
                                    {"t": "error", "err": "torn"})
                        return
                    if kind == "dup":
                        deliveries = 2
                reply = None
                for _ in range(deliveries):
                    try:
                        tenant, reply = self._apply(core, tenant, msg)
                    except IngestBusy as b:
                        self._reply(faults, {
                            "t": "busy",
                            "retry_after": b.retry_after})
                        return
                    except IngestFault:
                        return        # land:disconnect — no ack
                if reply is not None and \
                        not self._reply(faults, reply):
                    return
                if reply is not None and (reply.get("done")
                                          or reply.get("err")
                                          == "torn"):
                    return
        except (OSError, ValueError):
            return                    # peer vanished mid-frame

    def _apply(self, core: IngestCore,
               tenant: Optional[IngestTenant], msg: dict):
        t = msg.get("t")
        if t == "hello":
            tenant, acked = core.attach(msg["tenant"], msg["ts"],
                                        msg.get("header"))
            return tenant, {"t": "ack", "acked": acked}
        if tenant is None:
            return None, {"t": "error", "err": "protocol",
                          "msg": "ops before hello"}
        if t == "ops":
            if core.faults is not None and \
                    core.faults.fire("land") == "disconnect":
                # Landed-but-unacked is the contract under test: the
                # ops go durable, the ack never leaves, the client
                # replays, the sequencer dedupes.
                tenant.land(int(msg["seq"]), msg.get("ops") or [])
                raise IngestFault("land", -1, "disconnect")
            return tenant, tenant.land(int(msg["seq"]),
                                       msg.get("ops") or [])
        if t == "end":
            return tenant, tenant.end(int(msg["count"]))
        return tenant, {"t": "error", "err": "protocol",
                        "msg": f"unknown frame type {t!r}"}

    def _reply(self, faults, obj: dict) -> bool:
        """Send one reply frame through the ack-stage nemesis. False
        when the connection must drop (fault enacted or peer gone)."""
        if faults is not None:
            kind = faults.fire("ack")
            if kind == "disconnect":
                return False
            if kind == "torn":
                try:
                    write_frame(self.request, obj, torn=True)
                except OSError:
                    pass
                return False
        try:
            write_frame(self.request, obj)
            return True
        except OSError:
            return False


class IngestServer:
    """The socket ingest plane: a threading TCP server landing frames
    through one shared IngestCore. ``port=0`` binds an ephemeral port
    (``.port`` carries the bound one)."""

    def __init__(self, store: Optional[Store] = None,
                 host: str = "127.0.0.1", port: int = 0, *,
                 core: Optional[IngestCore] = None,
                 faults: Optional[IngestFaultInjector] = None,
                 overload: Optional[Callable[[], int]] = None):
        self.core = core or IngestCore(store, faults=faults,
                                       overload=overload)

        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = _Srv((host, port), _IngestHandler)
        self._srv.core = self.core
        self.host, self.port = self._srv.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def serve(self, block: bool = False):
        if block:
            self._srv.serve_forever(poll_interval=0.05)
            return self
        self._thread = threading.Thread(
            target=self._srv.serve_forever,
            kwargs={"poll_interval": 0.05},
            daemon=True, name="jepsen ingest")
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self.core.close()


# ---------------------------------------------------------- socket client

class _Busy(Exception):
    def __init__(self, retry_after: float):
        self.retry_after = retry_after
        super().__init__()


#: Client backoff base/cap — test-scale; the shape (jittered
#: exponential, backoff_delay) is the control plane's.
BACKOFF_BASE_S = 0.05
BACKOFF_CAP_S = 2.0


def stream_ops(host: str, port: int, name: str, ts: str,
               ops: Sequence[Op], *, header: Optional[dict] = None,
               attempts: Optional[int] = None,
               batch: Optional[int] = None, end: bool = True,
               timeout: float = 30.0) -> dict:
    """Stream an indexed history to an ingest server with the
    resume-from-acked-offset reconnect loop: on ANY transport failure
    (connection refused/reset, torn frame, lost ack) the client backs
    off with jittered exponential delay (backoff_delay — the
    with_retry discipline), reconnects, learns the durable acked
    offset from HELLO, and retransmits only the unacked suffix. A
    counted BUSY shed sleeps the server's advertised Retry-After
    instead. Returns ``{"acked", "retries", "sheds"}``."""
    encoded = encode_ops(list(ops))
    attempts = client_retries() if attempts is None else int(attempts)
    bsz = batch or batch_ops()
    retries = sheds = 0
    attempt = 0
    while True:
        try:
            return {**_stream_once(host, port, name, ts, encoded,
                                   header, bsz, end, timeout),
                    "retries": retries, "sheds": sheds}
        except _Busy as b:
            sheds += 1
            delay = b.retry_after
        except (OSError, FrameError):
            delay = backoff_delay(attempt, base=BACKOFF_BASE_S,
                                  cap=BACKOFF_CAP_S)
        if attempt >= attempts:
            raise IngestError(
                f"{name}/{ts}: out of reconnect attempts "
                f"({attempts + 1} tried)")
        retries += 1
        telemetry.REGISTRY.counter("ingest.retries").inc()
        attempt += 1
        time.sleep(delay)


def _stream_once(host, port, name, ts, encoded, header, bsz, end,
                 timeout) -> dict:
    """One connection's worth of streaming; raises on any transport
    failure (the caller's loop owns retry)."""
    with socket.create_connection((host, port),
                                  timeout=timeout) as sock:
        sock.settimeout(timeout)
        rfile = sock.makefile("rb")
        write_frame(sock, {"t": "hello", "tenant": name, "ts": ts,
                           "header": header or {},
                           "count": len(encoded)})
        r = read_frame(rfile)
        if r is None:
            raise FrameError("connection closed during hello")
        if r.get("t") == "busy":
            raise _Busy(float(r.get("retry_after") or
                              retry_after_default_s()))
        if r.get("t") != "ack":
            raise FrameError(f"unexpected hello reply: {r}")
        acked = int(r["acked"])
        while acked < len(encoded):
            write_frame(sock, {"t": "ops", "seq": acked,
                               "ops": encoded[acked:acked + bsz]})
            r = read_frame(rfile)
            if r is None:
                raise FrameError("connection closed awaiting ack")
            if r.get("t") == "error":
                if r.get("err") == "gap":
                    acked = int(r["acked"])   # rewind and resend
                    continue
                raise FrameError(f"server refused frame: {r}")
            acked = int(r["acked"])
        if end:
            write_frame(sock, {"t": "end", "count": len(encoded)})
            r = read_frame(rfile)
            if r is None or r.get("t") != "ack":
                raise FrameError(f"no final ack: {r}")
            acked = int(r["acked"])
        return {"acked": acked}


# ------------------------------------------------------------ HTTP client

def http_stream_ops(host: str, port: int, name: str, ts: str,
                    ops: Sequence[Op], *,
                    attempts: Optional[int] = None,
                    batch: Optional[int] = None, end: bool = True,
                    chunked: bool = True,
                    timeout: float = 30.0) -> dict:
    """The same contract over web.py's ``/ingest/`` endpoint: each
    batch POSTs as JSONL (chunked transfer-encoding by default) with
    ``X-JT-Seq`` the batch's first sequence number and ``X-JT-CRC``
    the body's CRC32; a GET probes the durable acked offset on
    reconnect. 429 sheds honor Retry-After; 409 gaps rewind to the
    server's acked offset; transport failures back off and retry."""
    import http.client

    encoded = encode_ops(list(ops))
    attempts = client_retries() if attempts is None else int(attempts)
    bsz = batch or batch_ops()
    path = f"/ingest/{name}/{ts}"
    retries = sheds = 0
    attempt = 0

    def fail(delay):
        nonlocal attempt, retries
        if attempt >= attempts:
            raise IngestError(
                f"{name}/{ts}: out of HTTP attempts "
                f"({attempts + 1} tried)")
        retries += 1
        telemetry.REGISTRY.counter("ingest.retries").inc()
        attempt += 1
        time.sleep(delay)

    acked: Optional[int] = None
    while True:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            if acked is None:
                conn.request("GET", path)
                r = conn.getresponse()
                body = json.loads(r.read() or b"{}")
                if r.status == 429:
                    sheds += 1
                    fail(float(r.headers.get("Retry-After")
                               or body.get("retry_after")
                               or retry_after_default_s()))
                    continue
                if r.status != 200:
                    raise FrameError(f"probe {r.status}: {body}")
                acked = int(body["acked"])
            while acked < len(encoded) or end:
                batch_ops_ = encoded[acked:acked + bsz]
                final = end and acked + len(batch_ops_) \
                    >= len(encoded)
                payload = "".join(
                    json.dumps(d, separators=(",", ":")) + "\n"
                    for d in batch_ops_).encode()
                headers = {"Content-Type": "application/jsonl",
                           "X-JT-Seq": str(acked),
                           "X-JT-CRC": str(zlib.crc32(payload))}
                if final:
                    headers["X-JT-End"] = str(len(encoded))
                if chunked:
                    headers["Transfer-Encoding"] = "chunked"
                    conn.request("POST", path, body=iter([payload]),
                                 headers=headers,
                                 encode_chunked=True)
                else:
                    conn.request("POST", path, body=payload,
                                 headers=headers)
                r = conn.getresponse()
                body = json.loads(r.read() or b"{}")
                if r.status == 429:
                    sheds += 1
                    fail(float(r.headers.get("Retry-After")
                               or body.get("retry_after")
                               or retry_after_default_s()))
                    break
                if r.status == 409:           # gap: rewind
                    acked = int(body["acked"])
                    continue
                if r.status != 200:
                    raise FrameError(f"POST {r.status}: {body}")
                acked = int(body["acked"])
                if final and body.get("done"):
                    return {"acked": acked, "retries": retries,
                            "sheds": sheds}
                if not end and acked >= len(encoded):
                    return {"acked": acked, "retries": retries,
                            "sheds": sheds}
        except (OSError, FrameError, ValueError):
            acked = None               # re-probe the durable offset
            fail(backoff_delay(attempt, base=BACKOFF_BASE_S,
                               cap=BACKOFF_CAP_S))
        finally:
            conn.close()


# -------------------------------------------------------------- sequence

def sequence_audit(wal_path) -> dict:
    """The exactly-once audit: read a landed segment and verify the op
    indices are exactly ``0..n-1`` in order — zero duplicated, zero
    lost, zero reordered ops, whatever the wire did. Returns
    ``{"ops", "ok", "duplicates", "gaps"}``."""
    from .history.wal import read_wal
    ops = read_wal(wal_path)["ops"]
    dup, gaps = [], []
    expect = 0
    for op in ops:
        if op.index == expect:
            expect += 1
        elif op.index < expect:
            dup.append(op.index)
        else:
            gaps.extend(range(expect, op.index))
            expect = op.index + 1
    return {"ops": len(ops), "ok": not dup and not gaps,
            "duplicates": dup, "gaps": gaps}


# ------------------------------------------------------------ EDN adapter

def parse_edn_history(text: str) -> List[Op]:
    """Minimal Jepsen EDN history adapter: one op map per line (the
    ``history.edn`` a stock Jepsen run stores), covering the subset a
    history needs — maps, vectors, keywords, strings, numbers,
    nil/true/false. Keywords become strings (``:invoke`` -> "invoke");
    ``:index``/``:time`` map onto the op's fields; unknown keys ride
    in ``extra``. Indices are reassigned densely when absent — the
    wire requires a dense prefix. ``:txn`` ops have their micro-op
    vectors (``[:r :x nil]``, ``[:append :y 2]``) normalized into the
    isolation checker's ``[f, key, value]`` lists."""
    ops: List[Op] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith(";"):
            continue
        val, pos = _edn_value(line, 0)
        if not isinstance(val, dict):
            raise ValueError(f"EDN history line is not a map: "
                             f"{line[:80]}")
        known = {"process", "type", "f", "value", "time", "index",
                 "error"}
        extra = {k: v for k, v in val.items() if k not in known}
        value = val.get("value")
        if val.get("f") == "txn":
            value = _txn_mops(value)
        ops.append(Op(process=val.get("process"),
                      type=val.get("type"),
                      f=val.get("f"),
                      value=value,
                      time=val.get("time"),
                      index=val.get("index"),
                      error=val.get("error"),
                      extra=extra or None))
    if any(op.index is None for op in ops):
        for i, op in enumerate(ops):
            op.index = i
    return ops


def _txn_mops(value):
    """Normalize a Jepsen ``:txn`` value — a vector of micro-op
    vectors, possibly short (``[:r :x]``) — into 3-slot
    ``[f, key, value]`` lists (ops.txn_graph's mop form). Non-vector
    values pass through untouched (the extractor raises its own,
    better error)."""
    if not isinstance(value, (list, tuple)):
        return value
    out = []
    for m in value:
        if isinstance(m, (list, tuple)) and 1 <= len(m) <= 3:
            m = list(m) + [None] * (3 - len(m))
        out.append(m)
    return out


_EDN_WS = " \t\r\n,"


def _edn_value(s: str, i: int):
    """Parse one EDN value at s[i:]; returns (value, next index)."""
    while i < len(s) and s[i] in _EDN_WS:
        i += 1
    if i >= len(s):
        raise ValueError("unexpected end of EDN input")
    c = s[i]
    if c == "{":
        out = {}
        i += 1
        while True:
            while i < len(s) and s[i] in _EDN_WS:
                i += 1
            if i < len(s) and s[i] == "}":
                return out, i + 1
            k, i = _edn_value(s, i)
            v, i = _edn_value(s, i)
            out[k] = v
    if c in "[(":
        close = "]" if c == "[" else ")"
        out = []
        i += 1
        while True:
            while i < len(s) and s[i] in _EDN_WS:
                i += 1
            if i < len(s) and s[i] == close:
                return out, i + 1
            v, i = _edn_value(s, i)
            out.append(v)
    if c == '"':
        j = i + 1
        buf = []
        while j < len(s):
            if s[j] == "\\":
                esc = s[j + 1]
                buf.append({"n": "\n", "t": "\t", '"': '"',
                            "\\": "\\"}.get(esc, esc))
                j += 2
                continue
            if s[j] == '"':
                return "".join(buf), j + 1
            buf.append(s[j])
            j += 1
        raise ValueError("unterminated EDN string")
    if c == ":":
        j = i + 1
        while j < len(s) and s[j] not in _EDN_WS + "}])":
            j += 1
        # Namespaced keywords keep only the name part (:jepsen/op ->
        # "op"), matching how the checker reads plain histories.
        return s[i + 1:j].split("/")[-1], j
    j = i
    while j < len(s) and s[j] not in _EDN_WS + "}])":
        j += 1
    tok = s[i:j]
    if tok == "nil":
        return None, j
    if tok == "true":
        return True, j
    if tok == "false":
        return False, j
    try:
        return int(tok), j
    except ValueError:
        pass
    try:
        return float(tok), j
    except ValueError:
        pass
    return tok, j                       # bare symbol degrades to string
