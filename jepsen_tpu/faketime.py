"""libfaketime wrappers: run a DB under a skewed, rate-drifting clock.

Mirrors jepsen/src/jepsen/faketime.clj: replace a db binary with a shell
wrapper that launches the real binary under faketime with a per-node
random rate, so nodes' clocks drift apart continuously (as opposed to
the discrete jumps of the clock nemesis).
"""
from __future__ import annotations

import random
from typing import Optional

from .control.core import escape, exec_, exec_star, lit, su


def script(bin_path: str, rate: float) -> str:
    """A wrapper script body running bin under faketime at the given
    rate (faketime.clj:8-17)."""
    return (f"#!/bin/bash\n"
            f"faketime -m -f \"+0s x{rate:.2f}\" {bin_path}.real "
            f'"$@"\n')


def wrap(bin_path: str, rate: float) -> None:
    """Move bin to bin.real and install a faketime wrapper in its place
    (faketime.clj:19-31). Idempotent."""
    with su():
        moved = exec_star(
            f"if [ ! -f {escape(bin_path)}.real ]; then "
            f"mv {escape(bin_path)} {escape(bin_path)}.real; fi; echo ok")
        assert moved.strip() == "ok"
        exec_("printf", "%s", script(bin_path, rate),
              lit(">"), bin_path)
        exec_("chmod", "a+x", bin_path)


def unwrap(bin_path: str) -> None:
    """Restore the original binary."""
    with su():
        exec_star(
            f"if [ -f {escape(bin_path)}.real ]; then "
            f"mv {escape(bin_path)}.real {escape(bin_path)}; fi")


def rand_rate(rng: Optional[random.Random] = None) -> float:
    """A random clock rate in (0, 5] (faketime.clj rand-factor)."""
    return round(((rng or random).random() * 4.99) + 0.01, 2)
