"""Remote control: run commands on db nodes over SSH.

Mirrors jepsen/src/jepsen/control.clj. The reference keeps a persistent
jsch session per node wrapped in an auto-reconnect layer
(control.clj:270-286, reconnect.clj); here the transport is the OpenSSH
client with a ControlMaster multiplexing socket per node — the master
holds the persistent connection, each exec is a cheap mux client, and a
dropped master is re-established transparently by the next call, which
is the same reconnect discipline with the state pushed into ssh(1).
Transient transport failures (exit 255) are retried with jittered
backoff (control.clj:140-160).

The reference binds per-thread dynamic vars for host/session/dir/sudo
(control.clj:15-26); workers here carry the same state in a
``threading.local`` stack, so the API reads the same way:

    with control.with_session(node, session):
        with control.su():
            control.exec_("apt-get", "install", "-y", "etcd")

``dummy`` mode (control.clj:15,274-277) stubs the transport: commands
are recorded and acknowledged without any SSH, letting every layer above
— os/db setup, nemesis, full test orchestration — run anywhere.
"""
from __future__ import annotations

import os
import random
import re
import subprocess
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Transient-failure retry budget: ONE knob for every control-plane
# retry loop (ssh_run's transport retries and control.util.with_retry's
# exec-level retries), so suite setup survives a dropped connection
# without each call site inventing its own policy. $JT_SSH_RETRIES
# overrides; per-session "retries" in the ssh config still wins.
SSH_RETRIES = int(os.environ.get("JT_SSH_RETRIES", "3"))

# Exponential backoff base between transient retries (doubles per
# attempt, capped at SSH_BACKOFF_CAP_S, plus jitter).
SSH_BACKOFF_S = float(os.environ.get("JT_SSH_BACKOFF_S", "0.5"))
SSH_BACKOFF_CAP_S = 8.0

DEFAULT_SSH = {
    "username": "root",
    "password": None,
    "port": 22,
    "private_key_path": None,
    "strict_host_key_checking": False,
    "dummy": False,
    "retries": SSH_RETRIES,
}


class RemoteError(RuntimeError):
    """A remote command returned nonzero exit status
    (control.clj:118-133)."""

    def __init__(self, cmd: str, host, exit: int, out: str, err: str):
        super().__init__(
            f"{cmd} returned non-zero exit status {exit} on {host}. "
            f"STDOUT:\n{out}\n\nSTDERR:\n{err}")
        self.cmd, self.host, self.exit, self.out, self.err = \
            cmd, host, exit, out, err


@dataclass
class Literal:
    """A string passed to the shell unescaped (control.clj:44-49)."""

    string: str


def lit(s: str) -> Literal:
    return Literal(s)


_NEEDS_QUOTE = re.compile(r'[\\$`"\'\s(){}\[\]*?<>&;|#~!]')


def escape(s) -> str:
    """Shell-escape a thing (control.clj:53-96): None → "", Literal
    passes through, sequences escape element-wise space-joined."""
    if s is None:
        return ""
    if isinstance(s, Literal):
        return s.string
    if isinstance(s, (list, tuple, set, frozenset)):
        return " ".join(escape(x) for x in s)
    s = str(s)
    if s == "":
        return '""'
    if _NEEDS_QUOTE.search(s):
        return '"' + re.sub(r'([\\$`"])', r"\\\1", s) + '"'
    return s


# ------------------------------------------------------------ transports

class Transport:
    def run(self, cmd: str, stdin: Optional[str]) -> Tuple[str, str, int]:
        raise NotImplementedError

    def upload(self, local: str, remote: str) -> None:
        raise NotImplementedError

    def download(self, remote: str, local: str) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SSHTransport(Transport):
    """OpenSSH subprocess transport with a ControlMaster mux socket."""

    def __init__(self, host, cfg: dict):
        self.host = str(host)
        self.cfg = cfg
        sockdir = os.path.join(
            os.environ.get("XDG_RUNTIME_DIR", "/tmp"), "jepsen-ssh")
        os.makedirs(sockdir, exist_ok=True)
        self.sock = os.path.join(sockdir, f"{self.host}-{os.getpid()}")

    def _base(self, prog: str) -> List[str]:
        cfg = self.cfg
        args = [prog, "-o", "BatchMode=yes",
                "-o", "ControlMaster=auto",
                "-o", f"ControlPath={self.sock}",
                "-o", "ControlPersist=60"]
        if not cfg.get("strict_host_key_checking"):
            args += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null",
                     "-o", "LogLevel=ERROR"]
        if cfg.get("private_key_path"):
            args += ["-i", cfg["private_key_path"]]
        port = cfg.get("port") or 22
        args += ["-p" if prog == "ssh" else "-P", str(port)]
        return args

    @property
    def _target(self) -> str:
        user = self.cfg.get("username") or "root"
        return f"{user}@{self.host}"

    def run(self, cmd: str, stdin: Optional[str]) -> Tuple[str, str, int]:
        timeout = self.cfg.get("timeout", 600)
        try:
            p = subprocess.run(self._base("ssh") + [self._target, cmd],
                               input=stdin, capture_output=True, text=True,
                               timeout=timeout)
        except subprocess.TimeoutExpired as e:
            # Surface as an ordinary failed command (exit 124, as
            # timeout(1) would) so callers' RemoteError handling and
            # retry policies apply instead of an uncaught exception.
            out = e.stdout.decode(errors="replace") if e.stdout else ""
            err = e.stderr.decode(errors="replace") if e.stderr else ""
            return out, err + f"\nssh command timed out after {timeout}s", 124
        return p.stdout, p.stderr, p.returncode

    def upload(self, local: str, remote: str) -> None:
        p = subprocess.run(
            self._base("scp") + ["-r", local, f"{self._target}:{remote}"],
            capture_output=True, text=True)
        if p.returncode != 0:
            raise RemoteError(f"scp {local}", self.host, p.returncode,
                              p.stdout, p.stderr)

    def download(self, remote: str, local: str) -> None:
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        p = subprocess.run(
            self._base("scp") + ["-r", f"{self._target}:{remote}", local],
            capture_output=True, text=True)
        if p.returncode != 0:
            raise RemoteError(f"scp {remote}", self.host, p.returncode,
                              p.stdout, p.stderr)

    def close(self) -> None:
        subprocess.run(self._base("ssh") + ["-O", "exit", self._target],
                       capture_output=True, text=True)


class LocalTransport(Transport):
    """Run commands as local subprocesses — the real-process twin of
    dummy mode. The "node" is a logical name; suites parameterize ports
    and directories per node. Every control-plane helper
    (install_archive, start_daemon, grepkill, the clock-tool compile
    path) executes against genuine local processes, which is the CI seam
    for suite integration tests in environments without SSH-able
    cluster nodes (enable with ssh: {"local": True})."""

    def __init__(self, host, cfg: dict):
        self.host = host
        self.cfg = cfg

    def run(self, cmd: str, stdin: Optional[str]) -> Tuple[str, str, int]:
        timeout = self.cfg.get("timeout", 600)
        try:
            p = subprocess.run(["bash", "-c", cmd], input=stdin,
                               capture_output=True, text=True,
                               timeout=timeout)
        except subprocess.TimeoutExpired as e:
            out = e.stdout.decode(errors="replace") if e.stdout else ""
            err = e.stderr.decode(errors="replace") if e.stderr else ""
            return out, err + f"\ncommand timed out after {timeout}s", 124
        return p.stdout, p.stderr, p.returncode

    def upload(self, local: str, remote: str) -> None:
        p = subprocess.run(["cp", "-r", local, remote],
                           capture_output=True, text=True)
        if p.returncode != 0:
            raise RemoteError(f"cp {local}", self.host, p.returncode,
                              p.stdout, p.stderr)

    def download(self, remote: str, local: str) -> None:
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        p = subprocess.run(["cp", "-r", remote, local],
                           capture_output=True, text=True)
        if p.returncode != 0:
            raise RemoteError(f"cp {remote}", self.host, p.returncode,
                              p.stdout, p.stderr)


class DummyTransport(Transport):
    """No SSH at all: records commands, acknowledges everything
    (control.clj:15,274-277). ``responder`` may map a command to fake
    (out, err, exit) results for tests."""

    def __init__(self, host, responder: Optional[Callable] = None):
        self.host = host
        self.commands: List[str] = []
        self.uploads: List[Tuple[str, str]] = []
        self.downloads: List[Tuple[str, str]] = []
        self.responder = responder
        self._lock = threading.Lock()

    def run(self, cmd, stdin):
        with self._lock:
            self.commands.append(cmd)
        if self.responder is not None:
            r = self.responder(self.host, cmd)
            if r is not None:
                return r
        return "", "", 0

    def upload(self, local, remote):
        with self._lock:
            self.uploads.append((local, remote))

    def download(self, remote, local):
        with self._lock:
            self.downloads.append((remote, local))


@dataclass
class Session:
    """A per-node control session: transport + retry policy + sudo
    password (carried here, not in thread-local state, so on_nodes
    worker threads see it)."""

    host: object
    transport: Transport
    retries: int = 5
    password: Optional[str] = None

    def close(self) -> None:
        self.transport.close()


def session(host, ssh_cfg: Optional[dict] = None,
            responder: Optional[Callable] = None) -> Session:
    cfg = {**DEFAULT_SSH, **(ssh_cfg or {})}
    if cfg.get("dummy"):
        t: Transport = DummyTransport(host, responder)
    elif cfg.get("local"):
        t = LocalTransport(host, cfg)
    else:
        t = SSHTransport(host, cfg)
    return Session(host=host, transport=t,
                   retries=cfg.get("retries", 5),
                   password=cfg.get("password"))


# --------------------------------------------------- per-thread context

class _Ctx(threading.local):
    def __init__(self):
        self.host = None
        self.session: Optional[Session] = None
        self.dir = "/"
        self.sudo: Optional[str] = None
        self.trace = False


_ctx = _Ctx()


@contextmanager
def with_session(host, sess: Session):
    """Bind host + session for this thread (control.clj:297-304)."""
    old = (_ctx.host, _ctx.session)
    _ctx.host, _ctx.session = host, sess
    try:
        yield sess
    finally:
        _ctx.host, _ctx.session = old


@contextmanager
def cd(dir: str):
    """Evaluate body in ``dir`` (control.clj:231-236); relative paths
    resolve against the current dir."""
    old = _ctx.dir
    _ctx.dir = expand_path(dir)
    try:
        yield
    finally:
        _ctx.dir = old


@contextmanager
def sudo(user: str):
    old = _ctx.sudo
    _ctx.sudo = user
    try:
        yield
    finally:
        _ctx.sudo = old


def su():
    """sudo root (control.clj:244-247)."""
    return sudo("root")


@contextmanager
def trace():
    old = _ctx.trace
    _ctx.trace = True
    try:
        yield
    finally:
        _ctx.trace = old


def expand_path(path: str) -> str:
    if path.startswith("/"):
        return path
    base = _ctx.dir or "/"
    return base + ("" if base.endswith("/") else "/") + path


def _wrap(cmd: str, stdin: Optional[str]) -> Tuple[str, Optional[str]]:
    if _ctx.dir:
        cmd = f"cd {escape(_ctx.dir)}; {cmd}"
    if _ctx.sudo:
        cmd = f"sudo -S -u {_ctx.sudo} bash -c {escape(cmd)}"
        pw = _ctx.session.password if _ctx.session else None
        stdin = (pw + "\n" + (stdin or "")) if pw else stdin
    return cmd, stdin


def backoff_delay(attempt: int, base: float = SSH_BACKOFF_S,
                  cap: float = SSH_BACKOFF_CAP_S) -> float:
    """Jittered exponential backoff: base·2^attempt capped, plus up to
    half the base of jitter so a whole node fleet retrying a dropped
    switch doesn't re-stampede in lockstep."""
    return min(cap, base * (2 ** attempt)) + random.random() * base / 2


def ssh_run(cmd: str, stdin: Optional[str] = None) -> Tuple[str, str, int]:
    """Run a raw (already-wrapped) command with transient-failure retry
    (control.clj:140-160; exit 255 = OpenSSH transport failure, which
    also covers failures to CONNECT — a dead master socket, a refused
    TCP connect). Retries use jittered exponential backoff up to the
    session's budget (the single SSH_RETRIES knob). OS-level transport
    errors (the ssh/scp subprocess itself failing to spawn or being
    torn down mid-call) are normalized to exit 255 so one retry policy
    covers every transient shape."""
    s = _ctx.session
    if s is None:
        raise RuntimeError(
            f"No SSH session bound for this thread (host={_ctx.host!r}); "
            f"run inside with_session/on/on_nodes")
    tries = s.retries
    attempt = 0
    while True:
        try:
            out, err, code = s.transport.run(cmd, stdin)
        except OSError as e:
            out, err, code = "", f"transport error: {e}", 255
        if code == 255 and tries > 0:
            tries -= 1
            time.sleep(backoff_delay(attempt))
            attempt += 1
            continue
        return out, err, code


def exec_star(*commands, stdin: Optional[str] = None) -> str:
    """Like exec_, but does not escape (control.clj:162-174)."""
    cmd = " ".join(str(c) for c in commands)
    cmd, stdin = _wrap(cmd, stdin)
    if _ctx.trace:
        import logging
        logging.getLogger("jepsen.control").info("%s: %s", _ctx.host, cmd)
    out, err, code = ssh_run(cmd, stdin)
    if code != 0:
        raise RemoteError(cmd, _ctx.host, code, out, err)
    return out.rstrip("\n")


def exec_(*commands, stdin: Optional[str] = None) -> str:
    """Run a command (args escaped), return trimmed stdout, throw on
    nonzero exit (control.clj:175-181)."""
    return exec_star(*(escape(c) for c in commands), stdin=stdin)


def upload(local: str, remote: str) -> None:
    """Copy a local path to the current node (control.clj:191-200)."""
    _ctx.session.transport.upload(local, remote)


def upload_bytes(data: bytes, remote: str) -> None:
    """Ship in-memory bytes to a remote file (used to push C sources and
    configs without temp-file bookkeeping)."""
    import base64
    b64 = base64.b64encode(data).decode("ascii")
    exec_star(f"echo {b64} | base64 -d > {escape(remote)}")


def download(remote: str, local: str) -> None:
    """Copy a remote path to the local machine (control.clj:205-217)."""
    _ctx.session.transport.download(remote, local)


@contextmanager
def on(host, ssh_cfg: Optional[dict] = None):
    """Open a session to host, bind it, close on exit
    (control.clj:306-315)."""
    s = session(host, ssh_cfg)
    try:
        with with_session(host, s):
            yield s
    finally:
        s.close()


@contextmanager
def with_ssh(test: dict):
    """Open sessions to every node into test["sessions"]; close them all
    at exit (control.clj:288-295 + with-resources at core.clj:400-404)."""
    cfg = {**DEFAULT_SSH, **(test.get("ssh") or {})}
    responder = (test.get("ssh") or {}).get("responder")
    sessions: Dict[object, Session] = {}
    try:
        for node in test.get("nodes") or []:
            sessions[node] = session(node, cfg, responder)
        test["sessions"] = sessions
        yield sessions
    finally:
        for s in sessions.values():
            try:
                s.close()
            except Exception:
                pass
        test.pop("sessions", None)


def on_nodes(test: dict, f: Callable, nodes: Optional[Sequence] = None
             ) -> dict:
    """Evaluate f(test, node) in parallel on each node with its session
    bound; returns {node: result} (control.clj:337-353)."""
    from concurrent.futures import ThreadPoolExecutor

    nodes = list(nodes if nodes is not None else (test.get("nodes") or []))
    if not nodes:
        return {}
    sessions = test.get("sessions") or {}

    def run_one(node):
        s = sessions.get(node)
        if s is None:
            raise RuntimeError(f"No session for node {node!r}")
        with with_session(node, s):
            return f(test, node)

    with ThreadPoolExecutor(max_workers=len(nodes),
                            thread_name_prefix="jepsen-node") as ex:
        futs = {node: ex.submit(run_one, node) for node in nodes}
        out, errs = {}, []
        for node, fut in futs.items():
            try:
                out[node] = fut.result()
            except Exception as e:
                errs.append(e)
        if errs:
            raise errs[0]
        return out


def on_many(hosts: Sequence, f: Callable,
            ssh_cfg: Optional[dict] = None) -> dict:
    """Open sessions to hosts, run f() on each in parallel
    (control.clj:317-326)."""
    from concurrent.futures import ThreadPoolExecutor

    def run_one(h):
        with on(h, ssh_cfg):
            return f(h)

    with ThreadPoolExecutor(max_workers=max(1, len(hosts))) as ex:
        return dict(zip(hosts, ex.map(run_one, hosts)))
