"""Host/IP helpers over the control session
(jepsen/src/jepsen/control/net.clj)."""
from __future__ import annotations

from .core import RemoteError, exec_, lit


def reachable(host) -> bool:
    """Can the current node ping host? (control/net.clj:7-12)"""
    try:
        exec_("ping", "-w", 1, host)
        return True
    except RemoteError:
        return False


def local_ip() -> str:
    """The local node's first IP address (control/net.clj:14-21)."""
    return exec_("hostname", "-I", lit("|"), "awk", lit("'{print $1}'"))


def ip(host: str) -> str:
    """Resolve a hostname to an IP on the current node via getent
    (control/net.clj:23-30)."""
    out = exec_("getent", "ahosts", host)
    for line in out.split("\n"):
        parts = line.split()
        if len(parts) >= 2 and parts[1] in ("STREAM", "RAW"):
            return parts[0]
    return out.split()[0] if out.split() else ""


def control_ip() -> str:
    """IP of the control node as seen from here."""
    return exec_("echo", lit("${SSH_CLIENT%% *}"))
