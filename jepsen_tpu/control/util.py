"""Utility functions for scripting installations on db nodes.

Mirrors jepsen/src/jepsen/control/util.clj: existence checks, tarball
deployment with corrupt-download retry, user management, pattern kills,
and daemon start/stop via start-stop-daemon + pidfiles.
"""
from __future__ import annotations

import logging
import posixpath
import random
import re
from typing import List, Optional

from .core import (SSH_RETRIES, RemoteError, backoff_delay, cd, exec_,
                   exec_star, expand_path, lit, su, _ctx)

log = logging.getLogger("jepsen.control.util")

TMP_DIR_BASE = "/tmp/jepsen"

# Remote exit codes that mean the TRANSPORT (not the command) failed:
# 255 = OpenSSH connect/exec failure, 124 = the transport-level command
# deadline fired (core's TimeoutExpired normalization).
TRANSIENT_EXITS = (255, 124)


def meh(f, *args, **kw):
    """Run f, swallowing remote errors (the reference's util/meh)."""
    try:
        return f(*args, **kw)
    except RemoteError:
        return None


def is_transient(e: BaseException) -> bool:
    """Did this remote failure come from the transport rather than the
    command? Only those are safe to blindly retry — a nonzero exit from
    the command itself usually isn't idempotent to repeat."""
    return isinstance(e, RemoteError) and e.exit in TRANSIENT_EXITS


def with_retry(f, *args, attempts: int = None, on_retry=None, **kw):
    """Run a control-plane step with bounded retry-with-backoff on
    TRANSIENT remote failures (is_transient) — the setup-level
    companion to ssh_run's per-command transport retry, for multi-
    command steps (install_archive, daemon starts, readiness probes)
    where one dropped connection mid-step must not abort the whole
    suite run. ``attempts`` defaults to the single SSH_RETRIES knob
    ($JT_SSH_RETRIES, default 3 — extra attempts beyond the first);
    non-transient failures propagate immediately."""
    attempts = SSH_RETRIES if attempts is None else max(0, int(attempts))
    for attempt in range(attempts + 1):
        try:
            return f(*args, **kw)
        except RemoteError as e:
            if not is_transient(e) or attempt == attempts:
                raise
            log.warning("transient remote failure on %s (attempt %s/%s"
                        "): %s; retrying", _ctx.host, attempt + 1,
                        attempts + 1, str(e).splitlines()[0])
            if on_retry is not None:
                on_retry(attempt, e)
            import time
            time.sleep(backoff_delay(attempt))
    raise AssertionError("unreachable")


def exists(filename: str) -> bool:
    """Is a path present? (util.clj:17-22)"""
    try:
        exec_("stat", filename)
        return True
    except RemoteError:
        return False


def ls(dir: str = ".") -> List[str]:
    """Directory entries, dotfiles included (util.clj:24-31)."""
    out = exec_("ls", "-A", dir)
    return [line for line in out.split("\n") if line.strip()]


def ls_full(dir: str) -> List[str]:
    d = dir if dir.endswith("/") else dir + "/"
    return [d + e for e in ls(d)]


def tmp_dir() -> str:
    """A fresh temporary directory under /tmp/jepsen (util.clj:41-49)."""
    while True:
        d = f"{TMP_DIR_BASE}/{random.randrange(2**31)}"
        if not exists(d):
            exec_("mkdir", "-p", d)
            return d


def wget(url: str, force: bool = False) -> str:
    """Download url into the current directory (skipping when cached);
    returns the filename (util.clj:51-70)."""
    filename = posixpath.basename(url)
    if force:
        exec_("rm", "-f", filename)
    if not exists(filename):
        exec_("wget", "--tries", 20, "--waitretry", 60,
              "--retry-connrefused", "--dns-timeout", 60,
              "--connect-timeout", 60, "--read-timeout", 60, url)
    return filename


def install_archive(url: str, dest: str, force: bool = False) -> str:
    """Fetch a tarball/zip (cached in /tmp/jepsen), extract its sole
    top-level directory's contents (or all files) into dest, retrying
    corrupt downloads (util.clj:72-141)."""
    m = re.match(r"file://(.+)", url)
    if m:
        local_file: Optional[str] = m.group(1)
        file = local_file
    else:
        local_file = None
        exec_("mkdir", "-p", TMP_DIR_BASE)
        with cd(TMP_DIR_BASE):
            # Downloads are idempotent (wget skips the cached file), so
            # a dropped connection mid-fetch retries instead of
            # aborting the node's whole setup.
            file = expand_path(with_retry(wget, url, force))
    tmpdir = tmp_dir()
    dest = expand_path(dest)

    exec_("rm", "-rf", dest)
    parent = exec_("dirname", dest)
    exec_("mkdir", "-p", parent)

    try:
        with cd(tmpdir):
            if re.search(r"\.zip$", file):
                exec_("unzip", file)
            else:
                exec_("tar", "xf", file)
            if _ctx.sudo == "root":
                exec_("chown", "-R", "root:root", ".")
            roots = ls()
            assert roots, "Archive contained no files"
            if len(roots) == 1:
                exec_("mv", roots[0], dest)
            else:
                exec_("mv", tmpdir, dest)
    except RemoteError as e:
        if "tar: Unexpected EOF" in str(e):
            if local_file:
                raise RuntimeError(
                    f"Local archive {local_file} on node {_ctx.host} is "
                    f"corrupt: unexpected EOF.") from e
            log.info("Retrying corrupt archive download")
            exec_("rm", "-rf", file)
            return install_archive(url, dest, force)
        raise
    finally:
        meh(exec_, "rm", "-rf", tmpdir)
    return dest


def ensure_user(username: str) -> str:
    """Make sure a user exists (util.clj:150-157)."""
    try:
        with su():
            exec_("adduser", "--disabled-password", "--gecos", lit("''"),
                  username)
    except RemoteError as e:
        if "already exists" not in str(e):
            raise
    return username


def grepkill(pattern: str, signal: int = 9) -> None:
    """Kill processes matching a pattern (util.clj:159-174)."""
    try:
        # xargs -r: no matching processes is routine, not an error.
        exec_("ps", "aux", lit("|"), "grep", pattern, lit("|"),
              "grep", "-v", "grep", lit("|"), "awk", lit("'{print $2}'"),
              lit("|"), "xargs", "-r", "kill", f"-{signal}")
    except RemoteError as e:
        # kill may still race a process that exited on its own.
        if "No such process" not in e.err:
            raise


def start_daemon(opts: dict, bin: str, *args) -> None:
    """Start a daemon with output to a logfile and a pidfile, via
    start-stop-daemon (util.clj:176-219). Opts: logfile, pidfile, chdir,
    background (True), make_pidfile (True), match_executable (True),
    match_process_name (False), process_name."""
    log.info("starting %s", posixpath.basename(bin))
    exec_("echo", lit("`date +'%Y-%m-%d %H:%M:%S'`"),
          "Jepsen starting", bin, " ".join(str(a) for a in args),
          lit(">>"), opts["logfile"])
    cmd: List = ["start-stop-daemon", "--start"]
    if opts.get("background", True):
        cmd += ["--background", "--no-close"]
    if opts.get("make_pidfile", True):
        cmd += ["--make-pidfile"]
    if opts.get("match_executable", True):
        cmd += ["--exec", bin]
    if opts.get("match_process_name", False):
        cmd += ["--name", opts.get("process_name", posixpath.basename(bin))]
    cmd += ["--pidfile", opts["pidfile"]]
    if opts.get("chdir"):
        cmd += ["--chdir", opts["chdir"]]
    cmd += ["--oknodo", "--startas", bin, "--"]
    cmd += list(args) + [lit(">>"), opts["logfile"], lit("2>&1")]
    exec_(*cmd)


def stop_daemon(pidfile: str, cmd: Optional[str] = None) -> None:
    """Kill a daemon by pidfile, or by command name (util.clj:221-236)."""
    if cmd is not None:
        log.info("Stopping %s", cmd)
        meh(exec_, "killall", "-9", "-w", cmd)
        meh(exec_, "rm", "-rf", pidfile)
        return
    if exists(pidfile):
        log.info("Stopping %s", pidfile)
        pid = int(exec_("cat", pidfile))
        meh(exec_, "kill", "-9", pid)
        meh(exec_, "rm", "-rf", pidfile)


def await_cmd(probe: str, desc: str, tries: int = 60,
              sleep: float = 1.0) -> None:
    """Poll a node-side probe command until it exits 0, failing loudly
    after ``tries`` attempts — the shared readiness-wait loop behind
    every "service is up" check (the reference's per-suite wait loops,
    e.g. elasticsearch core.clj:247-261, mongodb core.clj:228-232)."""
    exec_star(
        f"for i in $(seq {tries}); do "
        f"{probe} && exit 0; sleep {sleep}; done; "
        f"echo {desc} never became ready; exit 1")


def daemon_running(pidfile: str) -> bool:
    """Is the pidfile's process alive?"""
    if not exists(pidfile):
        return False
    try:
        pid = int(exec_("cat", pidfile))
        exec_("kill", "-0", pid)
        return True
    except (RemoteError, ValueError):
        return False
