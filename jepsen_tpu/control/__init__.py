"""Remote-control layer: SSH exec, install helpers, net utilities.

The communication backend of the harness (SURVEY.md §2.4): a persistent
multiplexed OpenSSH transport per node with retry/reconnect discipline,
plus a dummy transport that stubs it all out for no-cluster runs.
"""
from .core import (DEFAULT_SSH, DummyTransport, Literal, RemoteError,
                   Session, SSHTransport, cd, download, escape, exec_,
                   exec_star, lit, on, on_many, on_nodes, session, su, sudo,
                   trace, upload, upload_bytes, with_session, with_ssh)
