"""Mesh construction and the data-parallel sharded checker.

The batched checker is data-parallel over histories: shard every encoded
array's batch axis across the mesh and jit the vmapped kernel with
sharding annotations — XLA partitions the scan and inserts the collectives
for any cross-shard reductions (the summary all-reduce rides ICI). Scale-
out to multi-host batches is the same program over a bigger mesh (DCN
between hosts), which is how the reference's "check thousands of stored
histories" replay seam (jepsen/src/jepsen/store.clj:165-171) maps to
devices.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.linearize import MIN_ROWS_PER_DEVICE, make_kernel


def shard_min_rows() -> int:
    """$JT_SHARD_MIN_ROWS: per-device row floor for the batch-sharded
    (dataN) route. A sharded dispatch whose per-device slice drops
    below it pays more in collective setup and per-device launch than
    the split saves — the MULTICHIP_r06 curve's 4/8-device regression
    (dispatch_s 0.21 → 1.01 at n=4) was exactly this sub-minimum
    sharding, 256 fixed rows thinning to 64/32 per device. Default
    MIN_ROWS_PER_DEVICE (the historical floor); deployments that
    measure their own crossover raise it and the dataN path falls back
    to the single-device kernel below it (should_shard)."""
    env = os.environ.get("JT_SHARD_MIN_ROWS")
    if env is not None:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return MIN_ROWS_PER_DEVICE


def should_shard(rows: int, mesh: Optional[Mesh]) -> bool:
    """Whether a ``rows``-row batch should take the batch-sharded
    (dataN) route on ``mesh`` — False when the per-device slice would
    drop below ``shard_min_rows()``, in which case callers run the
    single-device kernel instead (ops.linearize.run_encoded_batch's
    routing; the BucketScheduler derives its default hand-off bound
    from the same floor)."""
    if mesh is None:
        return False
    return rows >= mesh.shape["data"] * shard_min_rows()


def checker_mesh(n_data: Optional[int] = None, n_frontier: int = 1,
                 devices: Optional[Sequence] = None) -> Mesh:
    """A ("data", "frontier") mesh. Defaults to all devices on the data
    axis (pure history-parallelism)."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_frontier
    use = np.array(devices[:n_data * n_frontier]).reshape(
        n_data, n_frontier)
    return Mesh(use, axis_names=("data", "frontier"))


def multihost_mesh(n_hosts: int, n_data: Optional[int] = None,
                   n_frontier: int = 1,
                   devices: Optional[Sequence] = None) -> Mesh:
    """A ("dcn", "data", "frontier") mesh for multi-host scale-out: the
    leading axis spans hosts (collectives across it ride DCN), the
    inner two stay within a host's ICI domain. The batch shards over
    ("dcn", "data") — histories are independent, so the ONLY cross-host
    traffic is the final verdict psum (summarize_verdicts), exactly the
    layout the scaling playbook prescribes: fat per-chip work, skinny
    DCN reductions. On real hardware build this from
    jax.devices() after multi-host init (one process per host sees the
    global device list); under the virtual CPU mesh it validates the
    same compiled program."""
    devices = list(devices if devices is not None else jax.devices())
    per_host = len(devices) // n_hosts
    if n_data is None:
        n_data = per_host // n_frontier
    need = n_hosts * n_data * n_frontier
    if n_data < 1 or need > len(devices):
        # Fail at construction, not deep inside XLA sharding.
        raise ValueError(
            f"multihost_mesh({n_hosts=}, {n_data=}, {n_frontier=}) "
            f"needs {max(need, n_hosts * n_frontier)} devices, "
            f"have {len(devices)}")
    use = np.array(devices[:need]).reshape(n_hosts, n_data, n_frontier)
    return Mesh(use, axis_names=("dcn", "data", "frontier"))


def _batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh axes the history batch shards over: every axis except
    the frontier (mask) axis — ("data",) on a flat mesh,
    ("dcn", "data") on a multi-host one."""
    return tuple(n for n in mesh.axis_names if n != "frontier")


def data_sharded_kernel(V: int, W: int, mesh: Mesh,
                        shared_target: bool = False,
                        donate: bool = False,
                        w_live: Optional[int] = None):
    """Compile the batched checker with the batch axis sharded over the
    mesh's batch axes (("data"), or ("dcn", "data") on a multi-host
    mesh). Returns check(ev_type [B,N], ev_slot [B,N],
    ev_slots [B,N,W], target [B,K+1,V]) -> (valid [B], bad [B],
    frontier [B, words(V), 2^W]); B must divide by the batch-axis size.
    ``shared_target``: target is one replicated [K+1, V] table instead
    of a per-row batch (one transfer, not B). ``donate``: the event
    buffers are donated to the call (the chunk path ships each exactly
    once). ``w_live`` bounds the kernel's slot unroll to the batch's
    real peak-live window (ops.linearize.make_kernel).

    Production dispatch resolves this builder through the process-wide
    kernel registry (ops.linearize.get_kernel) — one cache for the
    single-device, data-sharded, and frontier-sharded variants, so
    compile accounting and pre-warming see a single kernel set."""
    axes = _batch_axes(mesh)
    batch_spec = NamedSharding(mesh, P(axes))
    out_spec = NamedSharding(mesh, P(axes))
    tgt_spec = NamedSharding(mesh, P()) if shared_target else batch_spec
    kern = jax.vmap(make_kernel(V, W, w_live=w_live),
                    in_axes=(0, 0, 0, None if shared_target else 0))
    return jax.jit(kern,
                   in_shardings=(batch_spec,) * 3 + (tgt_spec,),
                   out_shardings=(out_spec, out_spec, out_spec),
                   donate_argnums=(0, 1, 2) if donate else ())


def summarize_verdicts(valid: jnp.ndarray) -> dict:
    """Global verdict reduction (XLA lowers these to psums on a sharded
    batch): total, invalid count, first invalid row."""
    n = valid.shape[0]
    invalid = jnp.sum(~valid)
    first_bad = jnp.min(jnp.where(valid, np.int32(2**31 - 1),
                                  jnp.arange(n, dtype=jnp.int32)))
    return {"histories": int(n), "invalid": int(invalid),
            "first_invalid_row": int(first_bad)}
