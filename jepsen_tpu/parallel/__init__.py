"""Device-mesh parallelism for the batched checker.

Two orthogonal axes (SURVEY.md §2.3 "TPU mapping"):

  data     — histories-per-batch: embarrassingly parallel; shard the batch
             axis of the encoded tensors over the mesh and let XLA insert
             the verdict all-reduce.
  frontier — within one history, the WGL configuration frontier's mask
             axis (2^W pending subsets) splits across devices — the
             sequence-parallel analog for this domain. Pending-op applies
             on device-local mask bits stay local; applies/completions on
             the top log2(D) bits become hypercube ppermute exchanges.

The reference has no device parallelism at all — its analogs are JVM
thread pools and pmap'd checkers (jepsen/src/jepsen/checker.clj:384-386,
jepsen/src/jepsen/util.clj:44-50); the mesh design subsumes them.
"""
from .mesh import checker_mesh, data_sharded_kernel, multihost_mesh
from .frontier import frontier_sharded_kernel
