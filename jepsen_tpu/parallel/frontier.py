"""Frontier-sharded WGL checking — sequence parallelism for histories.

One history's WGL configuration frontier F[V, 2^W] can exceed a single
core's VMEM when the pending window W is large (long histories under
heavy fault injection accumulate indeterminate ops, each pinning a slot —
SURVEY.md §5 "long-context"). The fix is the sequence-parallel analog for
this domain: split the mask axis across D = 2^log2D devices, so device d
holds the configs whose top log2D mask bits equal d.

  * applies/completions on slots < W_local touch only local mask bits —
    no communication;
  * an apply on top bit b maps configs (s, m w/o bit b) — which live
    entirely on devices with axis-index bit b clear — to (target s,
    m | bit b) on the partner device: a hypercube `lax.ppermute` exchange
    of the transitioned block;
  * a completion on top bit b moves the surviving blocks from bit-set
    devices to their bit-clear partners (the mask with the bit cleared);
  * emptiness and closure-convergence checks are `lax.psum` reductions
    over the frontier axis.

The encoder allocates low slots first (jepsen_tpu.ops.encode), so
histories whose live-pending count stays below W_local never touch the
top bits and pay only the convergence psums — communication is
proportional to how hard the history actually is.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..ops.encode import EV_OK
from ..ops.linearize import _apply_slot, _complete_slot, INT32_MAX


def _top_apply(F, b, tgt_b, V, D):
    """Close one step under the op in top-bit slot b (cross-device)."""
    bit = 1 << b
    ax = lax.axis_index("frontier")
    is_clear = (ax & bit) == 0
    onehot = tgt_b[:, None] == jnp.arange(V, dtype=jnp.int32)[None, :]
    G = jnp.matmul(onehot.astype(jnp.bfloat16).T, F.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) > 0
    G = jnp.where(is_clear, G, False)          # only bit-clear configs spawn
    perm = [(d, d | bit) for d in range(D) if not (d & bit)]
    recv = lax.ppermute(G, "frontier", perm)   # non-receivers get zeros
    return F | recv


def _top_complete(F, b, D):
    """OK-completion of the op in top-bit slot b: surviving blocks move
    from bit-set devices to their bit-clear partners."""
    bit = 1 << b
    perm = [(d | bit, d) for d in range(D) if not (d & bit)]
    recv = lax.ppermute(F, "frontier", perm)
    ax = lax.axis_index("frontier")
    return jnp.where((ax & bit) == 0, recv, False)


def _pany(x, axes=("frontier",)) -> jnp.ndarray:
    """Global any() over the given mesh axes."""
    return lax.psum(x.any().astype(jnp.int32), axes) > 0


def make_frontier_kernel(V: int, W: int, D: int,
                         sync_axes=("data", "frontier")):
    """Single-history checker with the frontier split over D devices.

    W is the *global* slot count; each device holds [V, 2^(W - log2 D)].
    Must run inside a shard_map binding axis name "frontier".

    ``sync_axes``: the closure's convergence flag must reduce over EVERY
    mesh axis, not just "frontier" — a data-dependent while_loop that
    contains collectives deadlocks the moment different shards pick
    different trip counts (each device must issue an identical collective
    sequence). The global psum makes all devices run the global-max
    iteration count; extra iterations on converged shards are idempotent.
    """
    log2d = D.bit_length() - 1
    assert 1 << log2d == D, "frontier axis size must be a power of two"
    W_local = W - log2d
    assert W_local >= 1
    M_local = 1 << W_local

    def closure(F, slots_row, target):
        tgt = target[slots_row]  # [W, V]

        def body(carry):
            F0, _ = carry
            Fn = F0
            for i in range(W_local):
                Fn = _apply_slot(Fn, i, tgt[i], V, M_local)
            for b in range(log2d):
                Fn = _top_apply(Fn, b, tgt[W_local + b], V, D)
            return Fn, _pany(Fn != F0, sync_axes)

        # F arrives varying over every mesh axis (the scan carry is
        # pcast below); the convergence flag is invariant — the psum in
        # the body spans every axis, so input and output types line up.
        F, _ = lax.while_loop(lambda c: c[1], body, (F, jnp.bool_(True)))
        return F

    def complete(F, slot):
        out = _complete_slot(F, jnp.minimum(slot, W_local - 1), M_local)
        for b in range(log2d):
            out = jnp.where(slot == W_local + b, _top_complete(F, b, D), out)
        return out

    def check(ev_type, ev_slot, ev_slots, target):
        def step(carry, ev):
            F, valid, bad = carry
            typ, slot, slots_row, idx = ev
            is_ok = typ == EV_OK
            Fc = closure(F, slots_row, target)
            F_ok = complete(Fc, slot)
            empty = is_ok & ~_pany(F_ok)
            F2 = jnp.where(is_ok, F_ok, F)
            return (F2, valid & ~empty,
                    jnp.minimum(bad, jnp.where(empty, idx, INT32_MAX))), None

        N = ev_type.shape[0]
        ax = lax.axis_index("frontier")
        F0 = jnp.zeros((V, M_local), jnp.bool_)
        F0 = F0.at[0, 0].set(ax == 0)    # global config (state 0, mask 0)
        # The scan consumes data-sharded events, so its carry is varying
        # over "data" — widen the initial carry's type to match.
        extra = tuple(a for a in sync_axes if a != "frontier")
        carry = (lax.pcast(F0, extra, to="varying"),
                 lax.pcast(jnp.bool_(True), extra, to="varying"),
                 lax.pcast(jnp.int32(INT32_MAX), extra, to="varying"))
        (F, valid, bad), _ = lax.scan(
            step, carry, (ev_type, ev_slot, ev_slots,
                          jnp.arange(N, dtype=jnp.int32)))
        return valid, bad

    return check


def frontier_sharded_kernel(V: int, W: int, mesh: Mesh):
    """Batched checker over a ("data", "frontier") mesh: batch rows shard
    over "data", each row's frontier splits over "frontier". Returns
    check(ev_type [B,N], ev_slot [B,N], ev_slots [B,N,W], target)
    -> (valid [B], bad [B])."""
    D = mesh.shape["frontier"]
    kern = jax.vmap(make_frontier_kernel(V, W, D), in_axes=(0, 0, 0, 0))
    ev = P("data", None)
    sharded = shard_map(kern, mesh=mesh,
                        in_specs=(ev, ev, P("data", None, None),
                                  P("data", None, None)),
                        out_specs=(P("data"), P("data")))
    return jax.jit(sharded)
