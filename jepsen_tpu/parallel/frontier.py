"""Frontier-sharded WGL checking — sequence parallelism for histories.

One history's WGL configuration frontier (packed words over 2^W mask
configs — jepsen_tpu.ops.linearize) can exceed a single core's VMEM when
the pending window W is large (long histories under heavy fault injection
accumulate indeterminate ops, each pinning a slot — SURVEY.md §5
"long-context"). The fix is the sequence-parallel analog for this domain:
split the mask axis across D = 2^log2D devices, so device d holds the
configs whose top log2D mask bits equal d.

  * applies/completions on slots < W_local touch only local mask bits —
    no communication;
  * an apply on top bit b maps configs (s, m w/o bit b) — which live
    entirely on devices with axis-index bit b clear — to (target s,
    m | bit b) on the partner device: a hypercube `lax.ppermute` exchange
    of the transitioned words;
  * a completion on top bit b moves the surviving words from bit-set
    devices to their bit-clear partners (the mask with the bit cleared);
  * emptiness and closure-convergence checks are `lax.psum` reductions
    over the frontier axis.

The encoder allocates low slots first (jepsen_tpu.ops.encode), so
histories whose live-pending count stays below W_local never touch the
top bits and pay only the convergence psums — communication is
proportional to how hard the history actually is.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..ops.encode import EV_CLOSE, EV_FUSED, EV_OK
from ..ops.linearize import (INT32_MAX, MAX_PACKED_STATES, _apply_slot,
                             _complete_slot, _changed, _union,
                             n_state_words, pack_rows, transition)


def _top_apply(F, b, rows_b, V, D):
    """Close one step under the op in top-bit slot b (cross-device):
    every config on a bit-clear device spawns its transitioned twin on
    the bit-set partner."""
    bit = 1 << b
    ax = lax.axis_index("frontier")
    is_clear = (ax & bit) == 0
    new = transition(F, rows_b, V)
    new = tuple(jnp.where(is_clear, n, jnp.uint32(0)) for n in new)
    perm = [(d, d | bit) for d in range(D) if not (d & bit)]
    recv = tuple(lax.ppermute(n, "frontier", perm) for n in new)
    return tuple(f | r for f, r in zip(F, recv))


def _top_complete(F, b, D):
    """OK-completion of the op in top-bit slot b: surviving words move
    from bit-set devices to their bit-clear partners."""
    bit = 1 << b
    perm = [(d | bit, d) for d in range(D) if not (d & bit)]
    recv = tuple(lax.ppermute(f, "frontier", perm) for f in F)
    ax = lax.axis_index("frontier")
    is_clear = (ax & bit) == 0
    return tuple(jnp.where(is_clear, r, jnp.uint32(0)) for r in recv)


def _pbool(x, axes=("frontier",)) -> jnp.ndarray:
    """Global any() of a local boolean over the given mesh axes."""
    return lax.psum(x.astype(jnp.int32), axes) > 0


def make_frontier_kernel(V: int, W: int, D: int,
                         sync_axes=("data", "frontier")):
    """Single-history checker with the frontier split over D devices.

    W is the *global* slot count; each device holds packed words over
    2^(W - log2 D) local mask configs. Must run inside a shard_map
    binding axis name "frontier".

    ``sync_axes``: the closure's convergence flag must reduce over EVERY
    mesh axis, not just "frontier" — a data-dependent while_loop that
    contains collectives deadlocks the moment different shards pick
    different trip counts (each device must issue an identical collective
    sequence). The global psum makes all devices run the global-max
    iteration count; extra iterations on converged shards are idempotent.
    """
    assert V <= MAX_PACKED_STATES
    log2d = D.bit_length() - 1
    assert 1 << log2d == D, "frontier axis size must be a power of two"
    W_local = W - log2d
    assert W_local >= 1
    M_local = 1 << W_local
    NW = n_state_words(V)

    def closure(F, slots_row, rows):
        tgt = tuple(r[slots_row] for r in rows)  # [W, V] per word

        def body(carry):
            F0, _ = carry
            Fn = F0
            for i in range(W_local):
                Fn = _apply_slot(Fn, i, tuple(t[i] for t in tgt),
                                 V, M_local)
            for b in range(log2d):
                Fn = _top_apply(Fn, b, tuple(t[W_local + b] for t in tgt),
                                V, D)
            return Fn, _pbool(_changed(Fn, F0), sync_axes)

        # F arrives varying over every mesh axis (the scan carry is
        # pcast below); the convergence flag is invariant — the psum in
        # the body spans every axis, so input and output types line up.
        F, _ = lax.while_loop(lambda c: c[1], body, (F, jnp.bool_(True)))
        return F

    def complete(F, slot):
        out = _complete_slot(F, jnp.minimum(slot, W_local - 1), M_local,
                             W_local)
        for b in range(log2d):
            top = _top_complete(F, b, D)
            out = tuple(jnp.where(slot == W_local + b, t, o)
                        for t, o in zip(top, out))
        return out

    def check(ev_type, ev_slot, ev_slots, target):
        # Narrow (int8) event arrays off the host; widen on device.
        ev_type = ev_type.astype(jnp.int32)
        ev_slot = ev_slot.astype(jnp.int32)
        ev_slots = ev_slots.astype(jnp.int32)
        rows = pack_rows(target, V)

        def step(carry, ev):
            F, Fbad, valid, bad = carry
            typ, slot, slots_row, idx = ev
            is_ok = (typ == EV_OK) | (typ == EV_FUSED)
            is_close = typ == EV_CLOSE
            Fc = closure(F, slots_row, rows)
            F_ok = complete(Fc, slot)
            empty = is_ok & ~_pbool((_union(F_ok) != 0).any())
            first = empty & valid
            F2 = tuple(jnp.where(is_ok, a, jnp.where(is_close, c, b))
                       for a, c, b in zip(F_ok, Fc, F))
            Fb2 = tuple(jnp.where(first, c, b) for c, b in zip(Fc, Fbad))
            return (F2, Fb2, valid & ~empty,
                    jnp.minimum(bad, jnp.where(empty, idx, INT32_MAX))), None

        N = ev_type.shape[0]
        ax = lax.axis_index("frontier")
        Fz = tuple(jnp.zeros((M_local,), jnp.uint32) for _ in range(NW))
        # Global config (state 0, mask 0) lives on frontier device 0.
        F0 = (Fz[0].at[0].set(jnp.where(ax == 0, jnp.uint32(1),
                                        jnp.uint32(0))),) + Fz[1:]
        # The scan consumes data-sharded events, so its carry is varying
        # over "data" — widen the initial carry's type to match. jax
        # without varying types (< pcast) skips the cast: the shard_map
        # below runs unreplicated (check_rep=False) there, so carry
        # types need no widening.
        extra = tuple(a for a in sync_axes if a != "frontier")
        if hasattr(lax, "pcast"):
            pcast = lambda x: lax.pcast(x, extra, to="varying")  # noqa: E731
            pcast_all = lambda x: lax.pcast(  # noqa: E731
                x, tuple(sync_axes), to="varying")
        else:
            pcast = pcast_all = lambda x: x  # noqa: E731
        # Fbad is written from Fc (varying over EVERY mesh axis — F0
        # derives from axis_index), so its initial value must be too.
        carry = (tuple(pcast(f) for f in F0),
                 tuple(pcast_all(f) for f in Fz),
                 pcast(jnp.bool_(True)), pcast(jnp.int32(INT32_MAX)))
        (F, Fbad, valid, bad), _ = lax.scan(
            step, carry, (ev_type, ev_slot, ev_slots,
                          jnp.arange(N, dtype=jnp.int32)))
        # Local shard of the latched frontier (mask-axis sharded; the
        # out_spec concatenation restores global mask order because the
        # top log2D mask bits ARE the frontier axis index).
        frontier = jnp.stack(
            [jnp.where(valid, a, b) for a, b in zip(F, Fbad)])
        return valid, bad, frontier

    return check


def frontier_sharded_kernel(V: int, W: int, mesh: Mesh,
                            shared_target: bool = False):
    """Batched checker over a ("data", "frontier") mesh: batch rows shard
    over "data", each row's frontier splits over "frontier". Returns
    check(ev_type [B,N], ev_slot [B,N], ev_slots [B,N,W], target)
    -> (valid [B], bad [B], frontier [B, words(V), 2^W]) — the same
    contract as the single-device kernel (ops.linearize.make_kernel), so
    production dispatch and counterexample decoding are path-agnostic.
    ``shared_target``: one replicated [K+1, V] transition table instead
    of a per-row batch."""
    D = mesh.shape["frontier"]
    kern = jax.vmap(make_frontier_kernel(V, W, D),
                    in_axes=(0, 0, 0, None if shared_target else 0))
    ev = P("data", None)
    tgt = P(None, None) if shared_target else P("data", None, None)
    kw = {}
    if not hasattr(lax, "pcast"):
        # Pre-varying-types jax: the replication checker can't see
        # through the axis_index-seeded carry + collective while_loop;
        # the out_specs still pin the sharding contract.
        kw["check_rep"] = False
    sharded = shard_map(kern, mesh=mesh,
                        in_specs=(ev, ev, P("data", None, None), tgt),
                        out_specs=(P("data"), P("data"),
                                   P("data", None, "frontier")), **kw)
    return jax.jit(sharded)
