"""Witness-guided synthesis fuzzing: generate → check → mutate, closed
on device.

The device generators (ops.synth_device) make re-synthesis cheaper than
storing histories, which turns the checker pipeline into a fuzz loop:
check a seeded batch, and for every INVALID history (the witness)
re-synthesize its PRNG neighborhood — ``order`` (same ops, perturbed
interleavings), ``values`` (same schedule, perturbed values — value
collisions), ``nemesis`` (shifted crash window, re-drawn
timeout/crash coins) — and re-dispatch the whole neighborhood as one
batch. Two things fall out:

  * **oracle fuzzing at scale** (``verify=``): every Nth neighborhood
    history ALSO decodes to the host Op-list form and re-checks on the
    exact host engine; a verdict disagreement is a checker bug, found
    by millions of generated histories instead of a hand corpus.
  * **minimal anomalies**: among the invalid neighbors the driver
    tracks the smallest witness (fewest real lines) — mutating around
    a failure hunts the cheapest history that still exhibits it.

Durability rides the existing spine, nothing new: each round's base
batch and neighborhood batch check under their own ChunkJournals keyed
by ``store.spec_digest`` (the spec names the batch — no
materialize-to-fingerprint), and rounds advance through a
CampaignCheckpoint. A killed campaign resumed with ``resume=True``
re-dispatches ZERO decided histories or neighborhoods: finished rounds
rehydrate their saved summaries, the in-flight round's journals slice
decided rows out before encoding (the PR-5/PR-6 resume discipline).

``jepsen-tpu fuzz`` (cli.py) is the operator surface.
"""
from __future__ import annotations

import dataclasses
import json
import logging
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

log = logging.getLogger("jepsen.fuzz")


def _round_spec(spec, r: int):
    """Round r's base spec: the campaign seed stream is seed + r (the
    workloads.synth seed_stream discipline)."""
    return dataclasses.replace(spec, seed=spec.seed + r)


def fuzz_round(model, rspec, *, synth: str, neighborhood: int,
               max_witnesses: int, modes: Sequence[str],
               journal_dir: Optional[Path], resume: bool,
               verify: Optional[int] = None,
               check_kwargs: Optional[dict] = None) -> dict:
    """One generate → check → mutate → re-dispatch round. Returns the
    round summary; journals (when ``journal_dir`` is set) make it
    resumable mid-round with zero re-dispatched rows."""
    from . import telemetry
    with telemetry.span("fuzz.round", seed=int(rspec.seed),
                        histories=int(rspec.n)) as _sp:
        out = _fuzz_round_impl(
            model, rspec, synth=synth, neighborhood=neighborhood,
            max_witnesses=max_witnesses, modes=modes,
            journal_dir=journal_dir, resume=resume, verify=verify,
            check_kwargs=check_kwargs)
        _sp.set(invalid=out["invalid"],
                neighborhoods=out["neighborhoods"])
    reg = telemetry.REGISTRY
    reg.counter("fuzz.checked").inc(out["checked"])
    reg.counter("fuzz.invalid").inc(out["invalid"])
    reg.counter("fuzz.neighborhoods").inc(out["neighborhoods"])
    reg.counter("fuzz.disagreements").inc(out.get("disagreements", 0))
    return out


def _fuzz_round_impl(model, rspec, *, synth, neighborhood,
                     max_witnesses, modes, journal_dir, resume, verify,
                     check_kwargs):
    from .ops.linearize import check_synth, check_columnar
    from .ops.synth_device import synth_cas_neighbors
    from .store import ChunkJournal, spec_digest

    # Neighborhoods are PRNG perturbations of the device family's
    # stream: the legacy host stream's row r is an unrelated history,
    # so fuzzing "around" its witnesses would be meaningless.
    assert synth in ("device", "numpy"), \
        "fuzz runs on the device generator family (device|numpy)"
    kw = dict(check_kwargs or {})
    base_j = neigh_j = None
    if journal_dir is not None:
        base_j = ChunkJournal(
            journal_dir / f"fuzz-{rspec.seed}.base.jsonl",
            {"spec": spec_digest(rspec, synth=synth, stage="base")},
            resume=resume)
    try:
        valid, bad = check_synth(model, rspec, synth=synth,
                                 journal=base_j, **kw)
    finally:
        if base_j is not None:
            base_j.close()

    witnesses = np.flatnonzero(~np.asarray(valid))[:max_witnesses]
    neighbors = [(int(row), mode, var)
                 for row in witnesses.tolist()
                 for mode in modes
                 for var in range(neighborhood)]
    out = {
        "seed": int(rspec.seed),
        "checked": int(len(valid)),
        "invalid": int((~np.asarray(valid)).sum()),
        "witnesses": [int(w) for w in witnesses.tolist()],
        "neighborhoods": len(neighbors),
        "neighborhood_invalid": 0,
        "min_anomaly_lines": None,
        "verified": 0,
        "disagreements": 0,
    }
    if not neighbors:
        if base_j is not None:
            base_j.finish()       # round complete: nothing to mutate
        return out

    ncols, _meta = synth_cas_neighbors(rspec, neighbors, backend=synth)
    if journal_dir is not None:
        neigh_j = ChunkJournal(
            journal_dir / f"fuzz-{rspec.seed}.neigh.jsonl",
            {"spec": spec_digest(rspec, synth=synth, stage="neigh",
                                 neighborhood=neighborhood,
                                 modes=list(modes),
                                 witnesses=[int(w) for w in witnesses])},
            resume=resume)
    try:
        nvalid, nbad = check_columnar(model, ncols, journal=neigh_j,
                                      **kw)
    finally:
        if neigh_j is not None:
            neigh_j.close()
    nvalid = np.asarray(nvalid)
    inv_rows = np.flatnonzero(~nvalid)
    out["neighborhood_invalid"] = int(inv_rows.size)
    if inv_rows.size:
        from .history.columnar import PAD
        lines = (ncols.type[inv_rows] != PAD).sum(axis=1)
        wmin = int(inv_rows[int(lines.argmin())])
        out["min_anomaly_lines"] = int(lines.min())
        out["min_anomaly"] = {"neighbor": list(neighbors[wmin]),
                              "bad": int(np.asarray(nbad)[wmin])}
        by_mode: Dict[str, int] = {}
        for r in inv_rows.tolist():
            by_mode[neighbors[r][1]] = by_mode.get(neighbors[r][1], 0) + 1
        out["invalid_by_mode"] = by_mode

    if verify:
        # Oracle-fuzz at scale: a deterministic stride of the
        # neighborhood decodes back to Op lists and re-checks on the
        # exact host engine; any verdict flip is a CHECKER bug. Keyed
        # batches verify per key (linearizability is per register —
        # Herlihy–Wing locality, the same strain the device path
        # rides): the host verdict is the AND over the history's
        # per-key sub-histories.
        from .checkers.linearizable import wgl_check
        from .history.columnar import columnar_to_ops
        from .ops.partition import partition_columnar
        cache: dict = {}
        bad_rows = []
        sample = list(range(0, len(neighbors), int(verify)))
        pb = partition_columnar(ncols)
        if pb is not None:
            subs_of: Dict[int, List[int]] = {}
            for s, h in enumerate(pb.sub_history.tolist()):
                subs_of.setdefault(int(h), []).append(s)

            def host_valid(r):
                vs = [wgl_check(model, columnar_to_ops(pb.cols, s),
                                space_cache=cache)["valid"]
                      for s in subs_of.get(r, [])]
                if any(v is False for v in vs):
                    return False
                return True if all(v is True for v in vs) else None
        else:
            def host_valid(r):
                v = wgl_check(model, columnar_to_ops(ncols, r),
                              space_cache=cache)["valid"]
                return v if isinstance(v, bool) else None
        for r in sample:
            want = host_valid(r)
            if want is None:
                # The oracle punted ("unknown": config cap exhausted)
                # — no verdict to disagree with, and counting it would
                # raise a false checker-bug alarm.
                continue
            out["verified"] += 1
            if want != bool(nvalid[r]):
                bad_rows.append(
                    {"neighbor": list(neighbors[r]),
                     "host": want, "device": bool(nvalid[r])})
        out["disagreements"] = len(bad_rows)
        if bad_rows:
            out["disagreement_sample"] = bad_rows[:5]
            log.error("fuzz: %d device/host verdict disagreements "
                      "(checker bug) — first: %r", len(bad_rows),
                      bad_rows[0])
    # Journals only outlive an interrupted round.
    for j in (base_j, neigh_j):
        if j is not None:
            j.finish()
    return out


def fuzz_campaign(spec, *, rounds: int = 1, neighborhood: int = 4,
                  max_witnesses: int = 8,
                  modes: Optional[Sequence[str]] = None,
                  synth: str = "device", model=None,
                  store_root=None, name: str = "fuzz",
                  resume: bool = False, verify: Optional[int] = None,
                  check_kwargs: Optional[dict] = None) -> dict:
    """Drive ``rounds`` fuzz rounds, durably. Campaign state lives
    under ``store/<name>/`` — a CampaignCheckpoint over round ordinals
    (finished rounds rehydrate their ``fuzz-round-N.json`` summary; a
    killed campaign resumes the in-flight round from its chunk
    journals with zero re-dispatched rows) plus one summary JSON at
    the end. ``store_root=None`` with ``name=None`` runs ephemeral
    (no durability). Exit surface: ``disagreements`` > 0 means the
    checker itself is wrong somewhere — the one genuinely alarming
    outcome."""
    from .models.core import cas_register
    from .ops.synth_device import NEIGHBOR_MODES
    from .store import (CampaignCheckpoint, DEFAULT, atomic_write_json,
                        spec_digest)

    if modes:
        modes = tuple(modes)
    else:
        # The nemesis mode re-draws the fault stream and shifts the
        # crash window; a spec with NO fault surface (p_info == 0 and
        # p_crash == 0) never reads either, so its "neighbors" would
        # be bit-identical witness copies — drop the mode by default.
        modes = tuple(m for m in NEIGHBOR_MODES
                      if m != "nemesis"
                      or spec.p_info > 0 or spec.p_crash > 0)
    model = model if model is not None else cas_register()
    cdir = ckpt = None
    if name is not None:
        root = store_root if store_root is not None else DEFAULT
        cdir = Path(root.base) / name
        cdir.mkdir(parents=True, exist_ok=True)
        ckpt = CampaignCheckpoint(
            cdir / "campaign.jsonl",
            {"fuzz": name, "rounds": rounds,
             "spec": spec_digest(spec, synth=synth, modes=list(modes),
                                 neighborhood=neighborhood,
                                 max_witnesses=max_witnesses)},
            resume=resume)
    round_outs: List[dict] = []
    try:
        for r in range(rounds):
            state = ckpt.seed_state(r) if ckpt is not None else None
            if state is not None and state["done"]:
                try:
                    round_outs.append(json.loads(
                        (cdir / f"fuzz-round-{r}.json").read_text()))
                    continue
                except Exception:
                    log.warning("fuzz resume: round %d marked done but "
                                "its summary is unreadable; re-running",
                                r)
            if ckpt is not None:
                ckpt.started(r, cdir)
            out = fuzz_round(model, _round_spec(spec, r), synth=synth,
                             neighborhood=neighborhood,
                             max_witnesses=max_witnesses, modes=modes,
                             journal_dir=cdir,
                             resume=state is not None or resume,
                             verify=verify, check_kwargs=check_kwargs)
            out["round"] = r
            if cdir is not None:
                atomic_write_json(cdir / f"fuzz-round-{r}.json", out)
            if ckpt is not None:
                ckpt.done(r)
            round_outs.append(out)
        if ckpt is not None:
            ckpt.finish()
    finally:
        if ckpt is not None:
            ckpt.close()

    summary = {
        "name": name, "rounds": rounds, "synth": synth,
        "modes": list(modes),
        "checked": sum(o["checked"] for o in round_outs),
        "invalid": sum(o["invalid"] for o in round_outs),
        "neighborhoods": sum(o["neighborhoods"] for o in round_outs),
        "neighborhood_invalid": sum(o["neighborhood_invalid"]
                                    for o in round_outs),
        "verified": sum(o.get("verified", 0) for o in round_outs),
        "disagreements": sum(o.get("disagreements", 0)
                             for o in round_outs),
        "min_anomaly_lines": min(
            (o["min_anomaly_lines"] for o in round_outs
             if o.get("min_anomaly_lines") is not None), default=None),
        "round_results": round_outs,
    }
    if cdir is not None:
        atomic_write_json(cdir / "fuzz-summary.json", summary)
    return summary
