"""Durable metrics time-series: periodic registry frames per worker.

The telemetry registry (PR 8) answers "what has THIS process counted
so far"; cross-worker state was heartbeat-cadence JSON snapshots that
overwrite themselves — nothing could answer "what was the cluster's
shed rate over the last minute" after the fact. This module is the
durable time axis: every worker (fleet worker, ``serve`` worker,
``watch`` daemon, plain runs) appends periodic ``Registry.snapshot()``
frames to its own ring file under

    store/telemetry/<host>-<pid>.series.jsonl

one JSON line per frame::

    {"series": "JTSER1", "t": <wall s>, "host": ..., "pid": ...,
     "worker": <host>-<pid>, "corr": <correlation id or null>,
     "snap": {counters/gauges/histograms}}

Write discipline is the WAL's (history/wal.py): whole-line appends,
flush every frame, fsync group-committed (``JT_SERIES_FSYNC_MS``), so
a reader tolerates exactly one torn tail and a crash loses at most
one fsync window of frames. The file is a bounded ring: past
``JT_SERIES_MAX_BYTES`` the writer compacts in place (tmp + rename,
newest frames kept) — an always-on worker's series never grows
unboundedly, and the newest window (what every query below reads) is
what survives.

Readers: ``read_series`` (torn-tail tolerant, the ``tolerant=True``
discipline every log reader here shares), ``latest_frames`` (one
newest frame per worker file), ``merged_latest`` (the cluster view —
counters summed via ``telemetry.merge_counter_snapshots``, histograms
via ``merge_histogram_snapshots``' conservative-max percentiles,
numeric gauges summed), and the windowed queries the alert evaluator
(``telemetry.alerts``) and ``bench --compare`` build on:
``rate_over_window`` (counter delta / elapsed), ``gauge_last``, and
``histogram_window`` (merged summary over a window's frames).

Recording is on by default at a 5 s cadence (``JT_SERIES_INTERVAL_S``;
``JT_SERIES=0`` disables) — the ≤5% overhead gate in tier-1
(tests/test_obsplane.py) pins the cost of the append path itself.
"""
from __future__ import annotations

import json
import os
import socket
import time
from pathlib import Path
from typing import Dict, List, Optional

from . import telemetry

SERIES_MAGIC = "JTSER1"

#: The store-level namespace (store/telemetry/) — series ring files
#: plus the alert log live here; store.Store.tests() excludes it.
#: Kept literal in both modules (store.TELEMETRY_DIR is the same
#: string): store imports telemetry which imports this module — a
#: store import here would cycle. tests/test_obsplane pins the two
#: equal.
TELEMETRY_DIR = "telemetry"

SERIES_SUFFIX = ".series.jsonl"


def enabled() -> bool:
    """$JT_SERIES=0 disables periodic series recording (tests that
    count exact filesystem traffic, stores on read-only media)."""
    return os.environ.get("JT_SERIES", "1") != "0"


def interval_s() -> float:
    try:
        return max(0.0, float(os.environ.get("JT_SERIES_INTERVAL_S",
                                             "5")))
    except ValueError:
        return 5.0


def max_bytes() -> int:
    try:
        return max(1 << 16, int(os.environ.get("JT_SERIES_MAX_BYTES",
                                               str(4 << 20))))
    except ValueError:
        return 4 << 20


def fsync_ms() -> float:
    try:
        return float(os.environ.get("JT_SERIES_FSYNC_MS", "1000"))
    except ValueError:
        return 1000.0


def telemetry_dir(store_base) -> Path:
    return Path(store_base) / TELEMETRY_DIR


def worker_key(host: Optional[str] = None,
               pid: Optional[int] = None) -> str:
    host = host or socket.gethostname()
    pid = os.getpid() if pid is None else int(pid)
    safe = "".join(c if c.isalnum() or c in "-._" else "_"
                   for c in host) or "unknown-host"
    return f"{safe}-{pid}"


def series_path(store_base, host: Optional[str] = None,
                pid: Optional[int] = None) -> Path:
    """This worker's ring file — one file PER (host, pid), never a
    shared read-modify-write document (the router-rates / lease-file
    rationale: concurrent workers must not race each other's tails)."""
    return telemetry_dir(store_base) / (worker_key(host, pid)
                                        + SERIES_SUFFIX)


class SeriesWriter:
    """One worker's periodic frame appender.

    ``maybe_append()`` is the tick hook: free until ``interval_s`` has
    elapsed since the last frame (one monotonic read), then one
    snapshot + one whole-line append. ``append()`` forces a frame (run
    completion, daemon shutdown). The writer owns compaction: when the
    file passes ``max_bytes`` the newest frames are rewritten through
    a tmp + atomic rename — readers never see a torn ring."""

    def __init__(self, store_base, *,
                 interval: Optional[float] = None,
                 limit_bytes: Optional[int] = None,
                 source=None):
        self.path = series_path(store_base)
        self.interval = interval_s() if interval is None \
            else float(interval)
        self.limit = max_bytes() if limit_bytes is None \
            else int(limit_bytes)
        self.source = source or telemetry.snapshot
        self.frames_written = 0
        self.compactions = 0
        self._f = None
        self._last = -1e18           # monotonic s of the last frame
        self._last_sync = time.monotonic()

    # ------------------------------------------------------- writing
    def maybe_append(self, now: Optional[float] = None) -> bool:
        """Append a frame iff the cadence is due. Returns True when a
        frame landed — the cheap path is one monotonic read and a
        compare, which is what lets every tick loop call this
        unconditionally."""
        now = time.monotonic() if now is None else now
        if now - self._last < self.interval:
            return False
        return self.append(now=now)

    def append(self, now: Optional[float] = None) -> bool:
        """Append one frame unconditionally (still best-effort: series
        recording is diagnostics — an unwritable store must never fail
        the worker)."""
        self._last = time.monotonic() if now is None else now
        try:
            snap = self.source()
            frame = {"series": SERIES_MAGIC,
                     "t": round(time.time(), 6),
                     "host": socket.gethostname(), "pid": os.getpid(),
                     "worker": worker_key(),
                     "corr": telemetry.correlation(),
                     "snap": snap}
            line = json.dumps(frame, default=str) + "\n"
            if self._f is None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                self._f = open(self.path, "a")
            self._f.write(line)
            self._f.flush()
            self._maybe_fsync()
            self.frames_written += 1
            if self._f.tell() > self.limit:
                self._compact()
            return True
        except Exception:
            return False

    def _maybe_fsync(self) -> None:
        """Group-commit the frames (the WAL's discipline): fsync when
        the window elapsed, bounding both the fsync rate and the
        frames a crash can lose."""
        win = fsync_ms()
        nowm = time.monotonic()
        if win <= 0 or (nowm - self._last_sync) * 1000.0 >= win:
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._last_sync = nowm

    def _compact(self) -> None:
        """Ring bound: rewrite keeping the newest frames that fit half
        the budget, tmp + atomic rename (readers tolerate the swap the
        same way the WAL tailer tolerates rotation: a fresh full read
        of a SMALLER file)."""
        self._f.close()
        self._f = None
        frames = read_series(self.path)
        keep: List[str] = []
        budget = self.limit // 2
        total = 0
        for fr in reversed(frames):
            line = json.dumps(fr, default=str) + "\n"
            total += len(line)
            if total > budget and keep:
                break
            keep.append(line)
        tmp = self.path.with_name(self.path.name
                                  + f".compact.{os.getpid()}")
        with open(tmp, "w") as f:
            f.writelines(reversed(keep))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self.compactions += 1
        self._f = open(self.path, "a")

    def close(self, final_frame: bool = False) -> None:
        if final_frame:
            self.append()
        if self._f is not None:
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                pass
            try:
                self._f.close()
            except Exception:
                pass
            self._f = None


def append_frame(store_base) -> bool:
    """One-shot frame append (plain runs at completion) — a throwaway
    writer with no cadence state. Respects the enable switch."""
    if not enabled():
        return False
    w = SeriesWriter(store_base, interval=0)
    try:
        return w.append()
    finally:
        w.close()


# ------------------------------------------------------------ reading

def read_magic_jsonl(path, magic_key: str, magic: str) -> List[dict]:
    """The shared tolerant log reader (one copy of the discipline the
    WAL/journal/trace readers all follow): whole lines only — a torn
    final line (the writer's in-flight append or a kill mid-write) or
    any corrupt line ends the read at the last good prefix — and only
    records carrying ``magic_key == magic`` count (foreign files
    answer [], never raise). The series files and the alert log both
    read through here."""
    out: List[dict] = []
    try:
        with open(path, "rb") as f:
            for line in f:
                if not line.endswith(b"\n"):
                    break
                try:
                    fr = json.loads(line)
                except Exception:
                    break
                if isinstance(fr, dict) and fr.get(magic_key) == magic:
                    out.append(fr)
    except OSError:
        pass
    return out


def read_series(path) -> List[dict]:
    """All whole frames of one ring file, oldest first (torn-tail
    tolerant — ``read_magic_jsonl``)."""
    return read_magic_jsonl(path, "series", SERIES_MAGIC)


def series_files(store_base) -> List[Path]:
    d = telemetry_dir(store_base)
    if not d.exists():
        return []
    return sorted(d.glob(f"*{SERIES_SUFFIX}"))


def all_series(store_base) -> Dict[str, List[dict]]:
    """{worker_key: frames} for every ring file in the store."""
    out: Dict[str, List[dict]] = {}
    for p in series_files(store_base):
        frames = read_series(p)
        if frames:
            key = p.name[:-len(SERIES_SUFFIX)]
            out[key] = frames
    return out


def latest_frames(store_base) -> Dict[str, dict]:
    """The newest frame per worker — the cluster's last-known state
    (what ``merged_latest`` and the offline exposition fold)."""
    return {k: frames[-1]
            for k, frames in all_series(store_base).items()}


def merged_latest(store_base, *, max_age_s: float = 0.0,
                  exclude=()) -> dict:
    """Cluster-merged snapshot from every worker's newest frame:
    counters summed, histograms merged with conservative-max
    percentiles (``telemetry.merge_histogram_snapshots``), numeric
    gauges summed. ``max_age_s`` > 0 drops frames older than that — a
    long-dead worker's final counters should not haunt a live scrape
    forever (0 keeps everything: offline analysis wants the dead
    workers too). ``exclude`` drops named worker keys — the live
    ``/metrics?merged=1`` scrape excludes its OWN key before folding
    its live registry in, or the serving process would count twice
    (once from its durable frame, once live)."""
    now = time.time()
    snaps = []
    for key, fr in latest_frames(store_base).items():
        if key in exclude:
            continue
        if max_age_s > 0 and now - float(fr.get("t") or 0) > max_age_s:
            continue
        snaps.append(fr.get("snap") or {})
    out: dict = {}
    counters = telemetry.merge_counter_snapshots(snaps)
    if counters:
        out["counters"] = {k: counters[k] for k in sorted(counters)}
    gauges = telemetry.merge_gauge_snapshots(snaps)
    if gauges:
        out["gauges"] = {k: gauges[k] for k in sorted(gauges)}
    hists = telemetry.merge_histogram_snapshots(snaps)
    if hists:
        out["histograms"] = {k: hists[k] for k in sorted(hists)}
    return out


# ---------------------------------------------------- windowed queries

def _window(frames: List[dict], window_s: float,
            now: Optional[float] = None) -> List[dict]:
    now = time.time() if now is None else now
    lo = now - window_s
    return [fr for fr in frames if float(fr.get("t") or 0) >= lo]


def rate_over_window(frames: List[dict], counter: str,
                     window_s: float,
                     now: Optional[float] = None) -> Optional[float]:
    """Counter rate (units/s) over the trailing window of ONE worker's
    frames: (last - first) / elapsed across the window's frames. None
    when fewer than two frames land in the window (no rate is honest —
    0.0 would claim quiescence on one sample). A counter absent from a
    frame reads 0 (registries only materialize touched keys)."""
    win = _window(frames, window_s, now)
    if len(win) < 2:
        return None
    t0, t1 = float(win[0]["t"]), float(win[-1]["t"])
    if t1 <= t0:
        return None

    def val(fr):
        v = ((fr.get("snap") or {}).get("counters") or {}) \
            .get(counter, 0)
        return float(v) if isinstance(v, (int, float)) else 0.0

    return max(0.0, (val(win[-1]) - val(win[0])) / (t1 - t0))


def cluster_rate(store_base, counter: str, window_s: float,
                 now: Optional[float] = None) -> Optional[float]:
    """Sum of per-worker rates over the window — the cluster-wide rate
    the alert rules threshold on. None when NO worker had a computable
    rate (distinct from a true 0.0 across quiet workers)."""
    rates = [r for r in
             (rate_over_window(frames, counter, window_s, now)
              for frames in all_series(store_base).values())
             if r is not None]
    return sum(rates) if rates else None


def gauge_last(frames: List[dict], name: str):
    """The newest frame's value for a gauge (None when never set)."""
    for fr in reversed(frames):
        g = ((fr.get("snap") or {}).get("gauges") or {})
        if name in g:
            return g[name]
    return None


def histogram_window(frames: List[dict], name: str, window_s: float,
                     now: Optional[float] = None) -> Optional[dict]:
    """Merged histogram summary over the window's frames (same
    conservative-max percentile semantics as the cross-worker merge —
    the right direction for an SLO breach signal). None when the
    window holds no observations of ``name``."""
    win = _window(frames, window_s, now)
    merged = telemetry.merge_histogram_snapshots(
        [fr.get("snap") or {} for fr in win[-1:]])
    # Histograms are process-cumulative: the newest frame in the
    # window IS the window's distribution upper bound; merging every
    # frame would multiply-count. Cross-frame merge only applies
    # across WORKERS, which merged_latest owns.
    return merged.get(name)
