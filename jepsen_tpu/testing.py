"""Test scaffolding: no-op test maps and in-process fakes.

Mirrors jepsen/src/jepsen/tests.clj — `noop_test` is the base test map
every suite merges over (tests.clj:12-25), and the atom DB/client pair
implements a CAS register on an in-process variable so a complete
linearizability-checked test runs with zero SSH and zero real database
(tests.clj:27-56; exercised by core_test.clj:17-28). This is the seam the
TPU CI reuses: fake cluster → real histories → device checker.
"""
from __future__ import annotations

import threading
from typing import Any, Optional

from . import gen
from .checkers.core import always_valid
from .client import Client
from .db import NoopDB
from .os_ import NoopOS


def noop_test(**overrides) -> dict:
    """A test map with everything stubbed (tests.clj:12-25)."""
    test = {
        "name": "noop",
        "nodes": [],
        "concurrency": 1,
        "os": NoopOS(),
        "db": NoopDB(),
        "client": NoopClientForTest(),
        "nemesis": None,
        "generator": None,   # exhausts immediately
        "checker": always_valid(),
        "model": None,
    }
    test.update(overrides)
    return test


class NoopClientForTest(Client):
    def invoke(self, test, op):
        return {**op, "type": "ok"}


class AtomRegister:
    """The shared in-process register (the reference's `atom-db`,
    tests.clj:27-32): a value plus a lock giving atomic read/write/cas."""

    def __init__(self, value: Any = None):
        self.value = value
        self._lock = threading.Lock()

    def read(self):
        with self._lock:
            return self.value

    def write(self, v):
        with self._lock:
            self.value = v

    def cas(self, old, new) -> bool:
        with self._lock:
            if self.value == old:
                self.value = new
                return True
            return False

    def reset(self):
        with self._lock:
            self.value = None


class AtomClient(Client):
    """CAS-register client over an AtomRegister (tests.clj:34-56)."""

    def __init__(self, register: Optional[AtomRegister] = None):
        self.register = register if register is not None else AtomRegister()

    def setup(self, test, node):
        return AtomClient(self.register)

    def invoke(self, test, op):
        f = op["f"]
        if f == "read":
            return {**op, "type": "ok", "value": self.register.read()}
        if f == "write":
            self.register.write(op["value"])
            return {**op, "type": "ok"}
        if f == "cas":
            old, new = op["value"]
            ok = self.register.cas(old, new)
            return {**op, "type": "ok" if ok else "fail"}
        raise ValueError(f"unknown op {f!r}")


class FlakyAtomClient(AtomClient):
    """AtomClient that crashes (raises) on a fraction of ops — exercises
    the worker's indeterminate-process-retirement path
    (core_test.clj:86-101 worker-recovery-test)."""

    def __init__(self, register=None, crash_every: int = 7):
        super().__init__(register)
        self.crash_every = crash_every
        self._n = 0
        self._lock = threading.Lock()

    def setup(self, test, node):
        c = FlakyAtomClient(self.register, self.crash_every)
        c._lock = self._lock
        return c

    def invoke(self, test, op):
        with self._lock:
            self._n += 1
            n = self._n
        if n % self.crash_every == 0:
            # Apply the op *sometimes* before crashing: truly indeterminate.
            if n % (2 * self.crash_every) == 0 and op["f"] == "write":
                self.register.write(op["value"])
            raise RuntimeError("simulated client crash")
        return super().invoke(test, op)


def atom_cas_test(*, time_limit: Optional[float] = None, n_ops: int = 200,
                  concurrency: int = 5, seed: int = 0,
                  client: Optional[Client] = None, **overrides) -> dict:
    """A complete in-process CAS-register test (core_test.clj:17-28):
    atom client + seeded cas generator + linearizability checking."""
    from .checkers.linearizable import linearizable
    from .models.core import cas_register

    g = gen.limit(n_ops, gen.cas_gen())
    if time_limit is not None:
        g = gen.time_limit(time_limit, g)
    test = noop_test(
        name="atom-cas",
        concurrency=concurrency,
        seed=seed,
        client=client if client is not None else AtomClient(),
        generator=gen.clients(g),
        checker=linearizable(),
        model=cas_register(),
    )
    test.update(overrides)
    return test
