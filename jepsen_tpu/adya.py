"""Workloads/checkers for Adya's proscribed weak-consistency anomalies.

Mirrors jepsen/src/jepsen/adya.clj: the G2 anti-dependency-cycle test
emits, per unique key, a *pair* of concurrent inserts — each transaction
first reads both tables for the key (predicate read) and inserts only if
both are empty. Under serializability at most one of the pair can
commit; two commits for one key witness a G2 anomaly.
"""
from __future__ import annotations

import itertools
import threading

from . import gen as g
from . import independent
from .checkers.core import Checker
from .history.ops import OK


def g2_gen() -> g.Generator:
    """Pairs of :insert ops [a_id, None] / [None, b_id] per unique key,
    two threads per key (adya.clj:13-55)."""
    counter = itertools.count(1)
    lock = threading.Lock()

    def next_id() -> int:
        with lock:
            return next(counter)

    def fgen(k):
        # Each element must emit exactly one insert then retire (a bare
        # callable in a seq is polled until IT returns None, forever).
        return g.seq([
            g.once(lambda: {"type": "invoke", "f": "insert",
                            "value": [None, next_id()]}),
            g.once(lambda: {"type": "invoke", "f": "insert",
                            "value": [next_id(), None]}),
        ])

    return independent.concurrent_generator(2, itertools.count(1), fgen)


class G2Checker(Checker):
    """At most one insert may succeed per key (adya.clj:57-83).
    ``illegal-keys`` lists the witnessing keys themselves (not just the
    per-key counts), so host verdicts compare field-for-field against
    the device cycle checker's ``illegal-keys``
    (checkers.cycle.CycleChecker over ops.graph.graph_adya_g2)."""

    def check(self, test, model, history, opts=None) -> dict:
        keys: dict = {}
        for op in history:
            if op.f == "insert" and isinstance(op.value, independent.KV):
                k = op.value.key
                if op.type == OK:
                    keys[k] = keys.get(k, 0) + 1
                else:
                    keys.setdefault(k, 0)
        insert_count = sum(1 for c in keys.values() if c > 0)
        illegal = {k: c for k, c in sorted(keys.items()) if c > 1}
        return {
            "valid": not illegal,
            "key-count": len(keys),
            "legal-count": insert_count - len(illegal),
            "illegal-count": len(illegal),
            "illegal": illegal,
            "illegal-keys": sorted(illegal),
        }


def g2_checker() -> Checker:
    return G2Checker()


def g2_cycle_checker() -> Checker:
    """The device twin: G2 histories lowered to anti-dependency graphs
    (ops.graph.graph_adya_g2) and decided by batched transitive closure
    on the MXU — a doubly-inserted key is an rw 2-cycle, the canonical
    G2 anomaly. Result carries the same ``illegal-keys`` list as
    G2Checker plus the refined witness cycle."""
    from .checkers.cycle import CycleChecker
    return CycleChecker(family="adya-g2")
