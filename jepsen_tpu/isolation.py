"""Isolation-ladder certification: the txn family's batch certifier,
Checker adapters, and the live isolation monitor.

``certify_batch`` is the check_graphs_batch twin for transactional
histories: one call certifies a corpus at the highest isolation level
each history satisfies (ops.txn_graph), scheduled on the MXU through
the parameterized ops.schedule.GraphScheduler (same fault ladder,
watchdog, OOM bisection, poison-row quarantine), journaled through
store.ChunkJournal (``bad`` encodes LADDER.index(level)), quarantined
rows re-decided by the pure-host oracle twin ``check_txn_host``.
``JT_TXN_DEVICE=0`` is the restore switch: every history certifies on
the host oracle, the device path never dispatches.

``IncrementalIsolation`` is the online daemon's monitor: as ops
stream in it re-extracts the typed graph, feeds only the NEW edges
into per-plane incremental closures (ops.graph.IncrementalClosure
with the ladder masks — O(new edges) closure work per tick, never a
V^3 re-close) plus a derived-SI closure fed composed RW·N edges, and
reports the strongest level still holding. The verdict is monotone
non-increasing by construction (closures only gain edges; a
retraction — an append-chain reorder or a txn changing status —
rebuilds the closures but the reported level is floored at the worst
level already seen). doc/isolation.md documents the contract.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .checkers.core import Checker
from .ops.graph import DepGraph, IncrementalClosure
from .ops.txn_graph import (LADDER, N_CYC_PLANES, TXN_EDGE_TYPES,
                            TXN_LEVEL_TYPES, TXN_PLANES, check_txn_host,
                            encode_txn_graphs, extract_txn_graph,
                            iso_abbrev, ladder_verdict,
                            refine_txn_witness, txn_kernel, txn_op_model,
                            txn_result)

__all__ = ["certify_batch", "certify_host", "IsolationChecker",
           "HostIsolationChecker", "IncrementalIsolation", "iso_abbrev"]


def device_enabled() -> bool:
    """The JT_TXN_DEVICE restore switch (default on)."""
    return os.environ.get("JT_TXN_DEVICE", "1") != "0"


def _as_graphs(items) -> List[DepGraph]:
    return [g if isinstance(g, DepGraph) else extract_txn_graph(g)
            for g in items]


def _decide(g: DepGraph, cyc, provenance: str) -> dict:
    """One device row → ladder verdict + host-refined witness."""
    g1a = bool(g.meta.get("g1a_reads"))
    g1b = bool(g.meta.get("g1b_reads"))
    level, anomaly, plane = ladder_verdict(g1a, g1b, cyc)
    witness = refine_txn_witness(g, anomaly, plane)
    return txn_result(g, level, anomaly, witness, provenance)


def _rehydrate(g: DepGraph, valid, bad, prov) -> dict:
    """A journal-resumed verdict: bare (level only, no witness —
    the checkers.cycle resume contract)."""
    level = "serializability" if valid else LADDER[int(bad)]
    out = txn_result(g, level, None, None, prov)
    out["valid"] = bool(valid)      # journal is authoritative
    out["resumed"] = True
    return out


def _chunk_recorder(sch, journal):
    """on_chunk hook journaling ladder verdicts as chunks retire;
    ``bad`` holds LADDER.index(level). Quarantined rows journal only
    when the host oracle truly decides them."""

    def on_chunk(bucket, lo, hi, cyc, node):
        rows, vals, bads, provs = [], [], [], []
        for r in range(lo, hi):
            i = bucket.indices[r]
            if i in sch.quarantined:
                continue
            g = bucket.meta[i]
            level, _, _ = ladder_verdict(
                bool(g.meta.get("g1a_reads")),
                bool(g.meta.get("g1b_reads")), cyc[r - lo])
            valid = level == "serializability"
            rows.append(i)
            vals.append(valid)
            bads.append(None if valid else LADDER.index(level))
            provs.append(sch.row_provenance.get(i, "device"))
        if rows:
            journal.record(rows, vals, bads, provs)

    return on_chunk


def certify_host(items: Sequence) -> List[dict]:
    """Host-oracle certification for a batch (the JT_TXN_DEVICE=0
    path and the fleet's txn-host backend)."""
    return [check_txn_host(g) for g in _as_graphs(items)]


def certify_batch(items: Sequence, *, faults=None, journal=None,
                  scheduler_opts: Optional[dict] = None,
                  stats_out: Optional[dict] = None) -> List[dict]:
    """Certify a batch of transactional histories (or pre-extracted
    DepGraphs) at their highest satisfied isolation level; one result
    dict per input (ops.txn_graph.txn_result shape), rows tagged
    ``device`` / ``device-retried`` / ``host-fallback``."""
    from .ops.schedule import GraphScheduler
    graphs = _as_graphs(items)
    if not device_enabled():
        results = certify_host(graphs)
        if journal is not None:
            for i, r in enumerate(results):
                bad = (None if r["valid"]
                       else LADDER.index(r["level"]))
                journal.record([i], [r["valid"]], [bad], ["host"])
        return results
    results: List[Optional[dict]] = [None] * len(graphs)
    if journal is not None:
        for i, (valid, bad, prov) in journal.decided().items():
            if 0 <= i < len(graphs):
                results[i] = _rehydrate(graphs[i], valid, bad, prov)
    todo = [i for i, r in enumerate(results) if r is None]
    sch = GraphScheduler(faults=faults, family="txn", kernel=txn_kernel,
                         levels=N_CYC_PLANES, op_model=txn_op_model,
                         **(scheduler_opts or {}))
    buckets = encode_txn_graphs([graphs[i] for i in todo], indices=todo)
    for b in buckets:
        # The recorder needs each row's host G1 flags; GraphBucket
        # doesn't carry graphs, so hang a per-bucket index → graph map.
        b.meta = {i: graphs[i] for i in b.indices}
    if journal is not None:
        sch.on_chunk = _chunk_recorder(sch, journal)
    for bucket, (cyc, node) in sch.run(buckets):
        for r, i in enumerate(bucket.indices):
            if i in sch.quarantined:
                continue
            results[i] = _decide(graphs[i], cyc[r],
                                 sch.row_provenance.get(i, "device"))
    for i, reason in sch.quarantined.items():
        r = check_txn_host(graphs[i], provenance="host-fallback")
        r["quarantine_reason"] = reason
        results[i] = r
        if journal is not None:
            bad = None if r["valid"] else LADDER.index(r["level"])
            journal.record([i], [r["valid"]], [bad], ["host-fallback"])
    if stats_out is not None:
        stats_out.update(sch.stats)
    assert all(r is not None for r in results), \
        "every history must receive a verdict"
    return results


class IsolationChecker(Checker):
    """Checker-protocol adapter: one history rides a batch of one
    (real scale comes from certify_batch)."""

    def __init__(self, device: bool = True):
        self.device = device

    def check(self, test, model, history, opts=None) -> dict:
        g = extract_txn_graph(list(history))
        if not self.device or not device_enabled():
            return check_txn_host(g)
        return certify_batch([g])[0]


class HostIsolationChecker(IsolationChecker):
    """The pure-host oracle twin (DFS per plane + the A_SI relation;
    no device, no shared cycle machinery)."""

    def __init__(self):
        super().__init__(device=False)


# ----------------------------------------------------- live monitoring

class IncrementalIsolation:
    """Monotone live isolation verdict over a growing txn history.

    Each ``observe(new_ops)`` call appends to the buffered history,
    re-extracts the typed dependency graph (a linear host pass — the
    expensive O(V^3) closure is what stays incremental), diffs the
    edge set against what the closures already hold, and feeds ONLY
    the new edges: the 4 packed ladder planes ride one parameterized
    IncrementalClosure and the derived SI plane a second single-plane
    closure fed N edges plus composed RW·N edges (bookkeeping below).
    A retraction — an edge that disappeared because an append chain
    reordered or a txn changed status under info-visibility — resets
    and refeeds both closures (counted in ``stats["rebuilds"]``).

    ``level()`` is the strongest ladder level still holding. It is
    monotone non-increasing by construction: closures only gain
    edges between rebuilds, the G1 flags latch, and the reported
    level is floored at the worst level already reported (so even a
    rebuild can never raise it)."""

    def __init__(self):
        self._ops: List = []
        self._fed: Set[Tuple[str, int, int]] = set()
        self._planes = IncrementalClosure(level_types=TXN_LEVEL_TYPES,
                                          names=TXN_PLANES)
        self._si = IncrementalClosure(level_types=(("e",),),
                                      names=("G-SI",))
        self._rw_in: Dict[int, Set[int]] = {}
        self._n_out: Dict[int, Set[int]] = {}
        self._g1a = False
        self._g1b = False
        self._floor = len(LADDER) - 1          # best = serializability
        self._malformed = False
        self.stats = {"ops": 0, "ticks": 0, "edges": 0, "rebuilds": 0}

    # ------------------------------------------------------- plumbing
    def _feed(self, t: str, u: int, v: int) -> None:
        self.stats["edges"] += 1
        self._planes.add_edge(t, u, v)
        if t in ("rwi", "rwp"):
            self._rw_in.setdefault(v, set()).add(u)
            for w in sorted(self._n_out.get(v, ())):
                self._si.add_edge("e", u, w)
        else:
            self._n_out.setdefault(u, set()).add(v)
            self._si.add_edge("e", u, v)
            for p in sorted(self._rw_in.get(u, ())):
                self._si.add_edge("e", p, v)

    def _rebuild(self, edges: Set[Tuple[str, int, int]]) -> None:
        self.stats["rebuilds"] += 1
        self._planes = IncrementalClosure(level_types=TXN_LEVEL_TYPES,
                                          names=TXN_PLANES)
        self._si = IncrementalClosure(level_types=(("e",),),
                                      names=("G-SI",))
        self._rw_in, self._n_out = {}, {}
        for t, u, v in sorted(edges):
            self._feed(t, u, v)

    # -------------------------------------------------------- updates
    def observe(self, new_ops: Sequence) -> Optional[str]:
        """Fold newly-streamed ops in; returns level() (None when the
        buffered history is malformed → verdict unknown)."""
        self._ops.extend(new_ops)
        self.stats["ops"] += len(new_ops)
        self.stats["ticks"] += 1
        try:
            g = extract_txn_graph(self._ops)
        except ValueError:
            self._malformed = True
            return self.level()
        self._malformed = False
        edges = {(t, int(u), int(v)) for t in TXN_EDGE_TYPES
                 for u, v in g.edges.get(t, ())}
        if self._fed <= edges:
            for t, u, v in sorted(edges - self._fed):
                self._feed(t, u, v)
        else:
            self._rebuild(edges)
        self._fed = edges
        self._g1a = self._g1a or bool(g.meta.get("g1a_reads"))
        self._g1b = self._g1b or bool(g.meta.get("g1b_reads"))
        cyc = self._planes.cyclic_levels() + self._si.cyclic_levels()
        level, _, _ = ladder_verdict(self._g1a, self._g1b, cyc)
        self._floor = min(self._floor, LADDER.index(level))
        return self.level()

    # -------------------------------------------------------- verdict
    def level(self) -> Optional[str]:
        """The strongest ladder level still holding, or None while the
        buffered history is malformed (verdict unknown)."""
        if self._malformed:
            return None
        return LADDER[self._floor]

    def abbrev(self) -> str:
        return iso_abbrev(self.level())
