"""Auto-reconnecting client connection wrappers.

The runtime seam JDBC-style suite clients need
(jepsen/src/jepsen/reconnect.clj): a Wrapper owns one live connection
shared by many worker threads; ``with_conn`` hands the current
connection out under a read lock, and any exception inside the block
closes and reopens the connection (under the write lock) before
rethrowing — so the op that hit the fault still fails/infos, but the
next op gets a fresh connection instead of a poisoned one
(reconnect.clj:92-129).
"""
from __future__ import annotations

import logging
import threading
from contextlib import contextmanager
from typing import Any, Callable, Optional

log = logging.getLogger("jepsen.reconnect")


class RWLock:
    """A writer-preferring reader/writer lock (the reference uses a
    ReentrantReadWriteLock; many threads may hold a connection at once,
    open/close/reopen exclude them all)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    @contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextmanager
    def write(self):
        self.acquire_write()
        try:
            yield
        finally:
            self.release_write()


class Wrapper:
    """A stateful auto-reconnecting holder for one client connection
    (reconnect.clj:16-31)."""

    def __init__(self, open: Callable[[], Any],
                 close: Optional[Callable[[Any], None]] = None,
                 name: Optional[str] = None, log_reconnects: bool = True):
        assert callable(open)
        self._open = open
        self._close = close or (lambda conn: None)
        self.name = name
        self.log_reconnects = log_reconnects
        self._lock = RWLock()
        self._conn: Any = None

    def conn(self):
        """The active connection, if one exists."""
        return self._conn

    def open(self) -> "Wrapper":
        """Open a connection; no-op when already open
        (reconnect.clj:54-63)."""
        with self._lock.write():
            if self._conn is None:
                conn = self._open()
                if conn is None:
                    raise RuntimeError(
                        f"open() returned None for wrapper {self.name}")
                self._conn = conn
        return self

    def close(self) -> "Wrapper":
        """Close the connection, if open (reconnect.clj:65-72)."""
        with self._lock.write():
            if self._conn is not None:
                try:
                    self._close(self._conn)
                finally:
                    self._conn = None
        return self

    def reopen(self) -> "Wrapper":
        """Close and open atomically — one reconnect even when many
        threads hit the same fault (reconnect.clj:74-90)."""
        with self._lock.write():
            if self.log_reconnects:
                log.info("reconnecting %s", self.name or "client")
            if self._conn is not None:
                try:
                    self._close(self._conn)
                except Exception:
                    pass
                self._conn = None
            conn = self._open()
            if conn is None:
                raise RuntimeError(
                    f"open() returned None for wrapper {self.name}")
            self._conn = conn
        return self

    @contextmanager
    def with_conn(self):
        """Yield the current connection under the read lock; on ANY
        exception, close + reopen the connection and rethrow
        (reconnect.clj:92-129). Callers still see the failure — the op
        maps to fail/info as usual — but the next op gets a live
        connection."""
        self._lock.acquire_read()
        conn = self._conn
        # A concurrent failed reopen can null _conn between our open()
        # and re-acquiring the read lock; retry rather than yield None.
        attempts = 0
        while conn is None:
            self._lock.release_read()
            attempts += 1
            if attempts > 3:
                raise ConnectionError(
                    f"could not obtain a connection for {self.name}")
            self.open()            # raises when the DB stays down
            self._lock.acquire_read()
            conn = self._conn
        held = True
        try:
            try:
                yield conn
            except BaseException as e:
                self._lock.release_read()
                held = False
                if isinstance(e, Exception):
                    self._reopen_after_error(conn)
                raise
        finally:
            if held:
                self._lock.release_read()

    def _reopen_after_error(self, conn) -> None:
        """Close + reopen after a failure on ``conn`` — but only if it
        is still the current connection (another thread may have
        reconnected already). A failed reopen leaves the wrapper closed
        so the next with_conn attempts a fresh open."""
        with self._lock.write():
            if self._conn is not conn:
                return
            if self.log_reconnects:
                log.info("reconnecting %s after error",
                         self.name or "client")
            try:
                self._close(conn)
            except Exception:
                pass
            self._conn = None
            try:
                self._conn = self._open()
            except Exception:
                log.warning("reconnect of %s failed", self.name,
                            exc_info=True)


def wrapper(open: Callable[[], Any], close=None, name=None,
            log_reconnects: bool = True) -> Wrapper:
    return Wrapper(open, close, name, log_reconnects)
