/* bump-time: jump the system wall clock by a signed millisecond delta.
 *
 * Usage: bump-time DELTA_MS
 *
 * Compiled on the db node by the harness (jepsen_tpu.nemesis.time) and
 * invoked by the clock nemesis; functional counterpart of the
 * reference's resources/bump-time.c. Uses clock_gettime/clock_settime
 * on CLOCK_REALTIME and normalizes nanosecond carry.
 */
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

int main(int argc, char **argv) {
  long long delta_ms;
  struct timespec ts;
  char *end;

  if (argc != 2) {
    fprintf(stderr, "usage: %s DELTA_MS\n", argv[0]);
    return 2;
  }
  delta_ms = strtoll(argv[1], &end, 10);
  if (*end != '\0') {
    fprintf(stderr, "bad delta: %s\n", argv[1]);
    return 2;
  }

  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) {
    perror("clock_gettime");
    return 1;
  }

  ts.tv_sec += delta_ms / 1000;
  ts.tv_nsec += (delta_ms % 1000) * 1000000LL;
  while (ts.tv_nsec >= 1000000000L) {
    ts.tv_nsec -= 1000000000L;
    ts.tv_sec += 1;
  }
  while (ts.tv_nsec < 0) {
    ts.tv_nsec += 1000000000L;
    ts.tv_sec -= 1;
  }

  if (clock_settime(CLOCK_REALTIME, &ts) != 0) {
    perror("clock_settime");
    return 1;
  }
  return 0;
}
