/* strobe-time: flip the wall clock between its true value and a skewed
 * value every PERIOD_MS, for DURATION_S seconds.
 *
 * Usage: strobe-time DELTA_MS PERIOD_MS DURATION_S
 *
 * The true time is tracked against CLOCK_MONOTONIC so repeated
 * settime calls don't accumulate drift: at each flip we recompute what
 * the wall clock *should* read from the monotonic anchor, then set it
 * either to that or to that plus DELTA_MS. Functional counterpart of
 * the reference's resources/strobe-time.c.
 */
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <unistd.h>

static long long ts_to_ns(const struct timespec *ts) {
  return (long long)ts->tv_sec * 1000000000LL + ts->tv_nsec;
}

static struct timespec ns_to_ts(long long ns) {
  struct timespec ts;
  ts.tv_sec = ns / 1000000000LL;
  ts.tv_nsec = ns % 1000000000LL;
  if (ts.tv_nsec < 0) {
    ts.tv_nsec += 1000000000LL;
    ts.tv_sec -= 1;
  }
  return ts;
}

int main(int argc, char **argv) {
  long long delta_ms, period_ms, duration_s;
  struct timespec mono0, real0, mono, set;
  long long anchor;  /* real0 - mono0, in ns */
  long long deadline_ns, now_mono_ns;
  int skewed = 0;

  if (argc != 4) {
    fprintf(stderr, "usage: %s DELTA_MS PERIOD_MS DURATION_S\n", argv[0]);
    return 2;
  }
  delta_ms = atoll(argv[1]);
  period_ms = atoll(argv[2]);
  duration_s = atoll(argv[3]);
  if (period_ms <= 0 || duration_s < 0) {
    fprintf(stderr, "period must be positive; duration non-negative\n");
    return 2;
  }

  if (clock_gettime(CLOCK_MONOTONIC, &mono0) != 0 ||
      clock_gettime(CLOCK_REALTIME, &real0) != 0) {
    perror("clock_gettime");
    return 1;
  }
  anchor = ts_to_ns(&real0) - ts_to_ns(&mono0);
  deadline_ns = ts_to_ns(&mono0) + duration_s * 1000000000LL;

  for (;;) {
    if (clock_gettime(CLOCK_MONOTONIC, &mono) != 0) {
      perror("clock_gettime");
      return 1;
    }
    now_mono_ns = ts_to_ns(&mono);
    if (now_mono_ns >= deadline_ns)
      break;

    skewed = !skewed;
    set = ns_to_ts(anchor + now_mono_ns +
                   (skewed ? delta_ms * 1000000LL : 0));
    if (clock_settime(CLOCK_REALTIME, &set) != 0) {
      perror("clock_settime");
      return 1;
    }
    usleep((useconds_t)(period_ms * 1000));
  }

  /* Restore the true time on the way out. */
  if (clock_gettime(CLOCK_MONOTONIC, &mono) == 0) {
    set = ns_to_ts(anchor + ts_to_ns(&mono));
    clock_settime(CLOCK_REALTIME, &set);
  }
  return 0;
}
