/* strobe-time-experiment: measure how fast and how precisely this
 * node's wall clock can actually be strobed.
 *
 * The production tool (strobe-time.c) flips the wall clock between
 * normal and +delta offsets on a fixed cadence and trusts the kernel
 * to keep up. This experimental variant (the analog of the reference's
 * jepsen/resources/strobe-time-experiment.c, 205 LoC) instruments the
 * same loop: it records, per flip, how far the achieved flip time
 * drifted from the ideal cadence, and reports flip count plus
 * min/mean/max inter-flip latency in nanoseconds. Use it to calibrate
 * a believable --period for strobe-time on a given box before leaning
 * on sub-millisecond skew schedules.
 *
 * Like the sibling tools this is a genuine rewrite on clock_gettime /
 * clock_settime (the reference pair uses gettimeofday math): flips are
 * anchored to CLOCK_MONOTONIC so wall-clock jumps the tool itself
 * makes never distort its own schedule.
 *
 * usage: strobe-time-experiment <delta-ms> <period-ms> <duration-s>
 * output: "<flips> <min-ns> <mean-ns> <max-ns>"
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

static const int64_t NS = 1000000000LL;

static int64_t now_ns(clockid_t clk) {
  struct timespec t;
  if (clock_gettime(clk, &t) != 0) {
    perror("clock_gettime");
    exit(2);
  }
  return (int64_t)t.tv_sec * NS + t.tv_nsec;
}

static void set_wall_ns(int64_t ns) {
  struct timespec t;
  t.tv_sec = ns / NS;
  t.tv_nsec = ns % NS;
  if (t.tv_nsec < 0) {               /* keep tv_nsec in [0, NS) */
    t.tv_nsec += NS;
    t.tv_sec -= 1;
  }
  if (clock_settime(CLOCK_REALTIME, &t) != 0) {
    perror("clock_settime");
    exit(3);
  }
}

static void sleep_until_mono(int64_t target) {
  struct timespec t;
  t.tv_sec = target / NS;
  t.tv_nsec = target % NS;
  while (clock_nanosleep(CLOCK_MONOTONIC, TIMER_ABSTIME, &t, NULL) != 0)
    ;                                /* retry on EINTR */
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr,
            "usage: %s <delta-ms> <period-ms> <duration-s>\n"
            "Strobes the wall clock like strobe-time, but reports the\n"
            "achieved flip count and min/mean/max inter-flip latency\n"
            "(ns) instead of trusting the requested cadence.\n",
            argv[0]);
    return 2;
  }
  int64_t delta_ns = (int64_t)(atof(argv[1]) * 1e6);
  int64_t period_ns = (int64_t)(atof(argv[2]) * 1e6);
  int64_t duration_ns = (int64_t)(atof(argv[3]) * 1e9);
  if (period_ns <= 0 || duration_ns <= 0) {
    fprintf(stderr, "period and duration must be positive\n");
    return 2;
  }

  /* Wall = mono + offset; flip the offset, never the measured base. */
  int64_t normal_off = now_ns(CLOCK_REALTIME) - now_ns(CLOCK_MONOTONIC);
  int64_t start = now_ns(CLOCK_MONOTONIC);
  int64_t end = start + duration_ns;

  int64_t flips = 0, weird = 0;
  int64_t lat_min = INT64_MAX, lat_max = 0, lat_sum = 0, last = start;

  for (int64_t next = start; next < end; next += period_ns) {
    sleep_until_mono(next);
    int64_t mono = now_ns(CLOCK_MONOTONIC);
    weird = !weird;
    set_wall_ns(mono + normal_off + (weird ? delta_ns : 0));
    if (flips > 0) {
      int64_t lat = mono - last;
      if (lat < lat_min) lat_min = lat;
      if (lat > lat_max) lat_max = lat;
      lat_sum += lat;
    }
    last = mono;
    flips++;
  }

  /* Restore the normal offset before reporting. */
  set_wall_ns(now_ns(CLOCK_MONOTONIC) + normal_off);
  printf("%lld %lld %lld %lld\n", (long long)flips,
         (long long)(flips > 1 ? lat_min : 0),
         (long long)(flips > 1 ? lat_sum / (flips - 1) : 0),
         (long long)lat_max);
  return 0;
}
