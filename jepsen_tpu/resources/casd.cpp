// casd — a minimal CAS key-value server speaking the etcd v2 keys API
// subset the etcd suite's client uses (GET/PUT/DELETE on /v2/keys/<key>,
// conditional PUT via prevValue). It is the in-CI stand-in for a real
// etcd node: a genuine compiled binary that the framework installs via
// its own tarball deploy, starts with start-stop-daemon + pidfile,
// pauses with SIGSTOP, and kills — so the control plane, daemon
// helpers, and nemesis paths are exercised against real processes in
// environments with no cluster and no network egress.
//
// Semantics knob for fault-detection tests: state is in-memory by
// default, so kill+restart wipes the register and the linearizability
// checker must flag post-restart reads (a real consistency violation a
// real single-node etcd would not exhibit with its WAL). With
// --persist FILE, writes go through an fsync'd log replayed on boot,
// and restarts are harmless — valid histories stay valid.
//
// Besides the KV register it serves the other coordination primitives
// the checker families need real processes for (the role hazelcast /
// aerospike / rabbitmq servers play in the reference suites):
//   POST /lock/<name>     op=acquire|release&owner=O   (mutex)
//   POST /ids/next                                     (unique ids)
//   POST /counter/<name>  delta=N   GET /counter/<name>
//   POST /queue/<name>    op=enq&v=X | op=deq | op=drain
//   POST /set/<name>      op=add&v=X   GET /set/<name>
// All share the same in-memory-unless-persisted semantics, so the one
// kill+restart nemesis seeds a REAL violation in every family: wiped
// locks double-grant, a reset id counter duplicates, wiped queues/sets
// lose elements, a wiped counter under-reads.
//
// Deterministic fault seeding: --wipe-after-ops N drops ALL in-memory
// state the instant the Nth APPLIED state change lands (still under
// the state lock, deferred past N until there is state to lose, and
// counted across restarts via casd-wipe.state) — exactly the data
// loss a kill -9 + restart of a non-persistent node causes, but at a
// point fixed by the workload's own progress instead of a wall-clock
// race between nemesis cadence and workload phase. Fault-detection
// tests use it so their seeded violations are deterministic under any
// scheduler load; the kill/pause nemeses still exercise the
// process-control paths on top.
//
// Usage: casd --port P [--persist FILE] [--delay-ms N]
//             [--wipe-after-ops N] [--resp-port P]
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fstream>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

std::mutex g_mu;
std::map<std::string, std::string> g_store;
std::map<std::string, std::string> g_locks;               // name -> owner
std::map<std::string, long> g_counters;
std::map<std::string, std::deque<std::string>> g_queues;
std::map<std::string, std::set<std::string>> g_sets;
std::map<std::string, std::map<long, long>> g_banks;      // name -> acct->bal
long g_next_id = 0;
long g_next_ts = 0;                 // monotonic timestamp oracle
// --ts-wall: /ts/next returns wall-clock-derived timestamps instead of
// the counter — a naive clock-trusting oracle (the seam cockroach's
// hybrid-logical clock papers over). /ctl/clock skews this process's
// notion of wall time by an offset, the local-mode analog of the
// bump-time/strobe-time C tools run against a node's real clock
// (jepsen/resources/bump-time.c; cockroach nemesis.clj:233-255): a
// negative bump makes later grants regress below earlier ones, which
// the monotonic checker must catch.
bool g_ts_wall = false;
long g_clock_offset_ms = 0;
long g_ts_seq = 0;                  // sub-ms disambiguator
std::map<std::string, std::string> g_kv;       // consul-style KV
std::map<std::string, long> g_kv_index;        // per-key ModifyIndex
long g_kv_counter = 0;
// >0: transfers release the store lock between debit and credit for
// this many ms — a deliberately seedable read-skew/lost-total race the
// bank checker must catch (the violation cockroach's bank test hunts,
// cockroachdb/src/jepsen/cockroach/bank.clj:112-143).
int g_bank_split_ms = 0;
std::map<std::string, std::vector<long>> g_dirty;  // name -> row values
// >0: dirty-table writes release the lock between rows (see
// handle_dirty — the seeded dirty-read/inconsistent-read bug).
int g_dirty_split_ms = 0;
long g_index = 0;
std::string g_persist_path;
int g_delay_ms = 0;
// --wipe-after-ops: deterministic seeded data loss (see file header).
long g_wipe_after_ops = 0;
std::atomic<long> g_mutations_seen{0};
std::atomic<bool> g_wiped{false};
// Bumped by every wipe so a writer sleeping with the lock released
// (split-ms seeded races) can tell its world changed and die like a
// crashed writer instead of resurrecting pre-wipe state.
std::atomic<long> g_wipe_epoch{0};

// The mutation count must survive kill+restart (the nemesis restarts
// this process with the same argv and cwd): a fresh counter would let
// a fast kill cadence keep every lifetime under N and silently turn
// the deterministic wipe back into a timing race. Not the WAL — this
// is harness bookkeeping, not replayable state.
const char* WIPE_STATE_FILE = "casd-wipe.state";

void save_wipe_state() {
  std::ofstream f(WIPE_STATE_FILE, std::ios::trunc);
  f << g_mutations_seen.load() << " " << (g_wiped.load() ? 1 : 0) << "\n";
  f.flush();
}

void load_wipe_state() {
  std::ifstream f(WIPE_STATE_FILE);
  long seen = 0;
  int wiped = 0;
  if (f >> seen >> wiped) {
    g_mutations_seen = seen;
    g_wiped = wiped != 0;
  }
}

// Drop every piece of in-memory state a kill -9 of a non-persistent
// node would lose. The WAL file (if any) is untouched — this is a
// memory wipe, not a disk wipe; the clock offset survives because it
// models the NODE's clock, not process state.
void wipe_all_state() {
  g_store.clear();
  g_locks.clear();
  g_counters.clear();
  g_queues.clear();
  g_sets.clear();
  g_banks.clear();
  g_dirty.clear();
  g_kv.clear();
  g_kv_index.clear();
  g_next_id = 0;
  g_next_ts = 0;
  g_ts_seq = 0;
  g_kv_counter = 0;
  g_index = 0;
  ++g_wipe_epoch;
}

// Is there any acknowledged state a wipe would actually lose? The
// deterministic wipe defers until this holds, so a family whose state
// happens to be empty at change N (a fully-drained queue, say) still
// gets a guaranteed loss at the next state-creating change.
bool state_to_lose() {
  if (!g_store.empty() || !g_locks.empty() || !g_sets.empty() ||
      !g_banks.empty() || !g_dirty.empty() || !g_kv.empty() ||
      g_next_id > 0 || g_next_ts > 0 || g_ts_seq > 0)
    return true;
  for (const auto& q : g_queues)
    if (!q.second.empty()) return true;
  for (const auto& c : g_counters)
    if (c.second != 0) return true;
  return false;
}

// One state change just applied (plog's caller holds the state lock):
// advance the deterministic-wipe counter and fire the wipe — still
// under the lock, so nothing can observe the pre-wipe state between
// change N and the loss — once the count crosses N and there is state
// to lose.
void note_state_change() {
  if (g_wipe_after_ops <= 0 || g_wiped.load()) return;
  long n = ++g_mutations_seen;
  if (n >= g_wipe_after_ops && state_to_lose() &&
      !g_wiped.exchange(true))
    wipe_all_state();
  save_wipe_state();
}

const char* B64 =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

std::string b64_encode(const std::string& in) {
  std::string out;
  int val = 0, bits = -6;
  for (unsigned char c : in) {
    val = (val << 8) + c;
    bits += 8;
    while (bits >= 0) {
      out += B64[(val >> bits) & 0x3F];
      bits -= 6;
    }
  }
  if (bits > -6) out += B64[((val << 8) >> (bits + 8)) & 0x3F];
  while (out.size() % 4) out += '=';
  return out;
}

std::string b64_decode(const std::string& in) {
  static int rev[256];
  static bool init = false;
  if (!init) {
    for (int i = 0; i < 256; ++i) rev[i] = -1;
    for (int i = 0; i < 64; ++i) rev[(unsigned char)B64[i]] = i;
    init = true;
  }
  std::string out;
  int val = 0, bits = -8;
  for (unsigned char c : in) {
    if (rev[c] == -1) break;
    val = (val << 6) + rev[c];
    bits += 6;
    if (bits >= 0) {
      out += (char)((val >> bits) & 0xFF);
      bits -= 8;
    }
  }
  return out;
}

// Append one replayable record. Codes: S/D kv set/delete, L/U lock
// acquire/release, I id grant, Z timestamp grant, K/X consul-kv
// set(b64)/delete, C counter add, Q/R queue enq/deq, E set add,
// B bank init, T in-bank transfer, M cross-bank transfer,
// Y dirty-table init, W completed dirty-table write.
void plog(char code, const std::string& a, const std::string& b) {
  if (!g_persist_path.empty()) {
    std::ofstream f(g_persist_path, std::ios::app);
    f << code << " " << a << " " << b << "\n";
    f.flush();
  }
  // plog marks exactly the applied-state-change points, always under
  // the state lock — the deterministic-wipe counter lives here.
  note_state_change();
}

void persist(const std::string& key, const std::string& value, bool del) {
  plog(del ? 'D' : 'S', key, value);
}

void replay() {
  if (g_persist_path.empty()) return;
  std::ifstream f(g_persist_path);
  std::string op, key, value;
  while (f >> op >> key) {
    std::getline(f, value);
    if (!value.empty() && value[0] == ' ') value.erase(0, 1);
    if (op == "S") {
      g_store[key] = value;
    } else if (op == "D") {
      g_store.erase(key);
    } else if (op == "L") {
      g_locks[key] = value;
    } else if (op == "U") {
      g_locks.erase(key);
    } else if (op == "I") {
      ++g_next_id;
    } else if (op == "Z") {
      ++g_next_ts;
    } else if (op == "K") {          // consul kv set, value b64
      g_kv[key] = b64_decode(value);
      g_kv_index[key] = ++g_kv_counter;
    } else if (op == "X") {          // consul kv delete
      g_kv.erase(key);
      g_kv_index.erase(key);
    } else if (op == "C") {
      g_counters[key] += atol(value.c_str());
    } else if (op == "Q") {
      g_queues[key].push_back(value);
    } else if (op == "R") {
      auto& q = g_queues[key];
      auto it = std::find(q.begin(), q.end(), value);
      if (it != q.end()) q.erase(it);
    } else if (op == "E") {
      g_sets[key].insert(value);
    } else if (op == "B") {            // bank init "n_accounts:balance"
      auto c = value.find(':');
      long n = atol(value.c_str());
      long bal = atol(value.c_str() + c + 1);
      for (long a = 0; a < n; ++a) g_banks[key][a] = bal;
    } else if (op == "T") {            // transfer "from:to:amount"
      auto c1 = value.find(':');
      auto c2 = value.find(':', c1 + 1);
      long from = atol(value.c_str());
      long to = atol(value.c_str() + c1 + 1);
      long amount = atol(value.c_str() + c2 + 1);
      g_banks[key][from] -= amount;
      g_banks[key][to] += amount;
    } else if (op == "M") {            // xtransfer; key=from, "to:amount"
      auto c1 = value.find(':');
      std::string tob = value.substr(0, c1);
      long amount = atol(value.c_str() + c1 + 1);
      g_banks[key][0] -= amount;
      g_banks[tob][0] += amount;
    } else if (op == "Y") {            // dirty-table init, rows
      auto& t = g_dirty[key];
      if (t.empty()) t.assign((size_t)atol(value.c_str()), -1);
    } else if (op == "W") {            // dirty-table completed write
      auto it = g_dirty.find(key);
      if (it != g_dirty.end())
        for (auto& row : it->second) row = atol(value.c_str());
    }
    ++g_index;
  }
}

// --------------------------------------------------------- tiny HTTP

struct Request {
  std::string method, path, body;
  std::map<std::string, std::string> form;  // urlencoded body/query
};

std::string url_decode(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '%' && i + 2 < s.size()) {
      out += static_cast<char>(std::stoi(s.substr(i + 1, 2), nullptr, 16));
      i += 2;
    } else if (s[i] == '+') {
      out += ' ';
    } else {
      out += s[i];
    }
  }
  return out;
}

void parse_form(const std::string& s, std::map<std::string, std::string>* out) {
  std::istringstream is(s);
  std::string pair;
  while (std::getline(is, pair, '&')) {
    auto eq = pair.find('=');
    if (eq != std::string::npos)
      (*out)[url_decode(pair.substr(0, eq))] = url_decode(pair.substr(eq + 1));
  }
}

bool read_request(int fd, Request* req) {
  std::string buf;
  char chunk[4096];
  size_t header_end = std::string::npos;
  while (header_end == std::string::npos) {
    ssize_t n = read(fd, chunk, sizeof(chunk));
    if (n <= 0) return false;
    buf.append(chunk, n);
    header_end = buf.find("\r\n\r\n");
    if (buf.size() > 1 << 20) return false;
  }
  std::istringstream head(buf.substr(0, header_end));
  std::string version;
  head >> req->method >> req->path >> version;
  size_t content_length = 0;
  std::string line;
  std::getline(head, line);
  while (std::getline(head, line)) {
    if (strncasecmp(line.c_str(), "content-length:", 15) == 0)
      content_length = std::stoul(line.substr(15));
  }
  req->body = buf.substr(header_end + 4);
  while (req->body.size() < content_length) {
    ssize_t n = read(fd, chunk, sizeof(chunk));
    if (n <= 0) return false;
    req->body.append(chunk, n);
  }
  auto q = req->path.find('?');
  if (q != std::string::npos) {
    parse_form(req->path.substr(q + 1), &req->form);
    req->path.resize(q);
  }
  parse_form(req->body, &req->form);
  return true;
}

void respond(int fd, int status, const std::string& json) {
  const char* reason = status == 200 ? "OK"
                       : status == 201 ? "Created"
                       : status == 404 ? "Not Found"
                       : status == 412 ? "Precondition Failed"
                                       : "Bad Request";
  std::ostringstream os;
  os << "HTTP/1.1 " << status << " " << reason << "\r\n"
     << "Content-Type: application/json\r\n"
     << "Content-Length: " << json.size() << "\r\n"
     << "Connection: close\r\n\r\n"
     << json;
  std::string s = os.str();
  size_t off = 0;
  while (off < s.size()) {
    ssize_t n = write(fd, s.data() + off, s.size() - off);
    if (n <= 0) break;
    off += n;
  }
}

std::string node_json(const std::string& key, const std::string& value,
                      long index) {
  std::ostringstream os;
  os << "{\"key\":\"/" << key << "\",\"value\":\"" << value
     << "\",\"modifiedIndex\":" << index << "}";
  return os.str();
}

std::string json_list(const std::vector<std::string>& vs) {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < vs.size(); ++i)
    os << (i ? "," : "") << "\"" << vs[i] << "\"";
  os << "]";
  return os.str();
}

bool starts_with(const std::string& s, const std::string& p,
                 std::string* rest) {
  if (s.compare(0, p.size(), p) != 0) return false;
  *rest = s.substr(p.size());
  return true;
}

// The coordination services. Caller holds g_mu.
void handle_service(int fd, Request& req) {
  std::string name;
  if (req.path == "/ids/next") {
    long id = g_next_id++;
    plog('I', "-", "-");
    respond(fd, 200, "{\"id\":" + std::to_string(id) + "}");
  } else if (req.path == "/ts/next") {
    long ts;
    if (g_ts_wall) {
      auto now = std::chrono::duration_cast<std::chrono::milliseconds>(
                     std::chrono::system_clock::now().time_since_epoch())
                     .count();
      // Trusts the (possibly skewed) clock: no max() against the
      // previous grant — that trust is what the monotonic test probes.
      // seq is unbounded (not mod-wrapped): under a steady clock ts
      // stays strictly increasing even past 1000 grants/ms; a skew
      // bump (>=100ms = 1e5 ticks) still dwarfs seq drift.
      ts = (now + g_clock_offset_ms) * 1000 + g_ts_seq++;
    } else {
      ts = g_next_ts++;
      plog('Z', "-", "-");
    }
    respond(fd, 200, "{\"ts\":" + std::to_string(ts) + "}");
  } else if (req.path == "/ctl/clock") {
    // Skew this daemon's wall clock by delta_ms (cumulative); absolute
    // reset via set_ms. Admin seam for the local clock nemesis.
    if (req.form.count("set_ms"))
      g_clock_offset_ms = atol(req.form["set_ms"].c_str());
    else
      g_clock_offset_ms += atol(req.form["delta_ms"].c_str());
    respond(fd, 200,
            "{\"offset_ms\":" + std::to_string(g_clock_offset_ms) + "}");
  } else if (starts_with(req.path, "/v1/kv/", &name)) {
    // consul KV subset: base64 values, index-based check-and-set.
    auto it = g_kv.find(name);
    if (req.method == "GET") {
      if (it == g_kv.end()) {
        respond(fd, 404, "[]");
      } else {
        long idx = g_kv_index[name];
        respond(fd, 200,
                "[{\"CreateIndex\":" + std::to_string(idx) +
                    ",\"ModifyIndex\":" + std::to_string(idx) +
                    ",\"Key\":\"" + name + "\",\"Flags\":0,\"Value\":\"" +
                    b64_encode(it->second) + "\"}]");
      }
    } else if (req.method == "PUT") {
      auto cas = req.form.find("cas");
      if (cas != req.form.end()) {
        long want = atol(cas->second.c_str());
        long have = it == g_kv.end() ? 0 : g_kv_index[name];
        if (want != have) {
          respond(fd, 200, "false");
          return;
        }
      }
      g_kv[name] = req.body;
      g_kv_index[name] = ++g_kv_counter;
      plog('K', name, b64_encode(req.body));
      respond(fd, 200, "true");
    } else if (req.method == "DELETE") {
      g_kv.erase(name);
      g_kv_index.erase(name);
      plog('X', name, "-");
      respond(fd, 200, "true");
    } else {
      respond(fd, 400, "{\"error\":\"bad method\"}");
    }
  } else if (starts_with(req.path, "/lock/", &name)) {
    const std::string& op = req.form["op"];
    const std::string& owner = req.form["owner"];
    auto it = g_locks.find(name);
    if (op == "acquire") {
      if (it != g_locks.end()) {
        respond(fd, 409, "{\"held\":\"" + it->second + "\"}");
      } else {
        g_locks[name] = owner;
        plog('L', name, owner);
        respond(fd, 200, "{\"ok\":true}");
      }
    } else if (op == "release") {
      if (it == g_locks.end() || it->second != owner) {
        respond(fd, 409, "{\"error\":\"not holder\"}");
      } else {
        g_locks.erase(it);
        plog('U', name, "-");
        respond(fd, 200, "{\"ok\":true}");
      }
    } else {
      respond(fd, 400, "{\"error\":\"bad lock op\"}");
    }
  } else if (starts_with(req.path, "/counter/", &name)) {
    if (req.method == "GET") {
      respond(fd, 200,
              "{\"value\":" + std::to_string(g_counters[name]) + "}");
    } else {
      long d = atol(req.form["delta"].c_str());
      g_counters[name] += d;
      plog('C', name, std::to_string(d));
      respond(fd, 200,
              "{\"value\":" + std::to_string(g_counters[name]) + "}");
    }
  } else if (starts_with(req.path, "/queue/", &name)) {
    const std::string& op = req.form["op"];
    auto& q = g_queues[name];
    if (op == "enq") {
      q.push_back(req.form["v"]);
      plog('Q', name, req.form["v"]);
      respond(fd, 200, "{\"ok\":true}");
    } else if (op == "deq") {
      if (q.empty()) {
        respond(fd, 404, "{\"error\":\"empty\"}");
      } else {
        std::string v = q.front();
        q.pop_front();
        // At-least-once delivery: acknowledge BEFORE logging the
        // removal, so a crash in the window re-delivers the element on
        // replay (a duplicate, which total-queue tolerates) instead of
        // losing it (which it must flag) — persisted restarts stay
        // valid.
        respond(fd, 200, "{\"v\":\"" + v + "\"}");
        plog('R', name, v);
      }
    } else if (op == "drain") {
      std::vector<std::string> vs(q.begin(), q.end());
      q.clear();
      respond(fd, 200, "{\"vs\":" + json_list(vs) + "}");
      for (const auto& v : vs) plog('R', name, v);
    } else {
      respond(fd, 400, "{\"error\":\"bad queue op\"}");
    }
  } else if (starts_with(req.path, "/set/", &name)) {
    if (req.method == "GET") {
      std::vector<std::string> vs(g_sets[name].begin(),
                                  g_sets[name].end());
      respond(fd, 200, "{\"vs\":" + json_list(vs) + "}");
    } else {
      g_sets[name].insert(req.form["v"]);
      plog('E', name, req.form["v"]);
      respond(fd, 200, "{\"ok\":true}");
    }
  } else {
    respond(fd, 400, "{\"errorCode\":400,\"message\":\"bad path\"}");
  }
}

// Bank transfers manage g_mu themselves (the split-transfer race needs
// to drop the lock mid-transaction).
void handle_bank(int fd, Request& req, const std::string& name) {
  const std::string& op = req.form["op"];
  if (op == "init") {
    long n = atol(req.form["accounts"].c_str());
    long bal = atol(req.form["balance"].c_str());
    std::lock_guard<std::mutex> lock(g_mu);
    auto& bank = g_banks[name];
    if (bank.empty()) {
      for (long a = 0; a < n; ++a) bank[a] = bal;
      // One record for the whole init: replay can never restore a
      // partial bank (which later idempotent inits would skip fixing).
      plog('B', name, std::to_string(n) + ":" + std::to_string(bal));
    }
    respond(fd, 200, "{\"ok\":true}");
  } else if (op == "transfer") {
    long from = atol(req.form["from"].c_str());
    long to = atol(req.form["to"].c_str());
    long amount = atol(req.form["amount"].c_str());
    std::unique_lock<std::mutex> lock(g_mu);
    auto& bank = g_banks[name];
    if (bank.find(from) == bank.end() || bank.find(to) == bank.end()) {
      respond(fd, 404, "{\"error\":\"no such account\"}");
      return;
    }
    if (bank[from] < amount) {
      respond(fd, 409, "{\"error\":\"insufficient\"}");
      return;
    }
    bank[from] -= amount;
    if (g_bank_split_ms > 0) {
      // the seeded isolation bug: another request can observe (or
      // mutate) the mid-transfer state
      lock.unlock();
      std::this_thread::sleep_for(
          std::chrono::milliseconds(g_bank_split_ms));
      lock.lock();
    }
    // Re-look-up after the unlocked window: a --wipe-after-ops wipe
    // may have dropped the bank; die like a crashed mid-transfer
    // writer rather than dereference the stale node or resurrect it.
    auto wt = g_banks.find(name);
    if (wt == g_banks.end() || wt->second.find(to) == wt->second.end()) {
      respond(fd, 409, "{\"error\":\"wiped mid-transfer\"}");
      return;
    }
    wt->second[to] += amount;
    plog('T', name, std::to_string(from) + ":" + std::to_string(to) +
                        ":" + std::to_string(amount));
    respond(fd, 200, "{\"ok\":true}");
  } else if (op == "xtransfer") {
    // Cross-bank transfer: account 0 of bank `from` -> account 0 of
    // bank `to`, one bank per "table" (the multitable-bank shape,
    // cockroachdb/src/jepsen/cockroach/bank.clj:180-228). Honors the
    // same split-ms seeded race, now across distinct banks.
    const std::string& fromb = req.form["from"];
    const std::string& tob = req.form["to"];
    long amount = atol(req.form["amount"].c_str());
    std::unique_lock<std::mutex> lock(g_mu);
    if (g_banks.find(fromb) == g_banks.end() ||
        g_banks.find(tob) == g_banks.end()) {
      respond(fd, 404, "{\"error\":\"no such bank\"}");
      return;
    }
    if (g_banks[fromb][0] < amount) {
      respond(fd, 409, "{\"error\":\"insufficient\"}");
      return;
    }
    g_banks[fromb][0] -= amount;
    if (g_bank_split_ms > 0) {
      lock.unlock();
      std::this_thread::sleep_for(
          std::chrono::milliseconds(g_bank_split_ms));
      lock.lock();
    }
    // Same re-look-up discipline as transfer: never resurrect a
    // wiped bank through operator[].
    auto xt = g_banks.find(tob);
    if (xt == g_banks.end()) {
      respond(fd, 409, "{\"error\":\"wiped mid-transfer\"}");
      return;
    }
    xt->second[0] += amount;
    plog('M', fromb, tob + ":" + std::to_string(amount));
    respond(fd, 200, "{\"ok\":true}");
  } else if (op == "xread") {
    // Atomic snapshot across named banks (the multitable read txn,
    // bank.clj:198-206): form banks=a,b,c -> {"balances":{a:..,b:..}}.
    // Reads must not create banks: an unknown name is a 404, so a
    // wiped store surfaces as absence rather than phantom zeros.
    std::lock_guard<std::mutex> lock(g_mu);
    std::ostringstream os;
    os << "{\"balances\":{";
    std::istringstream is(req.form["banks"]);
    std::string b;
    bool first = true;
    while (std::getline(is, b, ',')) {
      auto bit = g_banks.find(b);
      if (bit == g_banks.end()) {
        respond(fd, 404, "{\"error\":\"no such bank\"}");
        return;
      }
      os << (first ? "" : ",") << "\"" << b << "\":" << bit->second[0];
      first = false;
    }
    os << "}}";
    respond(fd, 200, os.str());
  } else {  // GET: atomic snapshot of all balances
    std::lock_guard<std::mutex> lock(g_mu);
    auto& bank = g_banks[name];
    std::ostringstream os;
    os << "{\"balances\":{";
    bool first = true;
    for (const auto& kv : bank) {
      os << (first ? "" : ",") << "\"" << kv.first << "\":" << kv.second;
      first = false;
    }
    os << "}}";
    respond(fd, 200, os.str());
  }
}

// Dirty-reads table (galera/src/jepsen/galera/dirty_reads.clj): writers
// set EVERY row to one unique value; readers read all rows. Atomic mode
// (default) applies a write all-or-nothing under the lock, so an
// aborted write (form abort=1 -> 409) leaves nothing behind.
// --dirty-split-ms N is the seeded isolation bug: the lock is released
// between rows, so readers observe half-written states (inconsistent
// reads) and an aborted write leaves its first half applied — a FAILED
// transaction's value visible to readers, the dirty read the checker
// must catch.
void handle_dirty(int fd, Request& req, const std::string& name) {
  if (req.method == "GET") {
    std::lock_guard<std::mutex> lock(g_mu);
    auto it = g_dirty.find(name);
    if (it == g_dirty.end()) {
      respond(fd, 404, "{\"error\":\"no such table\"}");
      return;
    }
    std::ostringstream os;
    os << "{\"xs\":[";
    for (size_t i = 0; i < it->second.size(); ++i)
      os << (i ? "," : "") << it->second[i];
    os << "]}";
    respond(fd, 200, os.str());
    return;
  }
  const std::string& op = req.form["op"];
  if (op == "init") {
    long n = atol(req.form["rows"].c_str());
    std::lock_guard<std::mutex> lock(g_mu);
    auto& t = g_dirty[name];
    if (t.empty()) {
      t.assign((size_t)n, -1);
      plog('Y', name, std::to_string(n));
    }
    respond(fd, 200, "{\"ok\":true}");
  } else if (op == "write") {
    long x = atol(req.form["x"].c_str());
    bool abort = req.form["abort"] == "1";
    std::unique_lock<std::mutex> lock(g_mu);
    auto it = g_dirty.find(name);
    if (it == g_dirty.end()) {
      respond(fd, 404, "{\"error\":\"no such table\"}");
      return;
    }
    size_t n = it->second.size();
    if (g_dirty_split_ms <= 0) {
      // Atomic: aborted transactions apply nothing.
      if (!abort)
        for (size_t i = 0; i < n; ++i) it->second[i] = x;
    } else {
      // Row at a time with the lock dropped in between; an abort stops
      // after the first half, leaving its rows visible (the bug).
      size_t upto = abort ? n / 2 : n;
      long epoch = g_wipe_epoch.load();
      for (size_t i = 0; i < upto; ++i) {
        // Re-look-up after every relock: a concurrent wipe
        // (--wipe-after-ops) may have dropped — or a client re-init
        // recreated — the table mid-write; the epoch check makes the
        // writer die like a crashed one either way rather than write
        // pre-wipe values into a post-wipe table.
        auto jt = g_dirty.find(name);
        if (g_wipe_epoch.load() != epoch || jt == g_dirty.end() ||
            i >= jt->second.size())
          break;
        jt->second[i] = x;
        lock.unlock();
        std::this_thread::sleep_for(
            std::chrono::milliseconds(g_dirty_split_ms));
        lock.lock();
      }
    }
    if (abort) {
      // Rolled back (or, in split mode, half-applied then dropped):
      // never journaled — replay restores the last COMPLETED write,
      // the committed state.
      respond(fd, 409, "{\"error\":\"aborted\"}");
    } else {
      plog('W', name, std::to_string(x));
      respond(fd, 200, "{\"ok\":true}");
    }
  } else {
    respond(fd, 400, "{\"error\":\"bad op\"}");
  }
}

bool is_service_path(const std::string& p) {
  return p == "/ids/next" || p == "/ts/next" || p == "/ctl/clock" ||
         p.rfind("/v1/kv/", 0) == 0 || p.rfind("/lock/", 0) == 0 ||
         p.rfind("/counter/", 0) == 0 || p.rfind("/queue/", 0) == 0 ||
         p.rfind("/set/", 0) == 0;
}

void handle(int fd) {
  Request req;
  if (read_request(fd, &req)) {
    if (g_delay_ms > 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(g_delay_ms));
    const std::string prefix = "/v2/keys/";
    std::string bank_name;
    if (req.path == "/health") {
      respond(fd, 200, "{\"health\":\"true\"}");
    } else if (starts_with(req.path, "/bank/", &bank_name)) {
      handle_bank(fd, req, bank_name);   // manages g_mu itself
    } else if (starts_with(req.path, "/dirty/", &bank_name)) {
      handle_dirty(fd, req, bank_name);  // manages g_mu itself
    } else if (is_service_path(req.path)) {
      std::lock_guard<std::mutex> lock(g_mu);
      handle_service(fd, req);
    } else if (req.path.compare(0, prefix.size(), prefix) != 0) {
      respond(fd, 400, "{\"errorCode\":400,\"message\":\"bad path\"}");
    } else {
      std::string key = req.path.substr(prefix.size());
      std::lock_guard<std::mutex> lock(g_mu);
      auto it = g_store.find(key);
      if (req.method == "GET") {
        if (it == g_store.end()) {
          respond(fd, 404,
                  "{\"errorCode\":100,\"message\":\"Key not found\"}");
        } else {
          respond(fd, 200, "{\"action\":\"get\",\"node\":" +
                               node_json(key, it->second, g_index) + "}");
        }
      } else if (req.method == "PUT") {
        auto pv = req.form.find("prevValue");
        if (pv != req.form.end() &&
            (it == g_store.end() || it->second != pv->second)) {
          respond(fd, 412,
                  "{\"errorCode\":101,\"message\":\"Compare failed\"}");
        } else {
          g_store[key] = req.form["value"];
          ++g_index;
          persist(key, req.form["value"], false);
          respond(fd, it == g_store.end() ? 201 : 200,
                  "{\"action\":\"set\",\"node\":" +
                      node_json(key, req.form["value"], g_index) + "}");
        }
      } else if (req.method == "DELETE") {
        g_store.erase(key);
        ++g_index;
        persist(key, "", true);
        respond(fd, 200, "{\"action\":\"delete\"}");
      } else {
        respond(fd, 400, "{\"errorCode\":400,\"message\":\"bad method\"}");
      }
    }
  }
  close(fd);
}

// --------------------------------------------------------------- RESP
// A second, binary data plane: the disque job-queue command subset
// over RESP (REdis Serialization Protocol — what jedis speaks to real
// Disque in the reference suite, disque/src/jepsen/disque.clj:129-150).
// Commands: PING, ADDJOB <q> <body> <timeout-ms>, GETJOB [NOHANG]
// FROM <q>..., ACKJOB <id>, QLEN <q>. State is the SAME g_queues the
// HTTP plane serves, so kill/restart/--wipe-after-ops semantics apply
// identically to both planes. Enabled with --resp-port P.

long g_job_id = 0;  // guarded by g_mu

// Buffered line/byte reader for one RESP connection.
struct RespReader {
  int fd;
  std::string buf;
  size_t pos = 0;

  explicit RespReader(int fd) : fd(fd) {}

  bool fill() {
    char chunk[4096];
    ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
    if (n <= 0) return false;
    buf.append(chunk, (size_t)n);
    return true;
  }

  // One CRLF-terminated line, without the CRLF.
  bool line(std::string* out) {
    for (;;) {
      size_t nl = buf.find("\r\n", pos);
      if (nl != std::string::npos) {
        *out = buf.substr(pos, nl - pos);
        pos = nl + 2;
        if (pos > 65536) { buf.erase(0, pos); pos = 0; }
        return true;
      }
      if (!fill()) return false;
    }
  }

  // Exactly n bytes followed by CRLF (a bulk-string payload).
  bool bulk(size_t n, std::string* out) {
    while (buf.size() - pos < n + 2)
      if (!fill()) return false;
    *out = buf.substr(pos, n);
    pos += n + 2;
    return true;
  }
};

// Parse one command: an array of bulk strings (*N, then N x $len).
// Inline-command form is not supported (no client here uses it).
bool resp_read_command(RespReader& r, std::vector<std::string>* out) {
  std::string l;
  if (!r.line(&l) || l.empty() || l[0] != '*') return false;
  long n = atol(l.c_str() + 1);
  if (n <= 0 || n > 64) return false;
  out->clear();
  for (long i = 0; i < n; ++i) {
    if (!r.line(&l) || l.empty() || l[0] != '$') return false;
    long len = atol(l.c_str() + 1);
    if (len < 0 || len > 1 << 20) return false;
    std::string s;
    if (!r.bulk((size_t)len, &s)) return false;
    out->push_back(std::move(s));
  }
  return true;
}

void resp_send(int fd, const std::string& s) {
  send(fd, s.data(), s.size(), MSG_NOSIGNAL);
}

std::string resp_bulk(const std::string& s) {
  return "$" + std::to_string(s.size()) + "\r\n" + s + "\r\n";
}

std::string upper(std::string s) {
  for (auto& c : s) c = (char)toupper((unsigned char)c);
  return s;
}

void resp_handle(int fd) {
  RespReader r(fd);
  std::vector<std::string> cmd;
  while (resp_read_command(r, &cmd)) {
    std::string c = upper(cmd[0]);
    if (c == "PING") {
      resp_send(fd, "+PONG\r\n");
    } else if (c == "ADDJOB" && cmd.size() >= 3) {
      std::string id;
      {
        std::lock_guard<std::mutex> lock(g_mu);
        g_queues[cmd[1]].push_back(cmd[2]);
        id = "D-" + std::to_string(++g_job_id);
        plog('Q', cmd[1], cmd[2]);
      }
      resp_send(fd, resp_bulk(id));
    } else if (c == "GETJOB") {
      // GETJOB [NOHANG] [COUNT n] FROM q1 [q2 ...] — serve the first
      // non-empty queue, never block (the suite client always NOHANG).
      size_t from = 0;
      for (size_t i = 1; i < cmd.size(); ++i)
        if (upper(cmd[i]) == "FROM") { from = i + 1; break; }
      std::string q, body, id;
      bool got = false;
      if (from > 0) {
        std::lock_guard<std::mutex> lock(g_mu);
        for (size_t i = from; i < cmd.size() && !got; ++i) {
          auto it = g_queues.find(cmd[i]);
          if (it != g_queues.end() && !it->second.empty()) {
            q = cmd[i];
            body = it->second.front();
            it->second.pop_front();
            id = "D-" + std::to_string(++g_job_id);
            got = true;
          }
        }
      }
      if (!got) {
        resp_send(fd, "*-1\r\n");
      } else {
        // At-least-once: acknowledge BEFORE journaling the removal
        // (same crash-window discipline as the HTTP deq path).
        resp_send(fd, "*1\r\n*3\r\n" + resp_bulk(q) + resp_bulk(id) +
                          resp_bulk(body));
        std::lock_guard<std::mutex> lock(g_mu);
        plog('R', q, body);
      }
    } else if (c == "ACKJOB") {
      resp_send(fd, ":1\r\n");   // jobs are popped at GETJOB; ack is
                                 // a no-op in this at-least-once model
    } else if (c == "QLEN" && cmd.size() >= 2) {
      std::lock_guard<std::mutex> lock(g_mu);
      auto it = g_queues.find(cmd[1]);
      long n = it == g_queues.end() ? 0 : (long)it->second.size();
      resp_send(fd, ":" + std::to_string(n) + "\r\n");
    } else {
      resp_send(fd, "-ERR unknown command '" + cmd[0] + "'\r\n");
    }
  }
  close(fd);
}

int g_resp_port = 0;

void resp_listener() {
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)g_resp_port);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("resp bind");
    return;
  }
  listen(srv, 128);
  fprintf(stderr, "casd RESP listening on 127.0.0.1:%d\n", g_resp_port);
  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(resp_handle, fd).detach();
  }
}

}  // namespace

int main(int argc, char** argv) {
  int port = 2379;
  for (int i = 1; i < argc; ++i) {
    if (!strcmp(argv[i], "--ts-wall")) {  // valueless, may be last
      g_ts_wall = true;
      continue;
    }
    if (i + 1 >= argc) continue;
    if (!strcmp(argv[i], "--port")) port = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--persist")) g_persist_path = argv[i + 1];
    if (!strcmp(argv[i], "--delay-ms")) g_delay_ms = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--bank-split-ms"))
      g_bank_split_ms = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--dirty-split-ms"))
      g_dirty_split_ms = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--wipe-after-ops"))
      g_wipe_after_ops = atol(argv[i + 1]);
    if (!strcmp(argv[i], "--resp-port")) g_resp_port = atoi(argv[i + 1]);
  }
  if (g_wipe_after_ops > 0) load_wipe_state();
  replay();
  signal(SIGPIPE, SIG_IGN);
  if (g_resp_port > 0) std::thread(resp_listener).detach();

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(port);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  listen(srv, 128);
  fprintf(stderr, "casd listening on 127.0.0.1:%d\n", port);
  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(handle, fd).detach();
  }
}
