// casd — a minimal CAS key-value server speaking the etcd v2 keys API
// subset the etcd suite's client uses (GET/PUT/DELETE on /v2/keys/<key>,
// conditional PUT via prevValue). It is the in-CI stand-in for a real
// etcd node: a genuine compiled binary that the framework installs via
// its own tarball deploy, starts with start-stop-daemon + pidfile,
// pauses with SIGSTOP, and kills — so the control plane, daemon
// helpers, and nemesis paths are exercised against real processes in
// environments with no cluster and no network egress.
//
// Semantics knob for fault-detection tests: state is in-memory by
// default, so kill+restart wipes the register and the linearizability
// checker must flag post-restart reads (a real consistency violation a
// real single-node etcd would not exhibit with its WAL). With
// --persist FILE, writes go through an fsync'd log replayed on boot,
// and restarts are harmless — valid histories stay valid.
//
// Usage: casd --port P [--persist FILE] [--delay-ms N]
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>

namespace {

std::mutex g_mu;
std::map<std::string, std::string> g_store;
long g_index = 0;
std::string g_persist_path;
int g_delay_ms = 0;

void persist(const std::string& key, const std::string& value, bool del) {
  if (g_persist_path.empty()) return;
  std::ofstream f(g_persist_path, std::ios::app);
  f << (del ? "D" : "S") << " " << key << " " << value << "\n";
  f.flush();
}

void replay() {
  if (g_persist_path.empty()) return;
  std::ifstream f(g_persist_path);
  std::string op, key, value;
  while (f >> op >> key) {
    if (op == "S") {
      std::getline(f, value);
      if (!value.empty() && value[0] == ' ') value.erase(0, 1);
      g_store[key] = value;
    } else {
      std::getline(f, value);
      g_store.erase(key);
    }
    ++g_index;
  }
}

// --------------------------------------------------------- tiny HTTP

struct Request {
  std::string method, path, body;
  std::map<std::string, std::string> form;  // urlencoded body/query
};

std::string url_decode(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '%' && i + 2 < s.size()) {
      out += static_cast<char>(std::stoi(s.substr(i + 1, 2), nullptr, 16));
      i += 2;
    } else if (s[i] == '+') {
      out += ' ';
    } else {
      out += s[i];
    }
  }
  return out;
}

void parse_form(const std::string& s, std::map<std::string, std::string>* out) {
  std::istringstream is(s);
  std::string pair;
  while (std::getline(is, pair, '&')) {
    auto eq = pair.find('=');
    if (eq != std::string::npos)
      (*out)[url_decode(pair.substr(0, eq))] = url_decode(pair.substr(eq + 1));
  }
}

bool read_request(int fd, Request* req) {
  std::string buf;
  char chunk[4096];
  size_t header_end = std::string::npos;
  while (header_end == std::string::npos) {
    ssize_t n = read(fd, chunk, sizeof(chunk));
    if (n <= 0) return false;
    buf.append(chunk, n);
    header_end = buf.find("\r\n\r\n");
    if (buf.size() > 1 << 20) return false;
  }
  std::istringstream head(buf.substr(0, header_end));
  std::string version;
  head >> req->method >> req->path >> version;
  size_t content_length = 0;
  std::string line;
  std::getline(head, line);
  while (std::getline(head, line)) {
    if (strncasecmp(line.c_str(), "content-length:", 15) == 0)
      content_length = std::stoul(line.substr(15));
  }
  req->body = buf.substr(header_end + 4);
  while (req->body.size() < content_length) {
    ssize_t n = read(fd, chunk, sizeof(chunk));
    if (n <= 0) return false;
    req->body.append(chunk, n);
  }
  auto q = req->path.find('?');
  if (q != std::string::npos) {
    parse_form(req->path.substr(q + 1), &req->form);
    req->path.resize(q);
  }
  parse_form(req->body, &req->form);
  return true;
}

void respond(int fd, int status, const std::string& json) {
  const char* reason = status == 200 ? "OK"
                       : status == 201 ? "Created"
                       : status == 404 ? "Not Found"
                       : status == 412 ? "Precondition Failed"
                                       : "Bad Request";
  std::ostringstream os;
  os << "HTTP/1.1 " << status << " " << reason << "\r\n"
     << "Content-Type: application/json\r\n"
     << "Content-Length: " << json.size() << "\r\n"
     << "Connection: close\r\n\r\n"
     << json;
  std::string s = os.str();
  size_t off = 0;
  while (off < s.size()) {
    ssize_t n = write(fd, s.data() + off, s.size() - off);
    if (n <= 0) break;
    off += n;
  }
}

std::string node_json(const std::string& key, const std::string& value,
                      long index) {
  std::ostringstream os;
  os << "{\"key\":\"/" << key << "\",\"value\":\"" << value
     << "\",\"modifiedIndex\":" << index << "}";
  return os.str();
}

void handle(int fd) {
  Request req;
  if (read_request(fd, &req)) {
    if (g_delay_ms > 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(g_delay_ms));
    const std::string prefix = "/v2/keys/";
    if (req.path == "/health") {
      respond(fd, 200, "{\"health\":\"true\"}");
    } else if (req.path.compare(0, prefix.size(), prefix) != 0) {
      respond(fd, 400, "{\"errorCode\":400,\"message\":\"bad path\"}");
    } else {
      std::string key = req.path.substr(prefix.size());
      std::lock_guard<std::mutex> lock(g_mu);
      auto it = g_store.find(key);
      if (req.method == "GET") {
        if (it == g_store.end()) {
          respond(fd, 404,
                  "{\"errorCode\":100,\"message\":\"Key not found\"}");
        } else {
          respond(fd, 200, "{\"action\":\"get\",\"node\":" +
                               node_json(key, it->second, g_index) + "}");
        }
      } else if (req.method == "PUT") {
        auto pv = req.form.find("prevValue");
        if (pv != req.form.end() &&
            (it == g_store.end() || it->second != pv->second)) {
          respond(fd, 412,
                  "{\"errorCode\":101,\"message\":\"Compare failed\"}");
        } else {
          g_store[key] = req.form["value"];
          ++g_index;
          persist(key, req.form["value"], false);
          respond(fd, it == g_store.end() ? 201 : 200,
                  "{\"action\":\"set\",\"node\":" +
                      node_json(key, req.form["value"], g_index) + "}");
        }
      } else if (req.method == "DELETE") {
        g_store.erase(key);
        ++g_index;
        persist(key, "", true);
        respond(fd, 200, "{\"action\":\"delete\"}");
      } else {
        respond(fd, 400, "{\"errorCode\":400,\"message\":\"bad method\"}");
      }
    }
  }
  close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = 2379;
  for (int i = 1; i < argc - 1; ++i) {
    if (!strcmp(argv[i], "--port")) port = atoi(argv[i + 1]);
    if (!strcmp(argv[i], "--persist")) g_persist_path = argv[i + 1];
    if (!strcmp(argv[i], "--delay-ms")) g_delay_ms = atoi(argv[i + 1]);
  }
  replay();
  signal(SIGPIPE, SIG_IGN);

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(port);
  if (bind(srv, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  listen(srv, 128);
  fprintf(stderr, "casd listening on 127.0.0.1:%d\n", port);
  while (true) {
    int fd = accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(handle, fd).detach();
  }
}
