"""Client protocol: how workers apply operations to the system under test.

Mirrors jepsen/src/jepsen/client.clj:4-20. A client is specialized to a
node at setup (one client per worker process), invoked once per op, and
torn down at the end. ``invoke`` receives an invocation op dict and must
return a completion dict with type "ok" (definitely happened), "fail"
(definitely didn't), or "info" (indeterminate). Exceptions escaping
``invoke`` count as indeterminate: the worker logs an info op and retires
the process id (core.clj:185-205 semantics, see runtime.worker).
"""
from __future__ import annotations

from typing import Optional


class Client:
    def setup(self, test: dict, node) -> "Client":
        """Return a client specialized to ``node`` (may be self)."""
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        """Apply ``op``; return the completion op dict."""
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass


class NoopClient(Client):
    """Does nothing; acknowledges every op (client.clj:15-20)."""

    def invoke(self, test, op):
        return {**op, "type": "ok"}


def noop_client() -> Client:
    return NoopClient()
