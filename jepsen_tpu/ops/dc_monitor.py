"""Decrease-and-conquer peel-loop monitor — the fifth router backend.

Every WGL-family backend (scan, fused megakernel, event-chunked
resume, Pallas) pays ``events * 2^W``: the packed frontier enumerates
the pending-window powerset. "Efficient Decrease-and-Conquer
Linearizability Monitoring" (arXiv 2410.04581) shows the register
class never needs the powerset: repeatedly *peel* an extremal value
cluster — a write and the reads that observed it — whose members can
all legally linearize before everything still alive, and the history
is valid iff peeling runs to exhaustion. Cost is near-linear in
events and FLAT in W, which is exactly the unkeyed wide-window tail
(W=11+) where the frontier backends fall off a cliff.

The implementation is a *certifying pre-filter*, never a replacement:

  * ``dc_plan(batch)`` derives, on the host and from the
    ``EncodedBatch`` alone, each op's invocation time (first event
    whose slot snapshot contains it — the encoder snapshots the
    pending table at every completion), its response time (its own
    completion event index), and its value cluster (the event index
    of the write whose target state the read requires). Capability is
    decided from the row's transition TABLE, not from op names: a
    "write" is a kind valid from every state with one target, a
    "read" a kind that is the identity on exactly one state. Rows
    with fused events, pinned (info/crashed) ops in the close
    snapshot, duplicate write values, unmatched reads, cas-like
    kinds, or a statically impossible read-before-its-write are NOT
    capable and simply ride the existing WGL pipeline.
  * the device kernel is a batched, vmapped ``lax.while_loop``: each
    round is one scatter-min fold (earliest alive response per
    cluster), one scatter-max fold (latest alive invocation per
    cluster), a two-minima outside-response bound, and one gather to
    kill every peelable cluster at once. Peeling all peelable
    clusters per round is equivalent to peeling them one at a time
    (removing a peeled cluster only *raises* the others' outside
    bound), so rounds are bounded by the cluster count and typically
    O(1) on real histories.
  * the peel loop only ever *certifies validity* ("every op peeled").
    Stuck or incapable rows — the residue — fall through to the
    frontier scan inside the scheduler's one ``_ship`` sequence, so
    invalid verdicts, witnesses and bad-op indices keep exact parity
    with every other backend for free.

Soundness of a peel (cluster-first-block argument): let Z be value
v's cluster, I = max invocation time over Z, and t_out = the earliest
response among alive ops outside Z. If the write's invocation
precedes each member read's response (static) and I <= t_out, every
member can take a linearization point just after I — inside its own
interval, before every remaining op's response — and any valid
linearization of the remainder re-places above I (all its responses
are >= t_out >= I). Conversely a valid history always has a peelable
cluster: the cluster holding the first-linearized write. So "peeled
to exhaustion" == valid, and "stuck" == invalid *for capable rows* —
but stuck rows are conservatively left to the scan anyway, because
the scan also owns the counterexample decode.

``JT_ROUTER_DC=0`` removes the backend from pricing, routing and
dispatch entirely; with no probed/pinned ``dc_events_per_s`` rate the
router never selects it, so default routing is bit-identical to the
pre-DC tree (the Pallas precedent).
"""
from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .encode import EV_CLOSE, EV_FUSED, EV_OK, EncodedBatch
from .folds import _cached_kernel, _pow2

log = logging.getLogger("jepsen.dc")

_BIG = np.int32(1 << 30)


# ------------------------------------------------------------- gates

def dc_available() -> bool:
    """$JT_ROUTER_DC=0 removes the decrease-and-conquer backend from
    pricing, auto-routing AND forced dispatch — the full-disable
    switch, mirroring $JT_ROUTER_PALLAS."""
    return os.environ.get("JT_ROUTER_DC", "1") != "0"


def dc_max_rounds() -> int:
    """$JT_DC_MAX_ROUNDS caps peel rounds per dispatch (0 = the sound
    structural bound, one round per value cluster). A lower cap turns
    slow-converging rows into residue for the scan instead of
    spinning the while_loop."""
    try:
        return max(0, int(os.environ.get("JT_DC_MAX_ROUNDS", "0")))
    except ValueError:
        return 0


def dc_residue_max_frac() -> float:
    """$JT_DC_RESIDUE_MAX_FRAC: in auto routing, the peel pre-filter
    only engages when at most this fraction of a bucket's rows would
    fall through to the scan anyway (capability measured on the real
    plan) — a mostly-incapable bucket must not pay dc + scan."""
    try:
        return min(1.0, max(0.0, float(
            os.environ.get("JT_DC_RESIDUE_MAX_FRAC", "0.5"))))
    except ValueError:
        return 0.5


def online_dc_enabled() -> bool:
    """$JT_ONLINE_DC=1 wires the incremental peel monitor into the
    online daemon's delta tick (default off: the daemon's default
    behavior stays bit-identical)."""
    return os.environ.get("JT_ONLINE_DC", "0") != "0"


# ------------------------------------------------- history-level sniff

def dc_capable_history(history) -> bool:
    """Cheap Op-list sniff the router prices from (the real decision
    replays on the encoded plan): every client op completes ok, ops
    are plain read/write, written values are distinct, and every
    observed read value was written. Conservative — False only means
    the router won't price the dc backend for this unit."""
    writes: set = set()
    reads: List[object] = []
    open_inv: Dict[object, str] = {}
    for op in history:
        if not getattr(op, "is_client", True):
            continue
        if op.type == "invoke":
            if op.f not in ("read", "write"):
                return False
            open_inv[op.process] = op.f
        elif op.type == "ok":
            open_inv.pop(op.process, None)
            if op.f == "write":
                if op.value in writes:
                    return False
                writes.add(op.value)
            elif op.f == "read":
                if op.value is not None:
                    reads.append(op.value)
            else:
                return False
        else:                      # fail/info: pending-forever class
            return False
    if open_inv:
        return False
    return all(v in writes for v in reads)


# ---------------------------------------------------- space capability

def _space_roles(space) -> Optional[Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]]:
    """Classify one StateSpace's kinds from the transition TABLE:
    returns (is_write[K], is_read[K], state_of[K]) where a write is a
    constant map valid from every state (state_of = its target) and a
    read is the identity on exactly one state (state_of = it), or
    None when any non-identity kind fits neither role (cas-like) —
    the whole vocabulary is then incapable. Identity kinds have both
    flags False and constrain nothing (the stacked encoder drops
    them; the columnar walk keeps them — the plan masks them out)."""
    tgt = np.asarray(space.target)
    K, S = tgt.shape
    is_w = np.zeros(K, bool)
    is_r = np.zeros(K, bool)
    st = np.full(K, -1, np.int32)
    ident = space.identity_kinds
    states = np.arange(S)
    for k in range(K):
        row = tgt[k]
        if k in ident:
            continue
        if (row >= 0).all() and len(np.unique(row)) == 1:
            is_w[k] = True
            st[k] = int(row[0])
        else:
            ok = row == states
            if int(ok.sum()) == 1 and (row[~ok] < 0).all():
                is_r[k] = True
                st[k] = int(states[ok][0])
            else:
                return None
    return is_w, is_r, st


# ----------------------------------------------------------- the plan

@dataclass
class DCPlan:
    """Host-derived peel-loop inputs for one encoded bucket. Ops are
    indexed by their completion event (the encoder emits exactly one
    event per ok completion), so ``resp(op) == its event index``."""

    inv: np.ndarray        # int32 [B, E] first-appearance event index
    cluster: np.ndarray    # int32 [B, E] event index of the value's write
    active: np.ndarray     # bool  [B, E] capable-row op events
    capable: np.ndarray    # bool  [B]

    @property
    def capable_frac(self) -> float:
        b = len(self.capable)
        return float(self.capable.sum()) / b if b else 0.0


def dc_plan(batch: EncodedBatch) -> Optional[DCPlan]:
    """Derive the peel plan from the encoded arrays alone — no caller
    plumbing: invocation times come from a per-slot first-seen walk
    over the snapshots (reset at each completion of the slot; the
    snapshot at a completion still CONTAINS the completing op), value
    clusters from the transition-table roles. Returns None when no
    row is capable (or the batch carries no spaces)."""
    if not batch.spaces or len(batch.spaces) != batch.batch:
        return None
    B, E = batch.ev_type.shape
    K = batch.target.shape[1] - 1              # empty-slot sentinel
    etype = np.asarray(batch.ev_type)
    eslot = np.asarray(batch.ev_slot).astype(np.int64)
    slots = np.asarray(batch.ev_slots)

    capable = ~(etype == EV_FUSED).any(axis=1)
    is_ok = etype == EV_OK
    # The close snapshot is the end-of-history pending table: pinned
    # info/crashed ops stay optional-to-linearize forever, a case the
    # peel loop does not model.
    close = etype == EV_CLOSE
    has_close = close.any(axis=1)
    capable &= has_close
    ci = np.argmax(close, axis=1)
    capable &= (slots[np.arange(B), ci] == K).all(axis=1)

    # Completing op's kind per event: the snapshot row at its slot.
    kind = np.take_along_axis(slots, eslot[:, :, None],
                              axis=2)[:, :, 0].astype(np.int64)
    kind = np.where(is_ok, kind, K)

    # Per-slot first-seen walk -> invocation event index per op.
    inv = np.zeros((B, E), np.int32)
    occ = np.full((B, batch.ev_slots.shape[2]), -1, np.int32)
    comp = is_ok | (etype == EV_FUSED)
    for e in range(E):
        snap = slots[:, e, :]
        newly = (snap != K) & (occ < 0)
        occ[newly] = e
        r = np.flatnonzero(comp[:, e])
        if r.size:
            s = eslot[r, e]
            inv[r, e] = occ[r, s]
            occ[r, s] = -1

    active = np.zeros((B, E), bool)
    cluster = np.full((B, E), -1, np.int32)
    rows = np.arange(B)

    # Group rows by their StateSpace: role tables are per-vocabulary.
    by_space: Dict[int, List[int]] = {}
    spaces: Dict[int, object] = {}
    for b in np.flatnonzero(capable):
        sp = batch.spaces[b]
        by_space.setdefault(id(sp), []).append(int(b))
        spaces[id(sp)] = sp
    for sid, rws in by_space.items():
        sp = spaces[sid]
        roles = _space_roles(sp)
        r = np.asarray(rws)
        if roles is None:
            capable[r] = False
            continue
        is_w, is_r, st = roles
        nk = len(is_w)
        k = kind[r]                      # [b, E], sentinel K when pad
        known = k < nk
        # Fused-composed or foreign kind ids under a merged table.
        capable[r[((k != K) & ~known).any(axis=1)]] = False
        k = np.where(known, k, 0)
        w_ev = known & is_w[k] & is_ok[r]
        r_ev = known & is_r[k] & is_ok[r]
        act = w_ev | r_ev                # identity kinds drop out
        val = np.where(act, st[k], -1)   # register state == value id
        S = sp.n_states
        # One write per target state per row; duplicates -> incapable.
        wcount = np.zeros((len(r), S), np.int64)
        bw, ew = np.nonzero(w_ev)
        np.add.at(wcount, (bw, val[bw, ew]), 1)
        capable[r[(wcount > 1).any(axis=1)]] = False
        wpos = np.full((len(r), S), -1, np.int32)
        wpos[bw, val[bw, ew]] = ew
        cl = np.where(act, wpos[np.arange(len(r))[:, None],
                                np.clip(val, 0, S - 1)], -1)
        # A read of a never-written (e.g. initial) state: incapable —
        # the virtual initial write has no interval to peel against.
        capable[r[(act & (cl < 0)).any(axis=1)]] = False
        # Static order: a read's write must be invoked before the
        # read responds, else the history cannot be valid — leave the
        # verdict (and the witness) to the scan.
        inv_w = inv[r[:, None], np.clip(cl, 0, E - 1)]
        bad = act & (cl >= 0) & (inv_w > np.arange(E)[None, :])
        capable[r[bad.any(axis=1)]] = False
        active[r] = act
        cluster[r] = cl

    active &= capable[:, None]
    if not capable.any():
        return None
    return DCPlan(inv=inv, cluster=np.where(active, cluster, 0),
                  active=active, capable=capable)


_PLAN_MISS = object()


def dc_plan_for(batch: EncodedBatch) -> Optional[DCPlan]:
    """Per-batch memo of ``dc_plan`` (stashed on the batch object —
    chunks of one bucket share one plan)."""
    p = getattr(batch, "_dc_plan", _PLAN_MISS)
    if p is _PLAN_MISS:
        p = dc_plan(batch)
        try:
            batch._dc_plan = p
        except Exception:                          # pragma: no cover
            pass
    return p


# ------------------------------------------------------ the host twin

def dc_host_decide(inv: np.ndarray, cluster: np.ndarray,
                   active: np.ndarray,
                   max_rounds: int = 0) -> np.ndarray:
    """Pure-numpy parity oracle for the device peel loop: identical
    round structure (segment folds + two minima + batch peel), no
    jax. Returns decided-valid [B] bool."""
    B, E = active.shape
    resp = np.arange(E, dtype=np.int32)
    cap = max_rounds or E + 1
    decided = np.zeros(B, bool)
    for b in range(B):
        alive = active[b].copy()
        rounds = 0
        while alive.any() and rounds < cap:
            rounds += 1
            cl = cluster[b]
            m_resp = np.full(E, _BIG, np.int32)
            np.minimum.at(m_resp, cl[alive], resp[alive])
            m_inv = np.full(E, -1, np.int32)
            np.maximum.at(m_inv, cl[alive], inv[b][alive])
            has = m_resp < _BIG
            a1 = int(np.argmin(m_resp))
            g1 = m_resp[a1]
            m2 = m_resp.copy()
            m2[a1] = _BIG
            g2 = m2.min()
            t_out = np.where(np.arange(E) == a1, g2, g1)
            peel = has & (m_inv <= t_out)
            new_alive = alive & ~peel[cl]
            if (new_alive == alive).all():
                break
            alive = new_alive
        decided[b] = not alive.any()
    return decided


# -------------------------------------------------- the device kernel

_DC_KERNELS: Dict = {}


def get_dc_kernel(E: int, max_rounds: int = 0):
    """The batched vmapped peel loop for an E-event bucket (cached per
    (E, round cap)): per while_loop round one scatter-min / one
    scatter-max segment fold by cluster id, the two-minima outside
    bound, and one gather killing every peelable cluster. VPU-only by
    construction — no dot_general ever appears in the trace (pinned
    by the jaxpr lint's ``dc`` family allowlist)."""
    def build():
        import jax
        import jax.numpy as jnp
        from jax import lax

        cap = max_rounds or E + 1

        def one(inv, cluster, active):
            resp = jnp.arange(E, dtype=jnp.int32)
            big = jnp.int32(1 << 30)
            idx = jnp.arange(E)

            def body(state):
                alive, _, rounds = state
                cl = jnp.where(alive, cluster, 0)
                m_resp = jnp.full((E,), big, jnp.int32).at[cl].min(
                    jnp.where(alive, resp, big))
                m_inv = jnp.full((E,), -1, jnp.int32).at[cl].max(
                    jnp.where(alive, inv, -1))
                has = m_resp < big
                a1 = jnp.argmin(m_resp)
                g1 = m_resp[a1]
                g2 = jnp.min(m_resp.at[a1].set(big))
                t_out = jnp.where(idx == a1, g2, g1)
                peel = has & (m_inv <= t_out)
                new_alive = alive & ~peel[cluster]
                prog = jnp.any(new_alive != alive)
                return new_alive, prog, rounds + 1

            def cond(state):
                alive, prog, rounds = state
                return prog & jnp.any(alive) & (rounds < cap)

            alive, _, rounds = lax.while_loop(
                cond, body, (active, jnp.bool_(True), jnp.int32(0)))
            return ~jnp.any(alive), rounds

        return jax.jit(jax.vmap(one))
    return _cached_kernel(_DC_KERNELS, (int(E), int(max_rounds)), build)


def dc_decide(inv: np.ndarray, cluster: np.ndarray,
              active: np.ndarray) -> np.ndarray:
    """Run the device peel loop over plan rows (padded to pow2 shapes
    so the jit cache stays bounded). Returns decided-valid [B] bool —
    True ONLY for rows every op of which was peeled."""
    B, E = active.shape
    Bp, Ep = _pow2(max(B, 1)), _pow2(max(E, 1))
    pinv = np.zeros((Bp, Ep), np.int32)
    pcl = np.zeros((Bp, Ep), np.int32)
    pact = np.zeros((Bp, Ep), bool)
    pinv[:B, :E] = inv
    pcl[:B, :E] = np.clip(cluster, 0, Ep - 1)
    pact[:B, :E] = active
    kern = get_dc_kernel(Ep, dc_max_rounds())
    decided, _ = kern(pinv, pcl, pact)
    return np.asarray(decided)[:B]


def dc_prefilter_chunk(batch: EncodedBatch, lo: int,
                       hi: int) -> Optional[np.ndarray]:
    """The scheduler's per-chunk entry: peel rows [lo, hi) of a
    bucket. Returns decided-valid [hi-lo] bool (False = residue, the
    scan decides), or None when the chunk has no capable row (the
    dispatch proceeds exactly as before)."""
    plan = dc_plan_for(batch)
    if plan is None or not plan.capable[lo:hi].any():
        return None
    decided = dc_decide(plan.inv[lo:hi], plan.cluster[lo:hi],
                        plan.active[lo:hi])
    return decided & plan.capable[lo:hi]


# --------------------------------------------------------- rate probe

def make_probe_plan(rows: int = 64, events: int = 128,
                    w: int = 12) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
    """A deterministic dc-capable synthetic plan (inv/cluster/active)
    shaped like the unkeyed wide-window workload: W-overlapped
    write+read pairs, every cluster peelable — the rate probe and the
    bench backend_compare column both time the kernel on it."""
    E = events - (events % 2)
    inv = np.maximum(0, np.arange(E, dtype=np.int32) - int(w) + 1)
    cluster = (np.arange(E, dtype=np.int32) // 2) * 2
    active = np.ones(E, bool)
    return (np.broadcast_to(inv, (rows, E)).copy(),
            np.broadcast_to(cluster, (rows, E)).copy(),
            np.broadcast_to(active, (rows, E)).copy())


def probe_rates(rows: int = 64, events: int = 128,
                repeats: int = 3) -> Dict[str, object]:
    """Measure the peel kernel's event rate (events/s across the
    batch) on the synthetic wide-window plan — the router's
    ``dc_events_per_s`` basis; never hardcoded. Includes a host-twin
    parity bit on the probe itself."""
    out: Dict[str, object] = {"dc_events_per_s": 0.0, "probe_s": 0.0,
                              "parity": None}
    if not dc_available():
        return out
    t0 = time.monotonic()
    try:
        inv, cl, act = make_probe_plan(rows=rows, events=events)
        dev = dc_decide(inv, cl, act)          # compile outside clock
        best = None
        for _ in range(max(1, repeats)):
            t1 = time.perf_counter()
            dev = dc_decide(inv, cl, act)
            dt = time.perf_counter() - t1
            best = dt if best is None else min(best, dt)
        host = dc_host_decide(inv, cl, act)
        out["parity"] = bool((dev == host).all())
        if best and best > 0 and out["parity"]:
            out["dc_events_per_s"] = (rows * events) / best
    except Exception:                           # pragma: no cover
        log.warning("dc rate probe failed", exc_info=True)
    out["probe_s"] = round(time.monotonic() - t0, 4)
    return out


def router_prefers_dc(w: int, n_events: int, rows: int,
                      rates: Optional[dict] = None) -> bool:
    """Would the cost router run the peel pre-filter for this bucket
    shape? True when the dc term prices below every frontier device
    backend (the pre-filter's worst case adds its own cost to the
    scan's, so it must be cheap relative to the scan to be worth
    skipping scans with)."""
    from ..fleet import CostRouter
    r = CostRouter(rates=rates)
    costs = r.price_wgl(w, n_events, rows, dc=True)
    dc = costs.get("wgl-dc")
    if dc is None:
        return False
    dev = [v for k, v in costs.items()
           if k in ("wgl-device", "wgl-pallas")]
    return bool(dev) and dc < min(dev)


# ------------------------------------------------------ batch checking

def dc_check_batch(model, histories: Sequence, *,
                   details: object = "invalid") -> List[dict]:
    """Check a batch with the peel pre-filter pinned on
    (``wgl_backend="dc"``): decided rows skip their scan launch,
    residue rides the unchanged WGL pipeline inside the same
    dispatch. Rows whose scan was skipped carry
    ``provenance="wgl-dc"`` (the scheduler's row_provenance seam);
    residue rows keep their scan provenance — the verdict path is
    always named. This is route_check's ``wgl-dc`` group engine and
    the parity-test seam."""
    from .linearize import check_batch_columnar
    rs = check_batch_columnar(model, histories, details=details,
                              scheduler_opts={"wgl_backend": "dc"})
    for r in rs:
        r.setdefault("provenance", "wgl-dc")
    return rs


# --------------------------------------------- incremental (online) DC

class IncrementalDC:
    """The peel loop's decrement structure at the online daemon's
    ResidentFrontier seam ($JT_ONLINE_DC): each tick peels only the
    carried segment — the ops since the last *quiescent cut* — plus
    whatever arrived since the last tick, never the whole prefix.

    The cut rule is the soundness anchor: when a tick certifies the
    carry AND no invocation is open, the entire carry seals (drops)
    and its OVERWRITTEN values are remembered; the current epoch's
    write — when real time makes it the unique final — re-carries as
    a cut-pinned pseudo-write so live-value reads stay served. Everything after the cut
    is invoked in real time after everything before it responded, so
    a witness for the suffix composes with the sealed prefix's
    witness by pure concatenation — writes are valid from every
    state, suffix reads must observe suffix writes, and any late op
    touching a sealed value latches the carry undecided (the full
    engine owns that verdict; this monitor only ever *certifies*).

    ``advance`` returns True only for a certified-valid prefix and
    None whenever it cannot serve the tick — the caller falls through
    to the resident frontier, verdicts unchanged. Callers must drop
    the carry on ANY mid-advance fault (the engine's soundness guard
    does), exactly like the frontier itself."""

    def __init__(self):
        self.pos = 0                   # consumed history lines
        self.dead = False
        self.sealed_values: set = set()
        self._open: Dict[object, Tuple[str, object, int]] = {}
        # carried completed client ops since the cut: (inv, resp, f, v)
        self.ops: List[Tuple[int, int, str, object]] = []
        self.last_delta_ops = 0
        self.seals = 0

    def _latch(self) -> None:
        self.dead = True
        self.ops = []

    def advance(self, history: Sequence) -> Optional[bool]:
        if self.dead:
            return None
        new = history[self.pos:]
        self.last_delta_ops = len(new)
        t = self.pos
        for op in new:
            if getattr(op, "is_client", True):
                if op.type == "invoke":
                    if op.f not in ("read", "write"):
                        self._latch()
                        return None
                    self._open[op.process] = (op.f, op.value, t)
                elif op.type == "ok":
                    ent = self._open.pop(op.process, None)
                    if ent is None:
                        self._latch()
                        return None
                    f, _, inv_t = ent
                    if op.value in self.sealed_values:
                        # A late op on a sealed epoch: either invalid
                        # or beyond this monitor — never certified.
                        self._latch()
                        return None
                    if f == "read" and op.value is None:
                        # A read of the initial state: once any write
                        # sealed the initial value is history, and
                        # before that the peel order would need a
                        # virtual epoch — outside this monitor's
                        # class either way (the full engine decides).
                        self._latch()
                        return None
                    # Times are doubled so a cut-pinned pseudo-write
                    # can sit STRICTLY between two history lines.
                    self.ops.append((2 * inv_t, 2 * t, f, op.value))
                else:                   # fail / info: pending forever
                    self._latch()
                    return None
            t += 1
        self.pos = len(history)
        writes = [v for (_, _, f, v) in self.ops if f == "write"]
        if len(set(writes)) != len(writes):
            self._latch()
            return None
        vals = set(writes)
        # Reads must observe carried (completed) writes: a read of a
        # still-pending write means the completed part alone is not
        # the whole story — not servable this tick, maybe the next.
        for (_, _, f, v) in self.ops:
            if f == "read" and v is not None and v not in vals:
                return None
        if not self._run_peel():
            return None
        if not self._open:
            # Quiescent cut: the certified carry seals wholesale —
            # except the CURRENT epoch. When one carried write strictly
            # follows every other carried write in real time, EVERY
            # valid linearization ends with it, so its value is the
            # register's unique state at the cut: it re-carries as a
            # zero-width pseudo-write pinned just before the cut and
            # later reads of the live value keep being served. An
            # ambiguous final (overlapping tail writes) seals
            # everything — conservative, still sound.
            ws = [(i_, r_, v) for (i_, r_, f, v) in self.ops
                  if f == "write"]
            cur = None
            if ws:
                cand = max(ws, key=lambda e: e[0])
                if all(cand[0] > r_ for (i_, r_, _) in ws
                       if (i_, r_) != (cand[0], cand[1])):
                    cur = cand[2]
            self.sealed_values |= {v for v in vals if v != cur}
            cut = 2 * self.pos - 1
            self.ops = ([] if cur is None
                        else [(cut, cut, "write", cur)])
            self.seals += 1
        return True

    def _run_peel(self) -> bool:
        """Host peel over the carry. Open invocations are simply not
        linearized — a valid completed part IS a valid prefix (the
        pending set stays pending), so excluding them is sound for a
        monitor that only certifies."""
        if not self.ops:
            return True
        n = len(self.ops)
        inv = np.fromiter((o[0] for o in self.ops), np.int64, n)
        resp = np.fromiter((o[1] for o in self.ops), np.int64, n)
        wid = {v: k for k, (_, _, f, v) in enumerate(self.ops)
               if f == "write"}
        cl = np.fromiter((wid[o[3]] for o in self.ops), np.int64, n)
        alive = np.ones(n, bool)
        # Within-cluster feasibility, aggregated PER CLUSTER: the
        # write must be invoked before every member read responds
        # (inv_w < resp_r), or no linearization point exists and the
        # cluster can never peel — the carry stays undecided and the
        # tick answers None (the full engine owns the verdict).
        bad = np.zeros(n, bool)
        np.logical_or.at(bad, cl, inv[cl] > resp)
        while alive.any():
            m_resp = np.full(n, _BIG, np.int64)
            np.minimum.at(m_resp, cl[alive], resp[alive])
            m_inv = np.full(n, -1, np.int64)
            np.maximum.at(m_inv, cl[alive], inv[alive])
            a1 = int(np.argmin(m_resp))
            m2 = m_resp.copy()
            m2[a1] = _BIG
            t_out = np.where(np.arange(n) == a1, m2.min(), m_resp[a1])
            peel = (m_resp < _BIG) & (m_inv <= t_out) & ~bad
            new_alive = alive & ~peel[cl]
            if (new_alive == alive).all():
                return False
            alive = new_alive
        return True
