"""Host-side model state-space enumeration and transition lowering.

The TPU linearizability kernel is model-agnostic: it never interprets op
semantics. Instead, the host enumerates the *reachable state space* of a
sequential model under the history's op vocabulary (a BFS to fixpoint) and
lowers every distinct op kind to a dense transition vector
``target[s] -> s' or -1``. The host model (jepsen_tpu.models) is therefore
the single spec; the kernel merely follows integer tables.

This works whenever the reachable state space is small — which covers the
reference's practical linearizability workloads (CAS registers with small
value domains: etcd/consul/zookeeper/logcabin/aerospike; mutexes:
hazelcast locks — model semantics at jepsen/src/jepsen/model.clj:21-105).
Histories whose state space explodes past ``max_states`` fall back to the
host/native engine.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..history.ops import Op, INVOKE
from ..models.core import Model, is_inconsistent


def canonical_value(v: Any):
    """Hashable canonical form of an op value (lists become tuples)."""
    if isinstance(v, list):
        return tuple(canonical_value(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return frozenset(canonical_value(x) for x in v)
    if isinstance(v, tuple):
        return tuple(canonical_value(x) for x in v)
    return v


def op_kind(op: Op) -> Tuple:
    """The transition-relevant identity of an op: (f, canonical value)."""
    return (op.f, canonical_value(op.value))


class StateSpaceExplosion(Exception):
    """Reachable state space exceeded the kernel's static bound."""


@dataclass
class StateSpace:
    """An enumerated state space + transition tables for one op vocabulary.

    states:  model states; index 0 is the initial state.
    kinds:   op kinds, in first-seen order; index into ``target`` rows.
    target:  int32 [K, V] — target state index, or -1 if the op is
             inconsistent from that state.
    """

    states: List[Model]
    kinds: List[Tuple]
    kind_index: Dict[Tuple, int]
    target: np.ndarray
    _identity_kinds: Optional[frozenset] = None

    @property
    def n_states(self) -> int:
        return len(self.states)

    @property
    def n_kinds(self) -> int:
        return len(self.kinds)

    @property
    def identity_kinds(self) -> frozenset:
        """Kind indices whose transition is the total identity — valid
        from every state and state-preserving (they constrain nothing).
        Cached: one batch shares a StateSpace across thousands of
        histories."""
        if self._identity_kinds is None:
            V = self.target.shape[1]
            ident = np.arange(V, dtype=np.int32)
            self._identity_kinds = frozenset(
                k for k in range(self.n_kinds)
                if np.array_equal(self.target[k], ident))
        return self._identity_kinds

    def padded_target(self, v_pad: int, k_pad: int) -> np.ndarray:
        """Target table padded to [k_pad + 1, v_pad]; the final row is the
        all-invalid sentinel used for empty pending slots."""
        K, V = self.target.shape
        out = np.full((k_pad + 1, v_pad), -1, dtype=np.int32)
        out[:K, :V] = self.target
        return out


def _rep_op(kind: Tuple) -> Op:
    f, cv = kind
    v = list(cv) if isinstance(cv, tuple) else cv
    return Op(process=0, type=INVOKE, f=f, value=v)


# (model, kinds, max_states) -> StateSpace. One batch of histories pays
# the BFS once no matter how many pipeline stages re-derive the space
# (ingest, encode, check). StateSpaces are immutable once built.
_SPACE_MEMO: Dict[Tuple, StateSpace] = {}


def enumerate_statespace(model: Model, kinds: List[Tuple],
                         max_states: int) -> StateSpace:
    """BFS the reachable state space of ``model`` under ``kinds``.

    Raises StateSpaceExplosion past ``max_states``. Models must be
    hashable/eq-comparable (all jepsen_tpu.models are). Memoized.
    """
    key = (model, tuple(kinds), max_states)
    hit = _SPACE_MEMO.get(key)
    if hit is not None:
        return hit
    if len(_SPACE_MEMO) > 256:
        _SPACE_MEMO.clear()
    space = _enumerate_statespace(model, kinds, max_states)
    _SPACE_MEMO[key] = space
    return space


def _enumerate_statespace(model: Model, kinds: List[Tuple],
                          max_states: int) -> StateSpace:
    kind_ops = [(k, _rep_op(k)) for k in kinds]
    states: List[Model] = [model]
    index: Dict[Model, int] = {model: 0}
    edges: Dict[Tuple[int, int], int] = {}  # (state, kind) -> target

    frontier = [0]
    while frontier:
        nxt = []
        for si in frontier:
            s = states[si]
            for ki, (_, op) in enumerate(kind_ops):
                s2 = s.step(op)
                if is_inconsistent(s2):
                    continue
                ti = index.get(s2)
                if ti is None:
                    ti = len(states)
                    if ti >= max_states:
                        raise StateSpaceExplosion(
                            f"more than {max_states} reachable states")
                    states.append(s2)
                    index[s2] = ti
                    nxt.append(ti)
                edges[(si, ki)] = ti
        frontier = nxt

    K, V = len(kinds), len(states)
    target = np.full((K, V), -1, dtype=np.int32)
    for (si, ki), ti in edges.items():
        target[ki, si] = ti
    return StateSpace(states=states, kinds=kinds,
                      kind_index={k: i for i, (k, _) in enumerate(kind_ops)},
                      target=target)


def history_kinds(prepared: List[Op]) -> List[Tuple]:
    """Distinct op kinds among invocations, in first-seen order."""
    seen: Dict[Tuple, None] = {}
    for op in prepared:
        if op.type == INVOKE:
            seen.setdefault(op_kind(op), None)
    return list(seen.keys())


def restrict_statespace(space: StateSpace, kind_idx) -> Tuple[StateSpace,
                                                              np.ndarray]:
    """Re-enumerate ``space`` under a subset of its kind vocabulary —
    the *state renumbering* behind the per-history live-alphabet
    shrink: a history that only ever applies ``kind_idx`` kinds can
    never leave the sub-reachable space, so its frontier fits in
    ``sub.n_states`` packed states instead of the batch vocabulary's
    full reachable set (fewer packed words = less VPU work per
    transition and a smaller VMEM working set).

    Returns ``(sub, lut)`` where ``lut`` maps full-space kind indices
    to sub-space indices (-1 for kinds outside the subset). The
    verdict is unchanged by construction: every state the restricted
    history can reach is reachable under the subset BFS (same initial
    state, same transition semantics), and target rows restricted to
    substates stay within substates. Memoized through
    ``enumerate_statespace`` (the initial model is ``space.states[0]``).
    """
    kind_idx = sorted(int(k) for k in kind_idx)
    sub_kinds = [space.kinds[i] for i in kind_idx]
    sub = enumerate_statespace(space.states[0], sub_kinds,
                               len(space.states) + 1)
    lut = np.full(space.n_kinds + 1, -1, np.int32)
    for j, i in enumerate(kind_idx):
        lut[i] = j
    return sub, lut
