"""P-compositional pre-partition: split histories by key before encoding.

Linearizability is local (Herlihy & Wing; the P-compositionality frame
of arXiv:1504.00204 and the decrease-and-conquer monitors of
arXiv:2410.04581): a history over k independent keys/registers is
linearizable iff each per-key projection is. The WGL kernel's cost is
``2^W`` in the pending window W, and a multi-key history's window is
the SUM of its keys' concurrent+pinned ops — so partitioning first
collapses the expensive W classes multiplicatively: a W=14 keyed
history becomes k sub-histories at W<=6 each, 2^14 frontier words
becoming k * 2^6. This module owns that pre-encode stage for both
history forms:

  * **columnar** (``partition_columnar``): a keyed ColumnarOps batch
    (``cols.key``, workloads.synth ``n_keys``) strains into one flat
    sub-batch — one row per (history, key), lines gathered by key,
    unkeyed lines replicated into every sub (the independent.clj:233-244
    rule). The sub-batch's ``index`` column composes the partition map
    with any existing conversion map, so a sub-row's bad-op index is
    already in the ORIGINAL history's op-index space.
  * **Op lists** (``partition_histories``): KV-valued histories
    (jepsen_tpu.independent.KV) strain through the same
    ``independent.subhistory`` machinery the per-key checker uses —
    partition and IndependentChecker cannot drift because they share
    the strainer.

Recombination (``recombine_verdicts`` / ``recombine_details``) is
host-side and cheap: a history is valid iff all its sub-histories are;
the reported first-bad op is the invalid sub verdict with the smallest
original op index, and the witness carries ``independent_key`` — the
provenance the per-key checker has always reported.

Everything here is pure numpy/host work: the partition must compose
with CPU-only encode paths and never touch a device.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..history.ops import Op
from ..independent import KV, history_keys, is_kv, subhistory


@dataclass
class PartitionedBatch:
    """A keyed batch strained into per-(history, key) sub-rows.

    ``cols`` is the flat sub-batch (no key column — each sub-history is
    a plain single-register history); ``sub_history[s]`` is the original
    batch row sub ``s`` came from, ``sub_key[s]`` its key label (None
    for the unkeyed remainder of a row with no keyed lines). Sub order
    is deterministic — ascending (history row, key id) — which is what
    makes chunk-journal resume re-dispatch ZERO decided sub-histories.
    """

    cols: object
    sub_history: np.ndarray            # [S] int64
    sub_key: List                      # [S] key labels
    n_histories: int

    @property
    def n_subs(self) -> int:
        return int(self.sub_history.shape[0])

    def subs_per_history(self) -> float:
        return self.n_subs / max(self.n_histories, 1)


def pending_w_hist(cols) -> Dict[int, int]:
    """Pending-window histogram of a columnar batch BEFORE encoding:
    {peak window: rows}. The peak matches the encode walk's ``max_live``
    (invokes allocate, only ok-completions free — info ops stay pinned,
    exactly the 2^W axis the kernel pays). The bench's pre/post
    partition comparison is two of these.

    Device-synthesized batches (ops.synth_device) carry the answer as
    generator metadata — the peaks were computed on device as part of
    generation — so the full-batch cumsum re-scan is skipped (the
    metadata-agreement tests pin the two paths field-for-field)."""
    from ..history.columnar import C_INVOKE, C_OK
    meta = getattr(cols, "meta", None)
    if meta is not None and getattr(meta, "peak_w", None) is not None \
            and len(meta.peak_w) == cols.batch:
        return meta.w_hist()
    delta = ((cols.type == C_INVOKE).astype(np.int32)
             - (cols.type == C_OK).astype(np.int32))
    peak = np.maximum(np.cumsum(delta, axis=1).max(axis=1), 1)
    ws, counts = np.unique(peak, return_counts=True)
    return {int(w): int(c) for w, c in zip(ws, counts)}


def partition_columnar(cols) -> Optional[PartitionedBatch]:
    """Strain a keyed ColumnarOps batch into its per-key sub-batch.

    Returns None when the batch carries no key column or names at most
    one key (nothing to split — callers fall through to the
    unpartitioned path). Unkeyed lines (key < 0) replicate into every
    sub of their row; rows with ONLY unkeyed lines become a single
    sub with key None. Vectorized per distinct key — cost is
    O(keys * batch * lines) numpy, far below the encode walk it feeds.
    """
    from ..history.columnar import PAD, ColumnarOps
    from .. import telemetry
    key = getattr(cols, "key", None)
    if key is None:
        return None
    with telemetry.span("partition.strain", rows=cols.batch) as _sp:
        pb = _partition_columnar_impl(cols, key, PAD, ColumnarOps)
        if pb is not None:
            _sp.set(subs=pb.n_subs)
            telemetry.REGISTRY.counter("partition.batches").inc()
            telemetry.REGISTRY.counter("partition.subs").inc(pb.n_subs)
        return pb


def _partition_columnar_impl(cols, key, PAD, ColumnarOps):
    real = cols.type != PAD
    keyed = real & (key >= 0)
    uniq = np.unique(key[keyed]) if keyed.any() else np.empty(0, np.int64)
    if uniq.size <= 1 and not (real & ~keyed).any():
        return None

    unkeyed = real & (key < 0)
    has_unkeyed = bool(unkeyed.any())

    # The strain is timed inside the e2e window, so its numpy passes
    # are tuned for memory traffic: ``kmask`` folds the real-line mask
    # into one narrow key matrix up front (synth PADs retracted ops —
    # failed cas, dropped identity reads — AFTER stamping their key,
    # so a raw key compare would resurrect them), letting each per-key
    # pass touch 1 byte/line instead of 4 + a second mask pass. The
    # per-key pieces are independent (disjoint output rows) and run on
    # a thread pool — numpy releases the GIL for all of them.
    narrow = uniq.size and uniq.min() >= 0 and uniq.max() < 127
    kmask = np.where(real, key, -1).astype(
        np.int8 if narrow else key.dtype)
    cum_dtype = np.int16 if cols.type.shape[1] < (1 << 15) else np.int32

    def strain(k, rows, sel):
        """(sub rows, line coords, dest cols, per-sub counts) for one
        piece. ``sel`` full-batch when no unkeyed replication."""
        dst_all = sel.cumsum(axis=1, dtype=cum_dtype)
        rr, cc = np.nonzero(sel)
        dst = dst_all[rr, cc].astype(np.intp) - 1
        if sel.shape[0] == len(rows):          # subset form
            sl, sr = rr, rows[rr]
        else:                                  # full-batch form
            sub_of = np.empty(cols.batch, np.intp)
            sub_of[rows] = np.arange(len(rows))
            sl, sr = sub_of[rr], rr
        return k, rows, sl, sr, cc, dst, dst_all[rows, -1] \
            if sel.shape[0] != len(rows) else dst_all[:, -1]

    def piece(k):
        if has_unkeyed:
            hit = (kmask == k)
            rows = np.flatnonzero(hit.any(axis=1))
            return strain(k, rows, hit[rows] | unkeyed[rows])
        hit = kmask == k
        rows = np.flatnonzero(hit.any(axis=1))
        return strain(k, rows, hit)

    jobs: List = list(uniq.tolist())
    only_unkeyed = np.flatnonzero(real.any(axis=1)
                                  & ~keyed.any(axis=1))
    n_workers = min(max(len(jobs), 1), os.cpu_count() or 1)
    pool = None
    if n_workers > 1:
        from concurrent.futures import ThreadPoolExecutor
        pool = ThreadPoolExecutor(n_workers)   # shared by both phases
    if pool is not None:
        pieces = list(pool.map(piece, jobs))
    else:
        pieces = [piece(k) for k in jobs]
    if only_unkeyed.size:
        # Rows whose every real line is unkeyed: one passthrough sub.
        pieces.append(strain(None, only_unkeyed, real[only_unkeyed]))
    if not pieces:
        if pool is not None:
            pool.shutdown()
        return None

    Np = int(max(int(c.max()) for *_, c in pieces))
    S = int(sum(len(rows) for _, rows, *_ in pieces))

    typ = np.full((S, Np), PAD, cols.type.dtype)
    proc = np.zeros((S, Np), cols.process.dtype)
    kind = np.full((S, Np), -1, cols.kind.dtype)
    index = np.full((S, Np), -1, np.int32)
    sub_hist = np.empty(S, np.int64)
    sub_key: List = [None] * S

    starts = []
    s0 = 0
    for _, rows, *_ in pieces:
        starts.append(s0)
        s0 += len(rows)

    def scatter(arg):
        s0, (k, rows, sl, sr, cc, dst, _) = arg
        sl = s0 + sl
        typ[sl, dst] = cols.type[sr, cc]
        proc[sl, dst] = cols.process[sr, cc]
        kind[sl, dst] = cols.kind[sr, cc]
        # Compose the partition map with any conversion map: bad-op
        # indices reported off a sub-row land straight in the original
        # history's op-index space.
        index[sl, dst] = (cols.index[sr, cc]
                          if cols.index is not None
                          else cc.astype(np.int32))
        sub_hist[s0:s0 + len(rows)] = rows
        sub_key[s0:s0 + len(rows)] = [k] * len(rows)

    if pool is not None:
        list(pool.map(scatter, zip(starts, pieces)))
        pool.shutdown()
    else:
        for arg in zip(starts, pieces):
            scatter(arg)

    # Deterministic (history, key) order — the resume/journal contract.
    key_rank = np.array([-1 if k is None else int(k) for k in sub_key],
                        np.int64)
    order = np.lexsort((key_rank, sub_hist))
    sub = ColumnarOps(type=typ[order], process=proc[order],
                      kind=kind[order], kinds=cols.kinds,
                      index=index[order])
    return PartitionedBatch(cols=sub, sub_history=sub_hist[order],
                            sub_key=[sub_key[i] for i in order],
                            n_histories=cols.batch)


# ------------------------------------------------------- Op-list form

def history_has_kv(history: Sequence[Op], sample: int = 64) -> bool:
    """KV-valued history detection for the ``partition="auto"`` paths.
    Samples the first ``sample`` ops — KV workloads wrap every client
    value, so a deep-scan would only chase a pathological mix; callers
    with late-appearing keys pass ``partition=True`` explicitly."""
    for op in history[:sample]:
        if is_kv(op.value):
            return True
    return False


def partition_histories(histories: Sequence[List[Op]], *,
                        force: bool = False
                        ) -> Optional[Tuple[List[List[Op]], np.ndarray,
                                            List]]:
    """Strain KV-valued Op-list histories into per-key sub-histories.

    Returns ``(subs, sub_history, sub_key)`` — flat sub list plus the
    same mapping arrays as the columnar form — or None when no history
    carries KV values (sampled detection; ``force=True`` scans every
    op, for callers that already know the workload is keyed).
    Histories without keys pass through as a single sub (key None); op
    identity (and so ``op.index``) is preserved by the shared
    strainer, which is what maps bad ops back through the partition."""
    if not force and not any(history_has_kv(h) for h in histories):
        return None
    if force and not any(history_keys(h) for h in histories):
        return None
    subs: List[List[Op]] = []
    sub_hist: List[int] = []
    sub_key: List = []
    for i, h in enumerate(histories):
        ks = history_keys(h)
        if not ks:
            subs.append(list(h))
            sub_hist.append(i)
            sub_key.append(None)
            continue
        for k in ks:
            subs.append(subhistory(k, h))
            sub_hist.append(i)
            sub_key.append(k)
    return subs, np.asarray(sub_hist, np.int64), sub_key


# ------------------------------------------------------ recombination

def recombine_verdicts(valid: np.ndarray, bad: np.ndarray,
                       sub_history: np.ndarray, sub_key: Sequence,
                       n_histories: int
                       ) -> Tuple[np.ndarray, np.ndarray, Dict]:
    """(valid, bad) arrays over sub-rows -> arrays over histories.

    A history is valid iff every sub is; its bad index is the SMALLEST
    original op index among its invalid subs (the first violating op of
    the whole history — sub indices are already in original op-index
    space, see partition_columnar). Returns ``(valid, bad,
    bad_key)`` with ``bad_key`` mapping invalid history rows to the
    witnessing key."""
    from .linearize import INT32_MAX
    hv = np.ones(n_histories, bool)
    hb = np.full(n_histories, INT32_MAX, np.int32)
    bad_key: Dict[int, object] = {}
    inv = np.flatnonzero(~np.asarray(valid))
    for s in inv.tolist():
        h = int(sub_history[s])
        hv[h] = False
        b = int(np.asarray(bad)[s])
        if b < hb[h]:
            hb[h] = b
            bad_key[h] = sub_key[s]
    return hv, hb, bad_key


def recombine_details(results: Sequence[dict], sub_history: np.ndarray,
                      sub_key: Sequence, n_histories: int) -> List[dict]:
    """Per-sub result dicts -> per-history result dicts (host-engine
    shape). Valid histories return ``{"valid": True}`` (plus provenance
    when any sub left the happy path); invalid histories take the
    invalid sub with the smallest original bad-op index verbatim —
    op, configs, provenance — plus ``independent_key`` (the witness
    key) and ``failures`` (every invalid key), matching the lifted
    per-key checker's reporting."""
    from ..checkers.core import merge_valid
    by_hist: Dict[int, List[int]] = {}
    for s, h in enumerate(sub_history.tolist()):
        by_hist.setdefault(int(h), []).append(s)
    out: List[dict] = []
    for h in range(n_histories):
        subs = by_hist.get(h, [])
        rs = [results[s] for s in subs]
        vals = [r.get("valid") for r in rs]
        merged = merge_valid(vals) if rs else True
        if merged is True:
            r: dict = {"valid": True}
            provs = {x.get("provenance") for x in rs
                     if x.get("provenance") not in (None, "device")}
            if provs:
                r["provenance"] = sorted(provs)[0]
            if any(x.get("resumed") for x in rs):
                r["resumed"] = True
            out.append(r)
            continue
        bad_subs = [s for s in subs
                    if results[s].get("valid") is False]
        if not bad_subs:                   # only "unknown" subs
            r = dict(rs[vals.index(merged)])
            r["valid"] = merged
            out.append(r)
            continue

        def bad_index(s):
            op = results[s].get("op") or {}
            idx = op.get("index")
            return idx if idx is not None else (1 << 31) - 1

        win = min(bad_subs, key=bad_index)
        r = dict(results[win])
        r["valid"] = False
        r["independent_key"] = sub_key[win]
        r["failures"] = [sub_key[s] for s in bad_subs]
        out.append(r)
    return out


def merge_kv_histories(parts: Dict, relabel: bool = True) -> List[Op]:
    """Interleave per-key histories into one KV-valued history — the
    inverse of the strainer, used by tests and workload builders to
    manufacture multi-key histories with known per-key ground truth.
    ``parts`` maps key -> Op list; ops interleave round-robin in
    original order, values wrap in KV, and processes are relabeled
    (key-major) so keys never share a process."""
    from ..history.core import index as index_history
    items = sorted(parts.items(), key=lambda kv: repr(kv[0]))
    procs: Dict[Tuple, int] = {}
    merged: List[Op] = []
    cursors = [0] * len(items)
    while True:
        advanced = False
        for j, (k, h) in enumerate(items):
            if cursors[j] >= len(h):
                continue
            op = h[cursors[j]]
            cursors[j] += 1
            advanced = True
            p = op.process
            if relabel and isinstance(p, int):
                p = procs.setdefault((k, p), len(procs))
            merged.append(op.with_(process=p, value=KV(k, op.value),
                                   index=None))
        if not advanced:
            break
    return index_history(merged)
