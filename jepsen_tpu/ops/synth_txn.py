"""Seeded multi-key transactional history synthesis with per-level
anomaly injection — the txn family's workload generator.

Host-side (transactional extraction is host preprocessing anyway), in
the synth_device discipline: every draw is a pure function of
``(seed, history, stream)`` through the splitmix32 ``fold_in`` mixer,
with the schedule/values/fault streams split per class so perturbing
one leaves the others untouched.

Each history is a SERIAL base — ``n_txns`` committed multi-key
transactions of reads, unique-value writes, and (with probability
``p_predicate``) a full-snapshot predicate read — followed by an
injected anomaly SUFFIX on reserved keys/values that caps the
certifiable isolation level at exactly EXPECTED_CAP[anomaly]:

  ==================  ====================  =========================
  anomaly             Adya phenomenon       expected max level
  ==================  ====================  =========================
  None (clean)        —                     serializability
  write-skew          G2 (item, SI-safe)    snapshot-isolation
  phantom             G2 + G-SI             repeatable-read
  lost-update         G2-item + G-SI        read-committed
  fractured-read      G2-item + G-SI        read-committed
  aborted-read        G1a                   read-uncommitted
  intermediate-read   G1b                   read-uncommitted
  dirty-write         G0 (ww cycle)         none
  ==================  ====================  =========================

The constructions are documented edge-by-edge in doc/isolation.md;
tests/test_isolation.py pins each against BOTH engines.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..history.ops import Op, INVOKE, OK, FAIL
from .synth_device import fold_in, _ROOT

#: Injectable anomaly classes, in ladder order (strongest cap first).
ANOMALIES = ("write-skew", "phantom", "lost-update", "fractured-read",
             "aborted-read", "intermediate-read", "dirty-write")

#: The highest isolation level a history carrying the anomaly can
#: certify at (None key = clean history).
EXPECTED_CAP = {
    None: "serializability",
    "write-skew": "snapshot-isolation",
    "phantom": "repeatable-read",
    "lost-update": "read-committed",
    "fractured-read": "read-committed",
    "aborted-read": "read-uncommitted",
    "intermediate-read": "read-uncommitted",
    "dirty-write": "none",
}


@dataclass(frozen=True)
class TxnSpec:
    """One seeded batch of transactional histories.

    anomaly — None for clean histories, one of ANOMALIES to inject it
    into every history, or "mix" to draw per history from the fault
    stream (index 0 stays clean so a mix always has a SER baseline)."""

    n: int = 8
    seed: int = 0
    n_txns: int = 12
    n_keys: int = 4
    n_procs: int = 3
    ops_per_txn: int = 3
    p_predicate: float = 0.15
    anomaly: Optional[str] = None


def _rng(seed: int, i: int, stream: str) -> np.random.Generator:
    hk = fold_in(np, np.uint32(_ROOT), np.uint32(seed & 0xFFFFFFFF))
    hk = fold_in(np, hk, np.uint32(i))
    tag = sum(ord(c) << (8 * j) for j, c in enumerate(stream[:4]))
    return np.random.default_rng(int(fold_in(np, hk, np.uint32(tag))))


def _push(ops: List[Op], proc, typ, value):
    ops.append(Op(process=proc, type=typ, f="txn", value=value,
                  time=len(ops), index=len(ops)))


def _snapshot(state: dict) -> list:
    return [[k, v] for k, v in sorted(state.items()) if v is not None]


def synth_txn_history(spec: TxnSpec, i: int) -> Tuple[List[Op], Optional[str]]:
    """History ``i`` of the batch: (ops, injected-anomaly-or-None)."""
    if spec.n_procs < 2:
        raise ValueError("txn synthesis needs n_procs >= 2 "
                         "(concurrent anomaly constructions)")
    sched = _rng(spec.seed, i, "sched")
    anomaly = spec.anomaly
    if anomaly == "mix":
        fault = _rng(spec.seed, i, "fault")
        anomaly = (None if i == 0
                   else ANOMALIES[int(fault.integers(len(ANOMALIES)))])
    elif anomaly is not None and anomaly not in ANOMALIES:
        raise ValueError(f"unknown anomaly {anomaly!r}")

    keys = [f"k{j}" for j in range(spec.n_keys)]
    state = {k: None for k in keys}
    ops: List[Op] = []
    nextval = 1
    for t in range(spec.n_txns):
        proc = t % spec.n_procs
        invoke, okc = [], []
        used_pred = False
        for _ in range(spec.ops_per_txn):
            r = sched.random()
            if not used_pred and r < spec.p_predicate:
                used_pred = True
                invoke.append(["p", None, None])
                okc.append(["p", None, _snapshot(state)])
                continue
            k = keys[int(sched.integers(spec.n_keys))]
            if r < 0.5 + spec.p_predicate / 2:
                invoke.append(["r", k, None])
                okc.append(["r", k, state[k]])
            else:
                v = nextval
                nextval += 1
                invoke.append(["w", k, v])
                okc.append(["w", k, v])
                state[k] = v
        _push(ops, proc, INVOKE, invoke)
        _push(ops, proc, OK, okc)

    if anomaly is not None:
        _inject(ops, anomaly, state)
    return ops, anomaly


def synth_txn_batch(spec: TxnSpec
                    ) -> List[Tuple[List[Op], Optional[str]]]:
    """All ``spec.n`` histories, each (ops, injected anomaly)."""
    return [synth_txn_history(spec, i) for i in range(spec.n)]


# ------------------------------------------------- anomaly constructions
#
# Reserved keys ("x!", "y!", "k!") and negative values keep the suffix
# disjoint from the serial base, so the designed cycle is exactly what
# the extraction sees. Realtime edges from base txns point INTO the
# suffix and cannot close a cycle.

def _inject(ops: List[Op], anomaly: str, state: dict) -> None:
    pa, pb, pc = 0, 1, 0
    if anomaly == "dirty-write":
        # Two append txns, a reader observing contradictory list
        # orders: a ww 2-cycle (G0), below read-uncommitted.
        _push(ops, pa, INVOKE, [["append", "x!", -1], ["append", "y!", -2]])
        _push(ops, pa, OK, [["append", "x!", -1], ["append", "y!", -2]])
        _push(ops, pb, INVOKE, [["append", "x!", -3], ["append", "y!", -4]])
        _push(ops, pb, OK, [["append", "x!", -3], ["append", "y!", -4]])
        _push(ops, pc, INVOKE, [["r", "x!", None], ["r", "y!", None]])
        _push(ops, pc, OK, [["r", "x!", [-1, -3]], ["r", "y!", [-4, -2]]])
    elif anomaly == "aborted-read":
        # b reads a's write, but a ABORTED: G1a, caps at RU.
        _push(ops, pa, INVOKE, [["w", "k!", -1]])
        _push(ops, pa, FAIL, [["w", "k!", -1]])
        _push(ops, pb, INVOKE, [["r", "k!", None]])
        _push(ops, pb, OK, [["r", "k!", -1]])
    elif anomaly == "intermediate-read":
        # b reads a's NON-final write: G1b, caps at RU.
        _push(ops, pa, INVOKE, [["w", "k!", -1], ["w", "k!", -2]])
        _push(ops, pa, OK, [["w", "k!", -1], ["w", "k!", -2]])
        _push(ops, pb, INVOKE, [["r", "k!", None]])
        _push(ops, pb, OK, [["r", "k!", -1]])
    elif anomaly == "lost-update":
        # Both read the initial version, both overwrite: ww a→b plus
        # rwi b→a — a G2-item cycle that also breaks SI, caps at RC.
        _push(ops, pa, INVOKE, [["r", "k!", None], ["w", "k!", -1]])
        _push(ops, pa, OK, [["r", "k!", None], ["w", "k!", -1]])
        _push(ops, pb, INVOKE, [["r", "k!", None], ["w", "k!", -2]])
        _push(ops, pb, OK, [["r", "k!", None], ["w", "k!", -2]])
    elif anomaly == "fractured-read":
        # b sees a's write to x! but not to y!: wr a→b plus rwi b→a,
        # caps at RC.
        _push(ops, pa, INVOKE, [["w", "x!", -1], ["w", "y!", -2]])
        _push(ops, pa, OK, [["w", "x!", -1], ["w", "y!", -2]])
        _push(ops, pb, INVOKE, [["r", "x!", None], ["r", "y!", None]])
        _push(ops, pb, OK, [["r", "x!", -1], ["r", "y!", None]])
    elif anomaly == "write-skew":
        # Concurrent disjoint read-write pairs: rwi both ways and
        # nothing else — the cycle has two consecutive anti-deps, so
        # SI holds while repeatable-read fails: caps at SI.
        _push(ops, pa, INVOKE, [["r", "y!", None], ["w", "x!", -1]])
        _push(ops, pb, INVOKE, [["r", "x!", None], ["w", "y!", -2]])
        _push(ops, pa, OK, [["r", "y!", None], ["w", "x!", -1]])
        _push(ops, pb, OK, [["r", "x!", None], ["w", "y!", -2]])
    elif anomaly == "phantom":
        # b commits a row a's concurrent predicate read missed, and a
        # overwrites b on y!: ww b→a plus rwp a→b. The item planes
        # stay acyclic (RR holds); the predicate cycle has no two
        # consecutive anti-deps, so SI breaks too: caps at RR.
        snap = _snapshot(state)
        _push(ops, pa, INVOKE, [["p", None, None], ["w", "y!", -3]])
        _push(ops, pb, INVOKE, [["w", "k!", -1], ["w", "y!", -2]])
        _push(ops, pb, OK, [["w", "k!", -1], ["w", "y!", -2]])
        _push(ops, pa, OK, [["p", None, snap], ["w", "y!", -3]])
    else:                               # pragma: no cover - guarded above
        raise ValueError(f"unknown anomaly {anomaly!r}")
