"""Checker nemesis: deterministic fault injection for the pipeline itself.

Jepsen's premise is that a system must stay correct under injected
faults — and the batched device checker is itself a distributed system
(host encoder, XLA runtime, device, decode path), so it gets the same
treatment. This module is the fault layer ops.schedule's degradation
ladder is tested against: a FaultPlan names which fault fires at which
pipeline-stage boundary on which chunk, a FaultInjector executes it
deterministically, and tests assert verdict parity (field-for-field
against the fault-free run) under every schedule.

Stages mirror the streaming pipeline's boundaries:

  * ``encode``   — host-side chunk padding (before any bytes move);
  * ``dispatch`` — the device kernel call;
  * ``decode``   — the blocking device→host materialize.

Fault kinds model the real failure classes seen in production:

  * ``oom``     — raises a synthetic error carrying RESOURCE_EXHAUSTED
                  (the XLA allocator's message), driving the
                  scheduler's Bp-bisection path;
  * ``timeout`` — the chunk runs long enough to trip the watchdog
                  deadline once, then completes (late results are
                  discarded; the retry wins);
  * ``wedge``   — like timeout but far past the deadline: the dispatch
                  never comes back in useful time (the wedged-RPC /
                  dead-tunnel threat model, see DaemonFuture);
  * ``corrupt`` — the decoded verdict arrays are garbage; caught by
                  ``validate_decoded`` and treated as a retryable
                  fault (persistent corruption bisects down to the
                  poison rows, which quarantine to the host engine);
  * ``kill``    — an unclassified error that aborts the whole check
                  mid-stream (the process-death model); the scheduler
                  deliberately does NOT absorb it — it exists to test
                  the durable chunk journal's resume path.

Every injection is seeded by (stage, chunk ordinal): the same plan over
the same input produces the same fault at the same point, so fault
schedules are reproducible the way nemesis seeds are for databases.
Classification of *real* runtime failures (``classify_failure``) lives
here too, so the ladder treats injected and genuine faults through one
code path and the tests exercise exactly what production runs.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

STAGES = ("encode", "dispatch", "decode")
KINDS = ("oom", "timeout", "wedge", "corrupt", "kill")

INT32_MAX = np.int32(2**31 - 1)

# Exception type names classified as runtime (retryable / bisectable)
# failures. jax raises jaxlib's XlaRuntimeError for device and
# allocator errors; newer jax aliases it as JaxRuntimeError.
_RUNTIME_ERROR_NAMES = {"XlaRuntimeError", "JaxRuntimeError"}


class InjectedFault(RuntimeError):
    """A synthetic pipeline fault. ``kind == "oom"`` carries the XLA
    allocator's RESOURCE_EXHAUSTED tag so the one classifier handles
    injected and genuine OOMs identically."""

    def __init__(self, kind: str, stage: str, ordinal: int):
        self.kind, self.stage, self.ordinal = kind, stage, ordinal
        msg = f"injected {kind} at {stage} chunk {ordinal}"
        if kind == "oom":
            msg = "RESOURCE_EXHAUSTED: " + msg
        super().__init__(msg)


class InjectedKill(RuntimeError):
    """Deliberately unclassified: aborts the check mid-stream (the
    process-death fault the chunk journal's resume path is for)."""


class CorruptOutput(RuntimeError):
    """A decoded chunk failed the verdict-shape invariants
    (validate_decoded) — garbage from the device or the transfer."""


class WatchdogExpired(RuntimeError):
    """A chunk's decode exceeded its VPU-op-model deadline."""


def classify_failure(e: BaseException) -> Optional[str]:
    """Map a failure to the degradation ladder's branch.

    Returns ``"oom"`` (bisect the chunk), ``"transient"`` (bounded
    retry with backoff), or None (not a pipeline fault — programming
    errors and InjectedKill propagate untouched). One classifier for
    injected AND genuine faults, so the tested path is the production
    path.
    """
    if isinstance(e, InjectedKill):
        return None
    if isinstance(e, InjectedFault):
        return "oom" if e.kind == "oom" else "transient"
    if isinstance(e, (CorruptOutput, WatchdogExpired)):
        return "transient"
    if type(e).__name__ in _RUNTIME_ERROR_NAMES:
        return "oom" if "RESOURCE_EXHAUSTED" in str(e) else "transient"
    return None


def validate_decoded(valid: np.ndarray, bad: np.ndarray,
                     n_events: int) -> None:
    """Verdict-shape invariants every decoded chunk must satisfy: valid
    rows carry the INT32_MAX sentinel, invalid rows a bad-event index
    inside the real event axis. Cheap (two vectorized comparisons per
    chunk) and always on — this is how corrupt device output becomes a
    retryable fault instead of a wrong verdict."""
    v = np.asarray(valid)
    b = np.asarray(bad)
    if v.dtype != np.bool_ or v.shape != b.shape:
        raise CorruptOutput(
            f"verdict arrays malformed: valid {v.dtype}{v.shape} "
            f"bad {b.dtype}{b.shape}")
    if v.size and not (b[v] == INT32_MAX).all():
        raise CorruptOutput("valid row without the INT32_MAX sentinel")
    inv = b[~v]
    if inv.size and ((inv < 0) | (inv >= n_events)).any():
        raise CorruptOutput(
            f"invalid row with bad-event index outside [0, {n_events})")


def corrupt_arrays(valid: np.ndarray, bad: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """The ``corrupt`` fault's payload: verdicts flipped, bad indices
    insane — the shape a trashed transfer actually produces, and
    exactly what validate_decoded must catch."""
    v = np.asarray(valid).copy()
    b = np.asarray(bad).copy()
    v[:] = ~v
    b[:] = -7
    return v, b


@dataclass(frozen=True)
class FaultSpec:
    """One fault: ``kind`` at ``stage``, firing on chunk ordinal
    ``chunk`` (per-stage dispatch counter) or on EVERY chunk when
    ``chunk`` is None (a sticky/persistent fault)."""

    stage: str
    kind: str
    chunk: Optional[int] = 0

    def __post_init__(self):
        assert self.stage in STAGES, self.stage
        assert self.kind in KINDS, self.kind

    def matches(self, stage: str, ordinal: int) -> bool:
        return self.stage == stage and (self.chunk is None
                                        or self.chunk == ordinal)


class FaultPlan:
    """A deterministic fault schedule plus the timing the nemesis runs
    under. An active plan also shrinks the watchdog deadline and retry
    backoff — a nemesis exists to make faults FAST to exercise, and the
    production values (minutes) would turn every schedule into a soak
    test. ``deadline_s=None`` keeps the scheduler's own op-model
    deadline."""

    def __init__(self, specs: List[FaultSpec], *,
                 deadline_s: Optional[float] = 0.75,
                 sleep_timeout_s: float = 1.2,
                 sleep_wedge_s: float = 2.5,
                 backoff_s: float = 0.01):
        self.specs = list(specs)
        self.deadline_s = deadline_s
        self.sleep_timeout_s = sleep_timeout_s
        self.sleep_wedge_s = sleep_wedge_s
        self.backoff_s = backoff_s

    @classmethod
    def single(cls, stage: str, kind: str, chunk: int = 0,
               **kw) -> "FaultPlan":
        """One fault, once, at a specific chunk — the single-fault
        schedules the parity tests sweep."""
        return cls([FaultSpec(stage, kind, chunk)], **kw)

    @classmethod
    def sticky(cls, stage: str, kind: str, **kw) -> "FaultPlan":
        """The fault fires on EVERY chunk at that stage — persistent
        corruption/pressure; drives the full ladder down to poison-row
        quarantine."""
        return cls([FaultSpec(stage, kind, None)], **kw)

    @classmethod
    def parse(cls, text: str, **kw) -> "FaultPlan":
        """``"stage:kind[:chunk]"`` specs, comma/semicolon-separated;
        chunk ``*`` means sticky (the $JT_FAULT_PLAN syntax)."""
        specs = []
        for part in text.replace(";", ",").split(","):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            stage, kind = bits[0], bits[1]
            chunk: Optional[int] = 0
            if len(bits) > 2:
                chunk = None if bits[2] == "*" else int(bits[2])
            specs.append(FaultSpec(stage, kind, chunk))
        return cls(specs, **kw)

    def match(self, stage: str, ordinal: int) -> Optional[FaultSpec]:
        for s in self.specs:
            if s.matches(stage, ordinal):
                return s
        return None


def single_fault_schedules() -> List[Tuple[str, FaultPlan]]:
    """The canonical single-fault matrix the parity tests sweep: OOM at
    every stage boundary, one deadline-tripping timeout, one wedge, and
    one corrupt-output — each fired exactly once, on the first chunk
    that reaches its stage."""
    out = [(f"oom@{stage}", FaultPlan.single(stage, "oom"))
           for stage in STAGES]
    out.append(("timeout@dispatch", FaultPlan.single("dispatch",
                                                     "timeout")))
    out.append(("wedge@dispatch", FaultPlan.single("dispatch", "wedge")))
    out.append(("corrupt@decode", FaultPlan.single("decode", "corrupt")))
    return out


class FaultInjector:
    """Executes a FaultPlan at the pipeline's stage boundaries.

    ``fire(stage)`` is called once per chunk per stage (thread-safe:
    decode fires on watchdog worker threads). It raises for oom/kill
    faults and otherwise returns the fired kind — the CALLER interprets
    timeout/wedge (sleep via ``sleep_for``, applied where the watchdog
    can see it) and corrupt (apply ``corrupt_arrays`` to the decoded
    verdicts). ``log`` records every firing as (stage, ordinal, kind)
    for stats and for tests to assert the schedule actually engaged.
    """

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.log: List[Tuple[str, int, str]] = []
        self._ordinal: Dict[str, int] = {s: 0 for s in STAGES}
        self._lock = threading.Lock()

    @property
    def deadline_s(self) -> Optional[float]:
        return self.plan.deadline_s

    @property
    def backoff_s(self) -> Optional[float]:
        return self.plan.backoff_s

    def sleep_for(self, kind: Optional[str]) -> float:
        if kind == "timeout":
            return self.plan.sleep_timeout_s
        if kind == "wedge":
            return self.plan.sleep_wedge_s
        return 0.0

    def fire(self, stage: str) -> Optional[str]:
        with self._lock:
            n = self._ordinal[stage]
            self._ordinal[stage] = n + 1
            spec = self.plan.match(stage, n)
            if spec is None:
                return None
            self.log.append((stage, n, spec.kind))
        if spec.kind == "kill":
            raise InjectedKill(f"injected kill at {stage} chunk {n}")
        if spec.kind == "oom":
            raise InjectedFault("oom", stage, n)
        return spec.kind

    @classmethod
    def from_env(cls) -> Optional["FaultInjector"]:
        """$JT_FAULT_PLAN (e.g. ``dispatch:oom:0,decode:corrupt:*``)
        activates the nemesis process-wide — the CLI-level hook for
        running any suite or recheck under a fault schedule."""
        text = os.environ.get("JT_FAULT_PLAN")
        if not text:
            return None
        return cls(FaultPlan.parse(text))


# ------------------------------------------------- run-level nemesis

# Run lifecycle phases, in stamp order (runtime.run's WAL stamps).
RUN_PHASES = ("setup", "run", "teardown", "analyzed")

RUN_FAULT_KINDS = ("op", "phase", "wedge")


class RunFaultInjector:
    """The crash nemesis for the RUN layer — $JT_RUN_FAULT.

    Where FaultPlan kills the *checker* pipeline at chunk boundaries,
    this kills the *test run* itself at seeded lifecycle points, so
    crash-recovery parity (WAL salvage, campaign resume) is provable
    the same way checker-fault parity is. Grammar (one spec):

      * ``op:K[@R]``      — SIGKILL the process immediately after the
                            Kth history op (0-based, WAL-durable first)
                            of the Rth run in this process (default 0);
      * ``phase:NAME[@R]``— SIGKILL at that phase-stamp boundary (the
                            stamp is flushed first, so salvage sees the
                            boundary was reached);
      * ``wedge:K[:S]``   — the Kth barrier arrival (0-based, process-
                            wide) sleeps S seconds (default 3600) —
                            wedging a worker past the barrier deadline
                            so retirement is exercised, not simulated.

    Kills are SIGKILL — no handlers, no flushing beyond what already
    hit the disk: exactly the failure mode the WAL exists for. The
    fsync-before-kill for ``op:K`` is what makes schedules
    deterministic: salvage recovers exactly ops 0..K, every time.
    """

    def __init__(self, kind: str, arg, run: int = 0,
                 wedge_s: float = 3600.0):
        # ValueError, not assert: a typo'd $JT_RUN_FAULT must fail
        # loudly even under -O — a silently inert crash nemesis turns
        # every durability run into a vacuous pass.
        if kind not in RUN_FAULT_KINDS:
            raise ValueError(f"unknown run fault kind {kind!r} "
                             f"(kinds: {RUN_FAULT_KINDS})")
        if kind == "phase" and arg not in RUN_PHASES:
            raise ValueError(f"unknown run phase {arg!r} "
                             f"(phases: {RUN_PHASES})")
        self.kind = kind
        self.arg = arg
        self.run = run
        self.wedge_s = wedge_s
        self._runs = -1          # bumped by begin_run → 0-based ordinal
        self._arrivals = 0
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, text: str) -> "RunFaultInjector":
        """``op:12``, ``op:12@2``, ``phase:teardown``, ``wedge:1:0.5``."""
        bits = text.strip().split(":")
        kind = bits[0]
        if kind == "op":
            arg, run = bits[1], 0
            if "@" in arg:
                arg, r = arg.split("@")
                run = int(r)
            return cls("op", int(arg), run)
        if kind == "phase":
            arg, run = bits[1], 0
            if "@" in arg:
                arg, r = arg.split("@")
                run = int(r)
            return cls("phase", arg, run)
        if kind == "wedge":
            wedge_s = float(bits[2]) if len(bits) > 2 else 3600.0
            return cls("wedge", int(bits[1]), wedge_s=wedge_s)
        raise ValueError(f"unknown run fault {text!r} "
                         f"(kinds: {RUN_FAULT_KINDS})")

    @classmethod
    def from_env(cls) -> Optional["RunFaultInjector"]:
        text = os.environ.get("JT_RUN_FAULT")
        if not text:
            return None
        return cls.parse(text)

    def begin_run(self) -> None:
        """Called once per runtime.run — op/phase specs target one run
        ordinal, so seed campaigns can kill mid-campaign."""
        with self._lock:
            self._runs += 1

    def _kill(self) -> None:
        import signal
        os.kill(os.getpid(), signal.SIGKILL)

    def on_op(self, wal, ordinal: int) -> None:
        """WAL hook: op ``ordinal`` just appended. For a matching spec,
        force the group commit (the op must be salvageable — that is
        the fault being modeled: death AFTER acknowledge) then die."""
        if self.kind == "op" and self._runs == self.run \
                and ordinal == self.arg:
            wal.sync()
            self._kill()

    def on_phase(self, wal, phase: str) -> None:
        """WAL hook: ``phase`` stamp just written (and flushed)."""
        if self.kind == "phase" and self._runs == self.run \
                and phase == self.arg:
            wal.sync()
            self._kill()

    def barrier_delay(self) -> float:
        """DeadlineBarrier hook: seconds this arrival should sleep
        before waiting (0 for non-matching arrivals)."""
        if self.kind != "wedge":
            return 0.0
        with self._lock:
            n = self._arrivals
            self._arrivals = n + 1
        return self.wedge_s if n == self.arg else 0.0
