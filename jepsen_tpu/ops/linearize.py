"""Packed-frontier WGL linearizability kernel.

The WGL configuration set (see jepsen_tpu.checkers.linearizable for the
algorithm spec; the reference delegates the same search to Knossos at
jepsen/src/jepsen/checker.clj:82-107) is represented as a *state-packed*
boolean frontier: config (state s, linearized-pending-set m) is bit
``s % 32`` of word

    F[s // 32][m]        # one uint32 array of length M = 2^W per word

with ``m`` ranging over all 2^W subsets of the W pending-op slots. The
host encoder (jepsen_tpu.ops.encode) reduces the history to ok-completion
events, each carrying a precomputed snapshot of the pending-slot table;
a ``lax.scan`` drives one event per step:

  * close F under application of pending ops: for each occupied slot i,
    (s, m w/o i) → (target[s], m | i). One application splits mask bit i
    with a static reshape and applies the transition as V unrolled
    bit-extract / select-row / OR steps over packed words — pure VPU work
    on full 32-config lanes (V×V one-hot matmuls with V≈8 cannot feed
    the MXU; the packed formulation replaces them outright). Closure
    iterates to fixpoint via ``lax.while_loop`` (monotone OR, ≤ live
    slots iterations; re-running converged lanes under vmap is
    idempotent);
  * keep exactly the configs whose mask holds the completing slot's bit,
    clear it — a ``lax.switch`` over W static shift-halves of the mask
    axis (no gathers). An empty survivor set means the completed op
    cannot be linearized: the history is invalid, the event index is
    recorded, and the pre-completion frontier is latched so the host can
    decode a Knossos-parity counterexample config sample.

Shapes are fully static: [words(V), 2^W] per history, vmapped over the
batch and shardable over the device mesh on the batch axis
(jepsen_tpu.parallel). The mask axis provides long 128-lane vectors for
the VPU. Cost scales with 2^W * events, so callers bucket histories by
(V, W) cost class before batching.

Scheduling around the kernel lives in ops.schedule: production callers
(check_batch_tpu / check_columnar / Store.recheck) stream their cost
buckets through its BucketScheduler, which folds exact-W buckets into
a few W *classes* (padding a history into a wider class is semantics-
preserving — the extra slots stay empty in every snapshot and can never
acquire mask bits, see ops.encode.widen_batch), chunks each class for
double-buffered dispatch, and yields verdicts incrementally. Every
compiled checker variant resolves through this module's process-wide
``get_kernel`` registry, so compile accounting, AOT pre-warming, and
the persistent compilation cache see one coherent kernel set. The
exact-W flow (``scheduler=False`` / run_buckets_threaded) remains the
parity oracle the streamed path is tested against.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from ..history.ops import Op
from ..models.core import Model
from .encode import (EV_CLOSE, EV_FUSED, EV_OK, EncodedBatch,
                     batch_encode, bucket_encode, encode_history,
                     slot_ops_at_event)

INT32_MAX = np.int32(2**31 - 1)

# Widest state space the packed kernel accepts: two 32-state words.
MAX_PACKED_STATES = 64


def n_state_words(V: int) -> int:
    return (V + 31) // 32


def pack_rows(target: jnp.ndarray, V: int) -> Tuple[jnp.ndarray, ...]:
    """Lower a transition table to packed one-hot target rows.

    target: [K+1, V] int32 (-1 = inconsistent; final row = empty-slot
    sentinel, all -1). Returns one [K+1, V] uint32 array per state word:
    rows[w][k, s] has bit (target[k, s] - 32w) set when the target state
    lands in word w, else 0.
    """
    out = []
    for w in range(n_state_words(V)):
        t = target - 32 * w
        in_word = (t >= 0) & (t < 32)
        shift = jnp.clip(t, 0, 31).astype(jnp.uint32)
        out.append(jnp.where(in_word, jnp.uint32(1) << shift, jnp.uint32(0)))
    return tuple(out)


def transition(src: Tuple[jnp.ndarray, ...], rows_i: Tuple[jnp.ndarray, ...],
               V: int) -> Tuple[jnp.ndarray, ...]:
    """Apply one op to every packed config: out = ⋃_s {src has state s} ·
    rows_i[s]. ``src`` words share any shape; ``rows_i`` is [V] per word.
    Empty slots carry all-zero rows, making their application a no-op.
    """
    out = [None] * len(src)
    for s in range(V):
        bit = (src[s >> 5] >> jnp.uint32(s & 31)) & jnp.uint32(1)
        for w in range(len(src)):
            contrib = bit * rows_i[w][s]
            out[w] = contrib if out[w] is None else out[w] | contrib
    return tuple(out)


def _apply_slot(F: Tuple[jnp.ndarray, ...], i: int,
                rows_i: Tuple[jnp.ndarray, ...], V: int,
                M: int) -> Tuple[jnp.ndarray, ...]:
    """Close F one step under the op in slot ``i``: every config without
    bit i spawns (target-state, mask | bit i)."""
    hi, lo = M >> (i + 1), 1 << i
    Fr = [f.reshape(hi, 2, lo) for f in F]
    src = tuple(fr[:, 0, :] for fr in Fr)
    new = transition(src, rows_i, V)
    return tuple(
        jnp.concatenate([fr[:, :1, :], fr[:, 1:, :] | n[:, None, :]], axis=1)
           .reshape(M)
        for fr, n in zip(Fr, new))


def _complete_slot(F: Tuple[jnp.ndarray, ...], slot: jnp.ndarray, M: int,
                   W: int) -> Tuple[jnp.ndarray, ...]:
    """OK-completion of the op in (dynamic) slot: keep configs whose mask
    has the slot bit set, with the bit cleared. Static mask-axis reshape
    per branch; ``lax.switch`` picks the branch."""
    def make(i):
        def branch(F):
            hi, lo = M >> (i + 1), 1 << i
            out = []
            for f in F:
                fr = f.reshape(hi, 2, lo)
                out.append(jnp.concatenate(
                    [fr[:, 1:, :], jnp.zeros_like(fr[:, 1:, :])],
                    axis=1).reshape(M))
            return tuple(out)
        return branch

    return lax.switch(slot, [make(i) for i in range(W)], F)


def _union(F: Tuple[jnp.ndarray, ...]) -> jnp.ndarray:
    acc = F[0]
    for f in F[1:]:
        acc = acc | f
    return acc


def _changed(Fa, Fb) -> jnp.ndarray:
    acc = (Fa[0] != Fb[0]).any()
    for a, b in zip(Fa[1:], Fb[1:]):
        acc = acc | (a != b).any()
    return acc


def make_kernel(V: int, W: int, *, w_live: Optional[int] = None,
                instrument: bool = False, resume: bool = False):
    """Build the single-history checker for static bounds (V, W).

    Returns ``check(ev_type, ev_slot, ev_slots, target) ->
    (valid, bad, frontier)`` where ``bad`` is the event index of the
    first impossible completion (INT32_MAX when valid) and ``frontier``
    is the packed [words(V), 2^W] config set — the pre-failure closure
    when invalid, the final config set when valid (counterexample /
    result decoding: ``decode_frontier``). vmap/shard over a leading
    batch axis.

    ``w_live`` (<= W) bounds the closure/completion slot unroll to the
    rows' real peak-live window: a batch widened to a consolidated W
    class (ops.schedule) carries provably-empty upper slots whose
    applications are no-ops — skipping them statically cuts the VPU
    work per closure iteration by w_live/W while the mask axis keeps
    the class shape. ``instrument=True`` appends a fourth output: total
    closure while_loop iterations per row, the measured input to the
    VPU op-count roofline (vpu_op_model). ``resume=True`` builds the
    event-chunked variant instead: ``check(ev_type, ev_slot, ev_slots,
    target, F, Fbad, valid, bad) -> (valid, bad, F, Fbad)`` with the
    packed carry ([words, 2^W] uint32 per row) flowing between
    dispatches — see run_event_chunked.
    """
    assert V <= MAX_PACKED_STATES, "packed kernel bound; use host fallback"
    M = 1 << W
    NW = n_state_words(V)
    WL = W if w_live is None else max(1, min(int(w_live), W))

    def closure(F, slots_row, rows):
        # [WL, V] per word; empty slots gather zero rows. Slots >= WL
        # are empty in EVERY snapshot of the batch (encoder invariant:
        # lowest-free-first allocation keeps indices < peak-live), so
        # the static slice drops only no-op applications.
        tgt = tuple(r[slots_row[:WL]] for r in rows)

        def body(carry):
            F0, _, n = carry
            Fn = F0
            for i in range(WL):
                Fn = _apply_slot(Fn, i, tuple(t[i] for t in tgt), V, M)
            return Fn, _changed(Fn, F0), n + 1

        F, _, n = lax.while_loop(lambda c: c[1], body,
                                 (F, jnp.bool_(True), jnp.int32(0)))
        return F, n

    def step_fn(rows):
        def step(carry, ev):
            F, Fbad, valid, bad, iters = carry
            typ, slot, slots_row, idx = ev
            is_ok = (typ == EV_OK) | (typ == EV_FUSED)
            is_close = typ == EV_CLOSE  # final flush: keep the closure
            Fc, n = closure(F, slots_row, rows)
            F_ok = _complete_slot(Fc, slot, M, WL)
            empty = is_ok & ~(_union(F_ok) != 0).any()
            first = empty & valid
            F2 = tuple(jnp.where(is_ok, a, jnp.where(is_close, c, b))
                       for a, c, b in zip(F_ok, Fc, F))
            Fb2 = tuple(jnp.where(first, c, b) for c, b in zip(Fc, Fbad))
            return (F2, Fb2, valid & ~empty,
                    jnp.minimum(bad, jnp.where(empty, idx, INT32_MAX)),
                    iters + n), None
        return step

    def widen(ev_type, ev_slot, ev_slots):
        # Event arrays arrive narrow (int8 — transfer bytes are a real
        # cost off-chip); widen for gathers/switch on device.
        return (ev_type.astype(jnp.int32), ev_slot.astype(jnp.int32),
                ev_slots.astype(jnp.int32))

    def check(ev_type, ev_slot, ev_slots, target):
        ev_type, ev_slot, ev_slots = widen(ev_type, ev_slot, ev_slots)
        rows = pack_rows(target, V)
        N = ev_type.shape[0]
        Fz = tuple(jnp.zeros((M,), jnp.uint32) for _ in range(NW))
        F0 = (Fz[0].at[0].set(jnp.uint32(1)),) + Fz[1:]
        carry = (F0, Fz, jnp.bool_(True), jnp.int32(INT32_MAX),
                 jnp.int32(0))
        (F, Fbad, valid, bad, iters), _ = lax.scan(
            step_fn(rows), carry, (ev_type, ev_slot, ev_slots,
                                   jnp.arange(N, dtype=jnp.int32)))
        frontier = jnp.stack(
            [jnp.where(valid, a, b) for a, b in zip(F, Fbad)])
        if instrument:
            return valid, bad, frontier, iters
        return valid, bad, frontier

    def check_resume(ev_type, ev_slot, ev_slots, target, idx0, F_in,
                     Fb_in, valid_in, bad_in):
        ev_type, ev_slot, ev_slots = widen(ev_type, ev_slot, ev_slots)
        rows = pack_rows(target, V)
        N = ev_type.shape[0]
        carry = (tuple(F_in[i] for i in range(NW)),
                 tuple(Fb_in[i] for i in range(NW)),
                 valid_in, bad_in, jnp.int32(0))
        (F, Fbad, valid, bad, _), _ = lax.scan(
            step_fn(rows), carry,
            (ev_type, ev_slot, ev_slots,
             idx0 + jnp.arange(N, dtype=jnp.int32)))
        return valid, bad, jnp.stack(F), jnp.stack(Fbad)

    return check_resume if resume else check


# ------------------------------------------------------ kernel registry
# One process-wide registry resolves EVERY compiled checker variant —
# single-device ("data1"), batch-sharded ("data"), and mask-sharded
# ("frontier") — keyed by (kind, V, W, mesh, shared_target, donate).
# The streaming scheduler (ops.schedule) and the mesh dispatchers
# (parallel.mesh / parallel.frontier) all come through here, so compile
# accounting, pre-warming, and the persistent compilation cache see one
# coherent kernel set. ``donate=True`` builds the jit with the event
# arrays' device buffers donated (donate_argnums) — the chunked
# scheduler path ships each chunk exactly once, so XLA may recycle the
# input buffers as scan scratch instead of holding both live.
_KERNEL_REGISTRY: Dict[Tuple, object] = {}

# Every (registry key, batch shape, event shape) the process has
# dispatched — one entry per XLA compilation (jit compiles per shape).
# bench.py reports len() deltas; ops.schedule pre-warms against it.
KERNEL_SHAPE_LOG: "set" = set()

def _silence_donation_warning() -> None:
    # Donated buffers that XLA can't alias into outputs (ours never are
    # — the verdict outputs are tiny) warn on every call; the donation
    # is still honored for scratch reuse, so the warning is pure noise
    # here. Installed lazily on the first donated kernel build so
    # merely importing this library doesn't mutate global warning
    # state for applications that want the diagnostic for their own
    # donate_argnums jits.
    import warnings
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable")


def get_kernel(V: int, W: int, *, kind: str = "data1", mesh=None,
               shared_target: bool = False, donate: bool = False,
               w_live: Optional[int] = None, instrument: bool = False,
               resume: bool = False):
    """Resolve (build + cache) a compiled checker kernel.

    kind "data1" is the single-device vmapped kernel; "data" shards the
    batch axis over ``mesh``; "frontier" splits the mask axis over the
    mesh's frontier devices. All variants share the contract
    check(ev_type, ev_slot, ev_slots, target) -> (valid, bad, frontier).
    The frontier variant does not support donation (its shard_map
    carries the event arrays through a collective scan), so ``donate``
    is normalized off there rather than cached under a key that lies.

    ``w_live`` bounds the slot unroll to the batch's real peak-live
    window (make_kernel); normalized to W when it wouldn't shrink the
    unroll so equivalent requests share one compile. ``instrument`` and
    ``resume`` are single-device (data1) variants only.
    """
    if kind == "frontier":
        donate = False
    if w_live is None or w_live >= W or kind == "frontier":
        w_live = W
    key = (kind, V, W, id(mesh) if mesh is not None else None,
           shared_target, donate, w_live, instrument, resume)
    k = _KERNEL_REGISTRY.get(key)
    if k is None:
        donate_argnums = (0, 1, 2) if donate else ()
        if donate:
            _silence_donation_warning()
        if kind == "data1":
            assert not (instrument and resume)
            kern = make_kernel(V, W, w_live=w_live,
                               instrument=instrument, resume=resume)
            if resume:
                # idx0 is a shared scalar; carry arrays batch like the
                # event tables.
                k = jax.jit(jax.vmap(
                    kern, in_axes=(0, 0, 0,
                                   None if shared_target else 0,
                                   None, 0, 0, 0, 0)))
            else:
                k = jax.jit(jax.vmap(kern,
                                     in_axes=(0, 0, 0,
                                              None if shared_target
                                              else 0)),
                            donate_argnums=donate_argnums)
        elif kind == "frontier":
            from ..parallel.frontier import frontier_sharded_kernel
            k = frontier_sharded_kernel(V, W, mesh, shared_target)
        elif kind == "data":
            from ..parallel.mesh import data_sharded_kernel
            k = data_sharded_kernel(V, W, mesh, shared_target,
                                    donate=donate, w_live=w_live)
        else:
            raise ValueError(f"unknown kernel kind {kind!r}")
        _KERNEL_REGISTRY[key] = k
    return k


def make_fused_kernel(members):
    """Build the multi-bucket megakernel body: one program that scans
    several class buckets back to back — ``members`` is a tuple of
    (V, W, w_live, shared_target) per bucket chunk, the callable takes
    4 flat args per member (ev_type, ev_slot, ev_slots, target) and
    returns 3 flat outputs per member (valid, bad, frontier). One jit
    of this retires a whole dispatch group in a single XLA call — the
    per-dispatch overhead (host round trip, launch latency) that
    dominates the many-small-buckets shape is paid once per group
    instead of once per bucket (ops.schedule's fused dispatch path)."""
    kerns = []
    for (V, W, wl, shared) in members:
        kerns.append(jax.vmap(make_kernel(V, W, w_live=wl),
                              in_axes=(0, 0, 0, None if shared else 0)))

    def fused(*flat):
        out = []
        for i, kern in enumerate(kerns):
            out.extend(kern(*flat[4 * i:4 * i + 4]))
        return tuple(out)

    return fused


def get_fused_kernel(members, donate: bool = False):
    """Resolve (build + cache) a compiled fused multi-bucket kernel —
    the dispatch-group twin of ``get_kernel``, sharing the process-wide
    registry so compile accounting and AOT shipping see one kernel
    set. ``members`` as in make_fused_kernel; ``donate`` donates every
    member's event buffers (each group ships exactly once)."""
    members = tuple(tuple(m) for m in members)
    key = ("fusedN", members, donate)
    k = _KERNEL_REGISTRY.get(key)
    if k is None:
        donate_argnums = tuple(j for i in range(len(members))
                               for j in (4 * i, 4 * i + 1, 4 * i + 2)) \
            if donate else ()
        if donate:
            _silence_donation_warning()
        k = jax.jit(make_fused_kernel(members),
                    donate_argnums=donate_argnums)
        _KERNEL_REGISTRY[key] = k
    return k


def log_kernel_shapes(V: int, W: int, kind: str, shared_target: bool,
                      donate: bool, B: int, N: int,
                      w_live: Optional[int] = None) -> None:
    """Record a dispatch shape (one registry entry per XLA compile)."""
    KERNEL_SHAPE_LOG.add((kind, V, W, shared_target, donate, B, N,
                          w_live if w_live and w_live < W else W))


def batch_kernel(V: int, W: int, shared_target: bool = False,
                 w_live: Optional[int] = None):
    """``shared_target``: every row uses one transition table — the
    table is passed unbatched ([K+1, V]) and broadcast on device,
    saving the per-row transfer."""
    return get_kernel(V, W, shared_target=shared_target, w_live=w_live)


# Frontier-words budget per device dispatch: B * words(V) * 2^W uint32.
# Keeps the scan carry (plus XLA's temporaries) well inside one chip's
# HBM even for info-heavy windows (W=16 → 0.5 MB/history).
MAX_FRONTIER_ELEMENTS = 1 << 26

# Pending-window width the single-device kernel accepts at its
# VMEM-resident sweet spot; wider windows split their mask axis over the
# mesh's "frontier" devices (the sequence-parallel path,
# jepsen_tpu.parallel.frontier) — the TPU answer to the reference
# handing Knossos a 32 GB JVM heap (project.clj:22).
DATA_MAX_SLOTS = 16

# Without enough frontier devices, a single device still hosts this many
# extra window bits by letting the mask axis spill to HBM (2^18 masks =
# 2 MB/history/word) and shrinking the batch chunk to compensate — time
# and bandwidth traded for not falling back to the host engine. W=17-18
# buckets on the one-chip bench env ride this instead of the CPU.
SINGLE_DEVICE_EXTRA_SLOTS = 2

# Don't pay an SPMD compile to spread a handful of rows: batches below
# this many rows per device stay on one device.
MIN_ROWS_PER_DEVICE = 8

# Dispatch telemetry: (path, V, W, B) per device call — "data1" (single
# device), "dataN" (batch sharded over the mesh), "frontier" (mask axis
# sharded). Tests and the multichip dryrun assert the route taken;
# bounded so long-lived checker processes don't grow it forever.
from collections import deque
DISPATCH_LOG: "deque" = deque(maxlen=256)

_PROD_MESHES: Dict[Tuple[int, int], object] = {}


def device_frontier_capacity() -> int:
    """Extra pending-window bits the attached devices can host beyond
    DATA_MAX_SLOTS: log2 of the largest power-of-two device count (the
    frontier-sharded path), and never less than the single-device
    HBM-spill margin (the data1wide path). The encoder may window up to
    DATA_MAX_SLOTS + capacity slots before a history must fall back to
    the host engine."""
    import jax
    try:
        nd = len(jax.devices())
    except Exception:
        return 0   # no backend at all: no data1wide path either
    return max(nd.bit_length() - 1, SINGLE_DEVICE_EXTRA_SLOTS)


def production_mesh(n_frontier: int = 1):
    """The process-wide ("data", "frontier") mesh for production
    dispatch, or None when the devices can't host the frontier axis (or
    there is only one device and no frontier need)."""
    import jax
    nd = len(jax.devices())
    if n_frontier > nd or (nd < 2 and n_frontier == 1):
        return None
    key = (nd, n_frontier)
    mesh = _PROD_MESHES.get(key)
    if mesh is None:
        from ..parallel.mesh import checker_mesh
        mesh = checker_mesh(n_data=nd // n_frontier,
                            n_frontier=n_frontier)
        _PROD_MESHES[key] = mesh
    return mesh


def _sharded_kernel(kind: str, V: int, W: int, mesh,
                    shared_target: bool = False,
                    w_live: Optional[int] = None):
    return get_kernel(V, W, kind="frontier" if kind == "frontier"
                      else "data", mesh=mesh, shared_target=shared_target,
                      w_live=w_live)


def _pad_rows(batch: EncodedBatch, bp: int) -> Tuple[np.ndarray, ...]:
    """Pad a batch's arrays to ``bp`` rows with inert histories (all
    events PAD, empty slot tables, all-invalid targets): they scan to
    valid=True and are sliced off after the device call."""
    b, n, w = batch.batch, batch.n_events, batch.ev_slots.shape[2]
    K1, V = batch.target.shape[1], batch.target.shape[2]
    ev_type = np.zeros((bp, n), batch.ev_type.dtype)
    ev_slot = np.zeros((bp, n), batch.ev_slot.dtype)
    ev_slots = np.full((bp, n, w), K1 - 1, batch.ev_slots.dtype)
    ev_type[:b] = batch.ev_type
    ev_slot[:b] = batch.ev_slot
    ev_slots[:b] = batch.ev_slots
    if batch.shared_target:
        # Dispatch ships batch.target[0] once; don't materialize B copies.
        return ev_type, ev_slot, ev_slots, None
    target = np.full((bp, K1, V), -1, np.int32)
    target[:b] = batch.target
    return ev_type, ev_slot, ev_slots, target


def _round_up_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def run_encoded_batch(batch: EncodedBatch, return_frontier: bool = False):
    """Device-check one cost bucket; routes to the right kernel for the
    bucket's window and the attached devices:

      * W <= DATA_MAX_SLOTS, small batch or one device — single-device
        vmapped kernel, chunked to bound memory;
      * W <= DATA_MAX_SLOTS, large batch on a multi-device mesh — batch
        axis sharded over "data" (jepsen_tpu.parallel.mesh);
      * W > DATA_MAX_SLOTS — mask axis split over 2^(W - 16) "frontier"
        devices (jepsen_tpu.parallel.frontier). Raises WindowOverflow
        when the devices can't host the axis — callers route those rows
        to a host engine.

    Blocking; multi-bucket callers overlap the per-dispatch round trips
    with ``run_buckets_threaded``. Returns (valid [B] bool, bad [B],
    frontier) — frontier is [B, words(V), 2^W] uint32 when
    ``return_frontier=True``, None when False (skipping the
    device→host transfer, which verdict-only hot paths shouldn't pay),
    and with ``return_frontier="invalid"`` a dict {row: frontier_row}
    holding ONLY the invalid rows — gathered on device, so the replay
    product path never ships the valid majority's frontiers across a
    latency-bound link.
    """
    if batch.batch == 0:
        z = np.zeros((0,), bool)
        empty_front = ({} if return_frontier == "invalid" else
                       np.zeros((0, 1, 1 << batch.W), np.uint32)
                       if return_frontier else None)
        return z, np.zeros((0,), np.int32), empty_front

    if batch.W > DATA_MAX_SLOTS:
        D = 1 << (batch.W - DATA_MAX_SLOTS)
        mesh = production_mesh(D)
        if mesh is not None:
            pending = _dispatch_sharded("frontier", batch, mesh,
                                        return_frontier)
        elif batch.W - DATA_MAX_SLOTS <= SINGLE_DEVICE_EXTRA_SLOTS:
            # Not enough devices to shard the mask axis: run the wide
            # window on one device, HBM-resident, with the batch chunk
            # shrunk in proportion (time for memory — the single-chip
            # degradation path).
            pending = _data1_dispatch(batch, return_frontier,
                                      label="data1wide")
        else:
            raise WindowOverflow(
                f"window W={batch.W} needs {D} frontier devices")
    else:
        mesh = production_mesh(1)
        from ..parallel.mesh import should_shard
        if should_shard(batch.batch, mesh):
            pending = _dispatch_sharded("dataN", batch, mesh,
                                        return_frontier)
        else:
            # Sub-minimum sharding (rows/device below the
            # $JT_SHARD_MIN_ROWS floor) regresses — MULTICHIP_r06's
            # 4/8-device points — so thin batches stay on one device.
            pending = _data1_dispatch(batch, return_frontier)

    valids, bads, fronts = [], [], []
    front_map = {} if return_frontier == "invalid" else None
    off = 0
    for valid, bad, front, nb in pending:
        v = np.asarray(valid)[:nb]
        valids.append(v)
        bads.append(np.asarray(bad)[:nb])
        if return_frontier is True:
            fronts.append(np.asarray(front)[:nb])
        elif return_frontier == "invalid":
            rows = np.nonzero(~v)[0]
            if rows.size:
                sel = np.asarray(front[rows])   # device-side gather
                for i, r in enumerate(rows):
                    front_map[off + int(r)] = sel[i]
        off += nb
    frontier = (np.concatenate(fronts) if return_frontier is True
                else front_map)
    return np.concatenate(valids), np.concatenate(bads), frontier


def _data1_dispatch(batch: EncodedBatch, return_frontier: bool,
                    label: str = "data1"):
    """Single-device vmapped dispatch, batch-chunked so the in-flight
    frontier words stay inside MAX_FRONTIER_ELEMENTS (wide windows get
    proportionally smaller chunks)."""
    kern = batch_kernel(batch.V, batch.W, batch.shared_target,
                        w_live=batch.eff_w_live)
    per_hist = n_state_words(batch.V) << batch.W
    chunk = max(1, MAX_FRONTIER_ELEMENTS // per_hist)
    DISPATCH_LOG.append((label, batch.V, batch.W, batch.batch))
    pending = []
    for lo in range(0, batch.batch, chunk):
        hi = min(lo + chunk, batch.batch)
        log_kernel_shapes(batch.V, batch.W, "data1", batch.shared_target,
                          False, hi - lo, batch.n_events,
                          batch.eff_w_live)
        valid, bad, front = kern(
            batch.ev_type[lo:hi], batch.ev_slot[lo:hi],
            batch.ev_slots[lo:hi],
            batch.target[0] if batch.shared_target
            else batch.target[lo:hi])
        pending.append((valid, bad,
                        front if return_frontier else None,
                        hi - lo))
    return pending


def run_event_chunked(batch: EncodedBatch, events_per_chunk: int,
                      return_frontier: bool = False):
    """Single-device dispatch with the EVENT axis chunked: the packed
    frontier carry ([words, 2^W] per row) flows between dispatches, so
    a 100k-op history never materializes one 100k-step scan. Chunks are
    double-buffered for free — jax dispatch is async, so chunk k+1's
    (narrow int8) event upload overlaps chunk k's device scan; rows
    whose frontier already emptied are closed early in the only sense
    that matters on a converged scan: every further step is an
    idempotent no-op on an all-zero carry. Same (valid, bad, frontier)
    contract as run_encoded_batch; parity-tested against the one-shot
    scan (tests/test_fusion.py)."""
    assert batch.W <= DATA_MAX_SLOTS + SINGLE_DEVICE_EXTRA_SLOTS
    B, N = batch.batch, batch.n_events
    NW, M = n_state_words(batch.V), 1 << batch.W
    if B == 0:
        return (np.zeros((0,), bool), np.zeros((0,), np.int32),
                np.zeros((0, NW, M), np.uint32) if return_frontier
                else None)
    kern = get_kernel(batch.V, batch.W, shared_target=batch.shared_target,
                      w_live=batch.eff_w_live, resume=True)
    C = max(8, int(events_per_chunk))
    F = np.zeros((B, NW, M), np.uint32)
    F[:, 0, 0] = 1                      # (initial state, empty mask)
    Fb = np.zeros((B, NW, M), np.uint32)
    valid = np.ones(B, bool)
    bad = np.full(B, INT32_MAX, np.int32)
    tgt = (np.ascontiguousarray(batch.target[0]) if batch.shared_target
           else batch.target)
    out = (valid, bad, F, Fb)
    for lo in range(0, N, C):
        hi = min(lo + C, N)
        if hi - lo == C:
            # Full chunks pass slices straight through; only the final
            # ragged chunk pads (EV_PAD steps are no-ops), keeping one
            # compiled shape without copying every chunk.
            ev_t = batch.ev_type[:, lo:hi]
            ev_s = batch.ev_slot[:, lo:hi]
            ev_ss = batch.ev_slots[:, lo:hi]
        else:
            ev_t = np.zeros((B, C), batch.ev_type.dtype)
            ev_s = np.zeros((B, C), batch.ev_slot.dtype)
            ev_ss = np.full((B, C, batch.ev_slots.shape[2]),
                            batch.target.shape[1] - 1,
                            batch.ev_slots.dtype)
            ev_t[:, :hi - lo] = batch.ev_type[:, lo:hi]
            ev_s[:, :hi - lo] = batch.ev_slot[:, lo:hi]
            ev_ss[:, :hi - lo] = batch.ev_slots[:, lo:hi]
        log_kernel_shapes(batch.V, batch.W, "data1ev",
                          batch.shared_target, False, B, C,
                          batch.eff_w_live)
        out = kern(ev_t, ev_s, ev_ss, tgt, np.int32(lo), out[2], out[3],
                   out[0], out[1])
    valid = np.asarray(out[0])
    bad = np.asarray(out[1])
    frontier = None
    if return_frontier:
        F, Fb = np.asarray(out[2]), np.asarray(out[3])
        frontier = np.where(valid[:, None, None], F, Fb)
    return valid, bad, frontier


# ------------------------------------------------ carried-frontier seam
#
# The kernel contract's resume variant (make_kernel(resume=True)) is the
# seam the ONLINE incremental path rides: the packed carry — configs-so-
# far frontier F, the latched pre-failure frontier Fbad, and the running
# (valid, bad) verdict — flows OUT of one dispatch and back INTO the
# next, so a live tenant's rolling prefix check resumes from where the
# last tick stopped instead of re-walking from op 0 (ROADMAP item 2 /
# the decrease-and-conquer monitoring argument, arXiv 2410.04581).
# run_event_chunked uses the same carry within one call; these helpers
# let a caller hold it ACROSS calls — and across processes, via the
# export/import pair (zlib+b64, the journal frontier-checkpoint row's
# payload). The Pallas megakernel has no resume entry (its frontier
# lives in VMEM for exactly one launch — ops.pallas_wgl), so carried
# dispatch always rides the lax.scan kernel.

# Event-axis chunk for carried dispatch; shapes pad to the power-of-two
# ladder (floor CARRY_QUANTUM) so a daemon's varying tick sizes share a
# handful of compiled shapes per (V, W) instead of one per length.
CARRY_EVENT_CHUNK = 2048
CARRY_QUANTUM = 64


def frontier_carry_init(V: int, W: int) -> dict:
    """A fresh single-row carry: the initial config (state 0, empty
    mask) present, verdict valid, no bad event."""
    NW, M = n_state_words(V), 1 << W
    F = np.zeros((1, NW, M), np.uint32)
    F[0, 0, 0] = 1
    return {"valid": np.ones(1, bool),
            "bad": np.full(1, INT32_MAX, np.int32),
            "F": F,
            "Fb": np.zeros((1, NW, M), np.uint32)}


def run_carried_events(V: int, W: int, target: np.ndarray,
                       ev_type: np.ndarray, ev_slot: np.ndarray,
                       ev_slots: np.ndarray, idx0: int,
                       carry: dict) -> dict:
    """Advance a carried frontier over ``N`` new events (single row,
    shared target) and return the new carry, host-materialized. Events
    are dispatched in CARRY_EVENT_CHUNK steps padded to the power-of-
    two ladder (EV_PAD steps are no-ops on the scan), so one tenant
    compiles a bounded shape set however its tick sizes vary. ``bad``
    in the carry is a GLOBAL event ordinal (``idx0`` continues the
    tenant's event numbering across calls)."""
    N = int(ev_type.shape[0])
    kern = get_kernel(V, W, shared_target=True, resume=True)
    out = (carry["valid"], carry["bad"], carry["F"], carry["Fb"])
    tgt = np.ascontiguousarray(target)
    for lo in range(0, N, CARRY_EVENT_CHUNK):
        hi = min(lo + CARRY_EVENT_CHUNK, N)
        n = hi - lo
        C = min(CARRY_EVENT_CHUNK,
                max(CARRY_QUANTUM, 1 << (n - 1).bit_length()))
        et = np.zeros((1, C), np.int8)
        es = np.zeros((1, C), np.int8)
        ess = np.full((1, C, W), target.shape[0] - 1, np.int32)
        et[0, :n] = ev_type[lo:hi]
        es[0, :n] = ev_slot[lo:hi]
        ess[0, :n] = ev_slots[lo:hi]
        log_kernel_shapes(V, W, "data1carry", True, False, 1, C, W)
        out = kern(et, es, ess, tgt, np.int32(idx0 + lo),
                   out[2], out[3], out[0], out[1])
    return {"valid": np.asarray(out[0]), "bad": np.asarray(out[1]),
            "F": np.asarray(out[2]), "Fb": np.asarray(out[3])}


def export_frontier(carry: dict) -> dict:
    """Serialize a carry for the journal frontier-checkpoint row
    (doc/online.md documents the format). The packed bitsets compress
    hard (config sets are sparse), so the row stays journal-sized."""
    import base64
    import zlib

    def pack(a):
        return base64.b64encode(
            zlib.compress(np.ascontiguousarray(a).tobytes())).decode()

    return {"v": 1, "shape": list(carry["F"].shape),
            "valid": bool(carry["valid"][0]),
            "bad": int(carry["bad"][0]),
            "F": pack(carry["F"]), "Fb": pack(carry["Fb"])}


def import_frontier(d: dict, V: int, W: int) -> Optional[dict]:
    """Deserialize an exported carry; None on any mismatch (a stale or
    foreign checkpoint is a cache miss, never a failure mode)."""
    import base64
    import zlib
    try:
        if d.get("v") != 1:
            return None
        shape = tuple(d["shape"])
        if shape != (1, n_state_words(V), 1 << W):
            return None

        def unpack(s):
            a = np.frombuffer(zlib.decompress(base64.b64decode(s)),
                              np.uint32)
            return a.reshape(shape).copy()

        return {"valid": np.array([bool(d["valid"])]),
                "bad": np.array([int(d["bad"])], np.int32),
                "F": unpack(d["F"]), "Fb": unpack(d["Fb"])}
    except Exception:
        return None


def grow_frontier_states(carry: dict, old_words: int,
                         new_words: int) -> dict:
    """Widen a carry's state axis (appended vocabulary reached new
    states past the current word pad): new states' bits start 0 in
    every config, which is exactly right — no existing config holds
    them. The mask axis (2^W) is untouched."""
    if new_words == old_words:
        return carry
    assert new_words > old_words
    out = dict(carry)
    for k in ("F", "Fb"):
        a = carry[k]
        wide = np.zeros((a.shape[0], new_words, a.shape[2]), np.uint32)
        wide[:, :old_words] = a
        out[k] = wide
    return out


def fused_bad_rows(batch: EncodedBatch, valid, bad) -> np.ndarray:
    """Row positions (within ``batch``) whose first impossible
    completion landed on an EV_FUSED step. The device only knows such
    a run's FIRST member, so every consumer — check_batch_tpu,
    check_columnar, bench parity — re-derives these rows' exact bad
    op/counterexample through a host-side engine; this is the one
    shared detector so the invariant can't drift between them."""
    v = np.asarray(valid)
    b = np.asarray(bad)
    inv = np.nonzero(~v)[0]
    return inv[batch.ev_type[inv, b[inv]] == EV_FUSED]


def vpu_op_model(V: int, W: int, w_live: Optional[int] = None) -> dict:
    """Analytic uint32 VPU lane-op counts for the packed kernel — the
    op-count basis behind the bench's measured ``vpu_util`` roofline.

    Per closure ITERATION (one while_loop body pass): each of the
    ``w_live`` slot applications walks V states, paying 2 lane-ops to
    extract the state bit and, per packed word, a multiply + OR over
    the M/2 spawned-mask lanes, plus the OR-merge back into the mask
    halves; the convergence check compares + reduces every frontier
    word. Per EVENT on top: the completion shift-half, the emptiness
    union/any, and the three latch selects, all over full [NW, M]
    words. Host-side constants only — the measured input (iterations
    per row) comes from the instrumented kernel (make_kernel
    ``instrument=True``)."""
    NW = n_state_words(V)
    M = 1 << W
    WL = W if w_live is None else max(1, min(int(w_live), W))
    per_apply = (M // 2) * (V * (2 + 2 * NW) + NW)
    per_iteration = WL * per_apply + 2 * NW * M
    per_event = 5 * NW * M
    return {"per_iteration": per_iteration, "per_event": per_event,
            "words": NW, "masks": M, "w_live": WL}


class WindowOverflow(Exception):
    """A cost bucket's pending window exceeds what the attached devices
    can host; the rows belong on a host/native engine."""


class DaemonFuture:
    """A one-shot future computed on a DAEMON thread. Device dispatch
    can wedge (a dead tunnel, a hung runtime) and neither jax nor the
    native engine is interruptible mid-call — a non-daemon worker (or a
    concurrent.futures pool, whose atexit hook joins its threads even
    after shutdown(wait=False)) would then block interpreter exit, the
    exact hazard LinearizableChecker._compete dodges the same way."""

    def __init__(self, fn, *args, name: str = "jepsen-async"):
        import queue
        self._q: "queue.Queue" = queue.Queue(1)

        def run():
            try:
                self._q.put((fn(*args), None))
            except BaseException as e:   # noqa: BLE001 — relayed below
                self._q.put((None, e))

        import threading
        threading.Thread(target=run, name=name, daemon=True).start()

    def result(self):
        r, err = self._q.get()
        if err is not None:
            raise err
        return r


def daemon_map(fn, items, max_workers: int = 12):
    """Map ``fn`` over ``items`` on daemon worker threads, yielding
    results in SUBMISSION order as they become consumable (the
    run_buckets_threaded delivery contract)."""
    items = list(items)
    if not items:
        return
    if len(items) == 1:
        yield fn(items[0])
        return
    import queue
    import threading

    slots = [queue.Queue(1) for _ in items]
    feed: "queue.Queue" = queue.Queue()
    for i, it in enumerate(items):
        feed.put((i, it))

    def work():
        while True:
            try:
                i, it = feed.get_nowait()
            except queue.Empty:
                return
            try:
                slots[i].put((fn(it), None))
            except BaseException as e:  # noqa: BLE001 — relayed below
                slots[i].put((None, e))

    for n in range(min(max_workers, len(items))):
        threading.Thread(target=work, name=f"jepsen-bucket-{n}",
                         daemon=True).start()
    for s in slots:
        r, err = s.get()
        if err is not None:
            raise err
        yield r


def run_buckets_threaded(batches: Sequence[EncodedBatch],
                         return_frontier: bool = False):
    """Run many cost buckets concurrently from a daemon-thread pool and
    yield (batch, (valid, bad, frontier) | WindowOverflow) pairs. JAX
    execution is thread-safe; overlapping the per-call round trips is
    what keeps many-bucket batches fast when the device sits behind a
    link with real latency (PCIe queues locally, a network tunnel under
    axon). Yields in SUBMISSION order (callers zip against their input
    list) as results become consumable, so the caller's per-bucket host
    work overlaps buckets still on device. Workers are daemon threads:
    a wedged device dispatch must not block interpreter exit.

    See ops.schedule.run_buckets_streamed for the pipelined variant
    (W-class consolidation + chunked double-buffered dispatch); this
    exact-bucket path remains the parity oracle for it."""
    if not batches:
        return []

    def one(batch):
        try:
            return batch, run_encoded_batch(batch, return_frontier)
        except WindowOverflow as e:
            return batch, e

    return daemon_map(one, batches)


def _dispatch_sharded(kind: str, batch: EncodedBatch, mesh,
                      return_frontier: bool):
    """Queue one bucket through a sharded kernel, padding the batch to
    the data-axis multiple and chunking to bound per-device memory."""
    n_data = mesh.shape["data"]
    kern = _sharded_kernel("frontier" if kind == "frontier" else "data",
                           batch.V, batch.W, mesh, batch.shared_target,
                           w_live=batch.eff_w_live)
    # Per-device budget: (chunk / n_data) rows x (per_hist / n_frontier)
    # words <= MAX_FRONTIER_ELEMENTS  =>  chunk <= MAX * size / per_hist.
    per_hist = n_state_words(batch.V) << batch.W
    chunk = _round_up_to(
        max(n_data, MAX_FRONTIER_ELEMENTS * mesh.size // max(per_hist, 1)),
        n_data)
    DISPATCH_LOG.append((kind, batch.V, batch.W, batch.batch))
    out = []
    for lo in range(0, batch.batch, chunk):
        hi = min(lo + chunk, batch.batch)
        nb = hi - lo
        bp = _round_up_to(nb, n_data)
        sub = EncodedBatch(
            ev_type=batch.ev_type[lo:hi], ev_slot=batch.ev_slot[lo:hi],
            ev_slots=batch.ev_slots[lo:hi], ev_opidx=batch.ev_opidx[lo:hi],
            target=batch.target[lo:hi], V=batch.V, W=batch.W,
            indices=[], failures=[], shared_target=batch.shared_target)
        ev_type, ev_slot, ev_slots, target = _pad_rows(sub, bp)
        log_kernel_shapes(batch.V, batch.W, kind, batch.shared_target,
                          False, bp, batch.n_events,
                          batch.eff_w_live if kind != "frontier"
                          else batch.W)
        valid, bad, front = kern(
            ev_type, ev_slot, ev_slots,
            batch.target[0] if batch.shared_target else target)
        out.append((valid, bad, front if return_frontier else None, nb))
    return out


def decode_frontier(frontier: np.ndarray, space, slot_to_op: Dict[int, int],
                    n: int = 10) -> List[dict]:
    """Decode a packed [words, M] frontier into a bounded, deterministic
    config sample matching the host engine's shape
    (checkers.linearizable._sample_configs): ``{"model": repr(state),
    "pending": sorted linearized op indices}``, sorted, truncated to n —
    the reference's truncate-to-10 discipline (checker.clj:104-107)."""
    words, masks = np.nonzero(np.asarray(frontier))
    configs = []
    for w, m in zip(words.tolist(), masks.tolist()):
        bits = int(frontier[w, m])
        s = 0
        while bits:
            if bits & 1:
                state = 32 * w + s
                if state < len(space.states):
                    pend = sorted(slot_to_op[i] for i in range(32)
                                  if (m >> i) & 1 and i in slot_to_op)
                    configs.append({"model": repr(space.states[state]),
                                    "pending": pend})
            bits >>= 1
            s += 1
    configs.sort(key=lambda c: (c["model"], c["pending"]))
    return configs[:n]


def _decode_result(space, ops: List[Op], valid: bool,
                   op_index: int, frontier_row,
                   predropped: bool = False) -> dict:
    """Host-shaped result dict from a kernel verdict: {"valid"} plus, on
    failure, the impossible op and a decoded config sample — one decoder
    for both device paths so counterexample discipline can't drift.
    ``predropped``: the op stream already had identity drops applied
    (columnar-sourced rows), so the slot replay can skip the per-op
    state-space recompute."""
    if valid:
        out = {"valid": True}
        if space is not None:
            table = slot_ops_at_event(space, ops, None,
                                      predropped=predropped)
            out["configs"] = decode_frontier(frontier_row, space, table)
        return out
    op = next((o for o in ops if o.index == op_index), None)
    out = {"valid": False,
           "op": op.to_dict() if op is not None else {"index": op_index}}
    if space is not None:
        # Locate the pending table by the bad op's history index, not
        # the device event ordinal — fusion compacts the event axis, so
        # ordinals no longer line up with the unfused walk.
        table = slot_ops_at_event(space, ops, None, predropped=predropped,
                                  op_index=op_index)
        out["configs"] = decode_frontier(frontier_row, space, table)
    return out


def _result_for(row: int, batch: EncodedBatch, valid: np.ndarray,
                bad: np.ndarray, frontier: np.ndarray, model: Model,
                prepared: List[Op]) -> dict:
    space = batch.spaces[row] if batch.spaces else None
    ev = int(bad[row])
    op_index = int(batch.ev_opidx[row, ev]) if not bool(valid[row]) else -1
    return _decode_result(space, prepared, bool(valid[row]), op_index,
                          frontier[row])


def _rehydrate_verdict(valid: bool, bad: Optional[int],
                       prov: str) -> dict:
    """Result dict for a row decided by a previous interrupted run
    (chunk journal). Bare — no config sample; the journal records
    verdicts, not frontiers — and marked ``resumed``."""
    out: dict = {"valid": valid, "provenance": prov, "resumed": True}
    if valid is False:
        out["op"] = {"index": bad}
    return out


def _journal_result(journal, i: int, r: dict) -> None:
    """Journal one host-decided row's final verdict (no-op without a
    journal). One translation for both checkers: _sink_verdict."""
    if journal is not None:
        _sink_verdict(journal.record, i, r)


def check_batch_tpu(model: Model, histories: Sequence[List[Op]], *,
                    max_states: int = MAX_PACKED_STATES, max_slots: int = 16,
                    host_fallback=None, min_device_batch: int = 1,
                    scheduler: bool = True, faults=None, journal=None,
                    scheduler_opts: Optional[dict] = None,
                    partition: object = "auto") -> List[dict]:
    """Check many raw histories on device; per-history result dicts.

    Histories the encoder cannot bound (state-space explosion, pending
    window overflow) are delegated to ``host_fallback(model, history)``
    (default: the exact host engine). Cost-class buckets smaller than
    ``min_device_batch`` go to the native CPU engine instead — the tail
    of info-heavy (large-W) histories is typically a handful of rows,
    not worth an XLA compile or the widest frontier.

    ``scheduler=True`` (default) dispatches through the streaming
    bucket scheduler (ops.schedule): exact-W buckets consolidate into
    few W classes, chunks pipeline against host decode, and — because
    small narrow buckets now merge into healthy classes — the
    ``min_device_batch`` CPU route only applies to *wide*
    (W >= DATA_MAX_SLOTS) stragglers. ``scheduler=False`` keeps the
    one-kernel-per-exact-W flow (the parity oracle for the scheduler).

    On the scheduler path every result carries a ``provenance`` tag —
    ``device`` / ``device-retried`` / ``host-fallback`` (which engine,
    and how hard the ladder had to work, decided the row; see
    doc/resilience.md). Rows the scheduler quarantines after its
    degradation ladder are re-decided by ``host_fallback`` (the exact
    parity oracle), so every history gets a verdict under any fault
    schedule. ``faults`` injects a checker nemesis (ops.faults);
    ``journal`` (store.ChunkJournal) makes retired chunk verdicts
    durable and resumes from them; ``scheduler_opts`` forwards knobs to
    BucketScheduler (chunk_rows, max_classes, ...).

    ``partition`` is the P-compositional pre-partition (ops.partition):
    KV-valued histories strain into per-key sub-histories BEFORE
    encoding — each key checks at its own (much smaller) pending
    window W, collapsing the 2^W frontier cost — and verdicts
    recombine host-side with the witness key preserved
    (``independent_key``). ``"auto"`` (default) samples each history's
    head for KV values; True forces the strain; False keeps the
    unpartitioned path. The journal's row namespace becomes the
    (deterministically ordered) sub-history list, so a resumed run
    re-dispatches ZERO decided sub-histories.
    """
    from ..checkers.linearizable import prepare_history, wgl_check
    from ..history.core import index as index_history
    from .encode import take_rows
    if partition:
        from .partition import partition_histories, recombine_details
        parts = partition_histories(histories,
                                    force=partition is True)
        if parts is not None:
            subs, sub_hist, sub_key = parts
            inner = check_batch_tpu(
                model, subs, max_states=max_states, max_slots=max_slots,
                host_fallback=host_fallback,
                min_device_batch=min_device_batch, scheduler=scheduler,
                faults=faults, journal=journal,
                scheduler_opts=scheduler_opts, partition=False)
            return recombine_details(inner, sub_hist, sub_key,
                                     len(histories))
    if host_fallback is None:
        _cache: dict = {}

        def host_fallback(m, h):
            return wgl_check(m, h, space_cache=_cache)

    for h in histories:
        if any(op.index is None for op in h):
            index_history(h)
    prepared = [prepare_history(h) for h in histories]
    # Windows beyond the single-device kernel are encodable when the
    # mesh can shard their mask axis (the frontier path).
    eff_slots = max_slots + (device_frontier_capacity()
                             if max_slots >= DATA_MAX_SLOTS else 0)
    # The streamed path encodes fused (single-candidate runs collapse
    # into EV_FUSED steps); the exact path stays the unfused oracle.
    buckets = bucket_encode(model, prepared,
                            max_states=min(max_states, MAX_PACKED_STATES),
                            max_slots=eff_slots, fuse=scheduler)

    results: List[Optional[dict]] = [None] * len(histories)
    decided: dict = {}
    if journal is not None and scheduler:
        decided = {i: d for i, d in journal.decided().items()
                   if 0 <= i < len(histories)}
        for i, (vl, bd, pv) in decided.items():
            results[i] = _rehydrate_verdict(vl, bd, pv)
    device_batches = []
    for batch in buckets:
        if decided:
            # Resume: rows with journaled verdicts never re-dispatch.
            batch = take_rows(batch, [r for r, i in
                                      enumerate(batch.indices)
                                      if i not in decided])
        if 0 < batch.batch < min_device_batch and \
                (not scheduler or batch.W >= DATA_MAX_SLOTS):
            # Small-bucket CPU route. Under the scheduler, narrow small
            # buckets instead merge into a consolidated class — only
            # wide stragglers still ride the native engine.
            try:
                from ..native import check_batch_native
                rs = check_batch_native(model,
                                        [histories[i] for i in batch.indices])
            except Exception:
                rs = [host_fallback(model, histories[i])
                      for i in batch.indices]
            for i, r in zip(batch.indices, rs):
                if scheduler:
                    r.setdefault("provenance", "host-fallback")
                    _journal_result(journal, i, r)
                results[i] = r
        elif batch.batch:
            device_batches.append(batch)
        for i, reason in batch.failures:
            if i in decided:
                continue
            r = host_fallback(model, histories[i])
            r.setdefault("fallback", reason)
            if scheduler:
                r.setdefault("provenance", "host-fallback")
                _journal_result(journal, i, r)
            results[i] = r
    sch = None
    if scheduler:
        from .schedule import BucketScheduler
        sch = BucketScheduler(return_frontier=True, faults=faults,
                              **(scheduler_opts or {}))
        if journal is not None:
            sch.on_chunk = _batch_chunk_recorder(sch, journal)
        stream = sch.run(device_batches)
    else:
        stream = run_buckets_threaded(device_batches, return_frontier=True)
    for batch, out in stream:
        if isinstance(out, WindowOverflow):
            for i in batch.indices:
                r = host_fallback(model, histories[i])
                r.setdefault("fallback", str(out))
                if scheduler:
                    r.setdefault("provenance", "host-fallback")
                    _journal_result(journal, i, r)
                results[i] = r
            continue
        valid, bad, front = out
        valid, bad = np.asarray(valid), np.asarray(bad)
        fused = set(fused_bad_rows(batch, valid, bad).tolist())
        for row, i in enumerate(batch.indices):
            if sch is not None and i in sch.quarantined:
                continue           # placeholder; re-decided below
            if row in fused:
                # The first impossible completion fell inside a fused
                # run: the device only knows the run's first member.
                # Re-derive the exact bad op + counterexample on the
                # host — rare (invalid rows failing in a sequential
                # stretch), and the host engine is the parity shape.
                results[i] = host_fallback(model, histories[i])
                if scheduler:
                    results[i].setdefault("provenance", "host-fallback")
                    _journal_result(journal, i, results[i])
                continue
            results[i] = _result_for(row, batch, valid, bad, front,
                                     model, prepared[i])
            if sch is not None:
                results[i]["provenance"] = sch.row_provenance.get(
                    i, "device")
    if sch is not None:
        # Quarantined rows: the degradation ladder gave up on device —
        # the exact host oracle decides them, so every history still
        # gets a verdict under any fault schedule.
        for i, why in sch.quarantined.items():
            r = host_fallback(model, histories[i])
            r.setdefault("fallback", f"quarantined: {why}")
            r["provenance"] = "host-fallback"
            _journal_result(journal, i, r)
            results[i] = r
    return results


def _batch_chunk_recorder(sch, journal):
    """on_chunk hook journaling device chunk verdicts as they retire
    (check_batch_tpu shape: bad is the history-op index). Rows that
    need host re-derivation — fused-run failures, quarantined rows —
    are skipped here and journaled when their final verdict lands."""
    def on_chunk(b, lo, hi, v, bad, fr):
        rows, vals, bads, provs = [], [], [], []
        for k in range(hi - lo):
            rp = lo + k
            i = b.indices[rp]
            if i in sch.quarantined:
                continue
            vk = bool(v[k])
            bd = None
            if not vk:
                ev = int(bad[k])
                if b.ev_type[rp, ev] == EV_FUSED:
                    continue
                bd = int(b.ev_opidx[rp, ev])
            rows.append(i)
            vals.append(vk)
            bads.append(bd)
            provs.append(sch.row_provenance.get(i, "device"))
        journal.record(rows, vals, bads, provs)
    return on_chunk


def check_one_tpu(model: Model, history: List[Op], **kw) -> dict:
    """Single-history device check (the Checker-protocol TPU backend)."""
    return check_batch_tpu(model, [history], **kw)[0]


class _NativeTailWorker:
    """Chews small wide (W >= DATA_MAX_SLOTS) buckets on the native CPU
    engine, on a daemon thread UNDER the device window — the measured
    device/native cost crossover for tiny wide dispatches. ``add``
    enqueues row indices as the stream discovers them; ``finish``
    returns [(row, result-or-None)] (None = native engine failed; the
    caller re-routes those rows)."""

    def __init__(self, model: Model, cols):
        import queue
        self.model = model
        self.cols = cols
        self._q: "queue.Queue" = queue.Queue()
        self._fut: Optional[DaemonFuture] = None

    def add(self, indices) -> None:
        if self._fut is None:
            self._fut = DaemonFuture(self._run, name="jepsen-native-tail")
        self._q.put(list(indices))

    def finish(self) -> list:
        if self._fut is None:
            return []
        self._q.put(None)
        return self._fut.result()

    def _run(self) -> list:
        from ..history.columnar import columnar_to_ops
        from ..native import check_batch_native
        out = []
        while True:
            idxs = self._q.get()
            if idxs is None:
                return out
            try:
                rs = check_batch_native(
                    self.model,
                    [columnar_to_ops(self.cols, i) for i in idxs])
            except Exception:
                out.extend((i, None) for i in idxs)
                continue
            out.extend(zip(idxs, rs))


def _cols_take(cols, rows):
    """Row-subset of a ColumnarOps batch (the journal-resume filter)."""
    r = np.asarray(rows, np.int64)
    key = getattr(cols, "key", None)
    return type(cols)(
        type=cols.type[r], process=cols.process[r], kind=cols.kind[r],
        kinds=cols.kinds,
        index=cols.index[r] if cols.index is not None else None,
        key=key[r] if key is not None else None)


def check_columnar(model: Model, cols, *, max_slots: int = 16,
                   host_fallback=None, details=False,
                   min_device_batch: int = 1, scheduler: bool = True,
                   faults=None, journal=None,
                   scheduler_opts: Optional[dict] = None,
                   partition: object = "auto"):
    """Device-check a ColumnarOps batch end-to-end at tensor speed.

    Returns (valid [B] bool, bad [B] int32) — ``bad`` is the op index of
    the first impossible completion (the original-history index for
    converted batches, else the line position; INT32_MAX when valid).
    Rows the encoder cannot bound are converted to Op lists and routed
    to ``host_fallback`` (default: the exact host engine). In
    verdict-only and ``details="invalid"`` modes, WIDE tail buckets
    (W >= 16) smaller than ``min_device_batch`` ride the native CPU
    engine on a side thread under the device window — the measured
    device/native cost crossover; narrow small buckets stay on device,
    and ``details=True`` keeps every row there.

    With ``details=True`` the return is a list of per-row result dicts
    matching the host engine's shape — {"valid", "op", "configs"} with
    the reference's truncate-to-10 config-sample discipline
    (checker.clj:104-107) — decoded from the latched device frontiers.
    ``details="invalid"`` decodes lazily: only invalid rows pay the
    per-row Python replay walk; valid rows return {"valid": True} bare.
    The reference renders analysis only for invalid results
    (checker.clj:98-103), so this is the replay product path's mode —
    it keeps the batch at tensor speed when most rows are clean.

    ``scheduler=True`` (default) runs the whole thing through the
    streaming bucket scheduler (ops.schedule): the columnar encode walk
    chunks into groups that overlap device dispatch, exact-W buckets
    consolidate into few W classes, and verdicts land incrementally as
    chunks decode. ``scheduler=False`` keeps the fully-encoded
    exact-W flow — the parity oracle the streamed path is tested
    against.

    Fault tolerance (scheduler path; doc/resilience.md): chunks run
    under the degradation ladder — watchdog + retry, OOM bisection,
    poison-row quarantine to ``host_fallback`` — so every row gets a
    verdict under any single fault. ``faults`` injects the checker
    nemesis (ops.faults). ``journal`` (store.ChunkJournal) makes
    retired chunk verdicts durable: rows the journal already holds are
    sliced out BEFORE encoding and never re-dispatched, and fresh
    verdicts append as chunks retire — the kill-and-resume seam.
    Resumed rows' detail dicts are bare verdicts (no config sample)
    marked ``resumed``. ``scheduler_opts`` forwards BucketScheduler
    knobs (chunk_rows, max_classes, ...).

    ``partition`` (default ``"auto"``): a KEYED batch (``cols.key``,
    the columnar form of a KV-valued workload) strains into its
    per-key sub-batch before encoding (ops.partition) — the
    P-compositional W collapse — and verdicts recombine per history:
    valid iff every key is, ``bad`` the smallest original bad-op index
    over the invalid keys, and (details mode) the witness sub's result
    verbatim plus ``independent_key``. The journal then rides the
    sub-batch's deterministic row order, so a resumed run
    re-dispatches zero decided sub-histories.
    """
    if partition and getattr(cols, "key", None) is not None:
        from .partition import (partition_columnar, recombine_details,
                                recombine_verdicts)
        pb = partition_columnar(cols)
        if pb is not None:
            inner = check_columnar(
                model, pb.cols, max_slots=max_slots,
                host_fallback=host_fallback, details=details,
                min_device_batch=min_device_batch, scheduler=scheduler,
                faults=faults, journal=journal,
                scheduler_opts=scheduler_opts, partition=False)
            if details:
                return recombine_details(inner, pb.sub_history,
                                         pb.sub_key, cols.batch)
            v, b, _ = recombine_verdicts(inner[0], inner[1],
                                         pb.sub_history, pb.sub_key,
                                         cols.batch)
            return v, b
    if journal is None or not scheduler:
        return _check_columnar_impl(
            model, cols, max_slots=max_slots, host_fallback=host_fallback,
            details=details, min_device_batch=min_device_batch,
            scheduler=scheduler, faults=faults,
            scheduler_opts=scheduler_opts, sink=None)
    decided = {r: d for r, d in journal.decided().items()
               if 0 <= r < cols.batch}
    keep = [r for r in range(cols.batch) if r not in decided]
    if len(keep) == cols.batch:
        sub = cols

        def sink(rows, valid, bad, prov):
            journal.record(rows, valid, bad, prov)
    else:
        sub = _cols_take(cols, keep)

        def sink(rows, valid, bad, prov):
            journal.record([keep[int(r)] for r in rows], valid, bad,
                           prov)
    inner = _check_columnar_impl(
        model, sub, max_slots=max_slots, host_fallback=host_fallback,
        details=details, min_device_batch=min_device_batch,
        scheduler=True, faults=faults, scheduler_opts=scheduler_opts,
        sink=sink)
    if not decided:
        return inner
    if details:
        results: List[Optional[dict]] = [None] * cols.batch
        for r, (vl, bd, pv) in decided.items():
            results[r] = _rehydrate_verdict(vl, bd, pv)
        for j, r in enumerate(keep):
            results[r] = inner[j]
        return results
    valid = np.ones(cols.batch, bool)
    bad = np.full(cols.batch, INT32_MAX, np.int32)
    for r, (vl, bd, pv) in decided.items():
        valid[r] = vl
        if vl is False and bd is not None:
            bad[r] = bd
    if keep:
        k = np.asarray(keep)
        iv, ib = inner
        valid[k] = iv
        bad[k] = ib
    return valid, bad


def _check_columnar_impl(model: Model, cols, *, max_slots, host_fallback,
                         details, min_device_batch, scheduler, faults,
                         scheduler_opts, sink):
    from ..checkers.linearizable import wgl_check
    from ..history.columnar import columnar_to_ops
    from .encode import encode_columnar
    from .statespace import enumerate_statespace

    assert details in (False, True, "invalid"), details
    space = enumerate_statespace(model, cols.kinds, MAX_PACKED_STATES)
    eff_slots = max_slots + (device_frontier_capacity()
                             if max_slots >= DATA_MAX_SLOTS else 0)
    valid = np.ones(cols.batch, bool)
    bad = np.full(cols.batch, INT32_MAX, np.int32)
    results: List[Optional[dict]] = [None] * cols.batch if details else None
    failures: List[Tuple[int, str]] = []
    fused_refine: List[int] = []
    host_fallback = host_fallback or wgl_check
    # Wide-tail shortcut: measured per-row device cost doubles per W
    # while the native engine's grows far more slowly — on one chip the
    # crossover sits at W~16 (W15: 0.12 s/row device vs ~0.3 native;
    # W16: 0.77 device vs ~0.4 native). Small W>=16 buckets therefore
    # ride the native engine ON A SIDE THREAD, chewed by the
    # otherwise-idle CPU UNDER the device window (the bench's overlap
    # discipline). Verdict-only and lazy-details callers only;
    # full-details mode keeps every row on device so valid rows'
    # config samples stay device-derived. Without the native engine the
    # wide rows must STAY on device: the host fallback's cost grows
    # exponentially in W, while the device check stays bounded.
    tail = None
    if min_device_batch > 1 and details in (False, "invalid"):
        try:
            from ..native import check_batch_native  # noqa: F401
            tail = _NativeTailWorker(model, cols)
        except Exception:
            tail = None
    sch = None
    if scheduler:
        from .schedule import (DIVERTED, BucketScheduler,
                               iter_columnar_groups)
        groups = iter_columnar_groups(space, cols, max_slots=eff_slots,
                                      failures=failures, fuse=True,
                                      renumber=True)
        sch = BucketScheduler(
            return_frontier=details,
            min_device_rows=min_device_batch if tail is not None else 0,
            faults=faults, **(scheduler_opts or {}))
        if sink is not None:
            sch.on_chunk = _columnar_chunk_recorder(sch, cols, sink)
        stream = sch.run(groups)
    else:
        DIVERTED = object()       # never yielded by the threaded path
        buckets, fails = encode_columnar(space, cols, max_slots=eff_slots)
        failures.extend(fails)
        if tail is not None:
            small = [b for b in buckets
                     if b.W >= 16 and 0 < b.batch < min_device_batch]
            small_ids = {id(b) for b in small}
            buckets = [b for b in buckets if id(b) not in small_ids]
            for b in small:
                tail.add(b.indices)
        stream = run_buckets_threaded(buckets, return_frontier=details)
    for batch, out in stream:
        if out is DIVERTED:
            tail.add(batch.indices)
            continue
        if isinstance(out, WindowOverflow):
            failures.extend((i, str(out)) for i in batch.indices)
            continue
        v, b, front = out
        idx = np.asarray(batch.indices)
        valid[idx] = v
        inv = np.nonzero(~v)[0]
        bad_rows = idx[~v]
        bad_lines = batch.ev_opidx[inv, b[~v]]
        bad[bad_rows] = (cols.index[bad_rows, bad_lines]
                         if cols.index is not None else bad_lines)
        # Rows whose first impossible completion fell inside a fused
        # run only know the run's FIRST member: re-derive exactly on
        # the host after the stream drains (fused_refine).
        fb = fused_bad_rows(batch, v, b)
        fused_refine.extend(int(idx[x]) for x in fb)
        fused_local = set(fb.tolist())
        if details:
            for bi, row in enumerate(batch.indices):
                if sch is not None and row in sch.quarantined:
                    continue       # placeholder; host-decided below
                if details == "invalid" and bool(v[bi]):
                    # Lazy mode's valid rows stay the bare contract
                    # dict; provenance appears only when it carries
                    # information (the row left the happy path).
                    results[row] = {"valid": True}
                    if sch is not None and row in sch.row_provenance:
                        results[row]["provenance"] = \
                            sch.row_provenance[row]
                    continue
                if bi in fused_local:
                    continue               # refined below
                # The columnar form already applied the prepared-history
                # contract (value propagation + identity drop) at
                # conversion: reconstruct with propagated invokes and
                # skip both complete() and the per-op drop recompute —
                # the decode walk still sees exactly the encoder's op
                # kinds and slot assignment. Renumbered rows decode
                # against their own sub-space (batch.spaces).
                ops = columnar_to_ops(cols, row, propagated=True)
                sp = batch.spaces[bi] if batch.spaces else space
                results[row] = _decode_result(
                    sp, ops, bool(v[bi]),
                    int(bad[row]) if not bool(v[bi]) else -1, front[bi],
                    predropped=True)
                if sch is not None:
                    results[row]["provenance"] = \
                        sch.row_provenance.get(row, "device")
    if sch is not None:
        # Rows the degradation ladder quarantined carry inert
        # placeholder verdicts in the stream: re-decide each through
        # the host engine (the failures path below), so every row gets
        # a real verdict under any fault schedule.
        failures.extend((i, f"quarantined: {why}")
                        for i, why in sch.quarantined.items())
    if tail is not None:
        for i, r in tail.finish():
            if r is None:                    # native engine unavailable
                failures.append((i, "small bucket"))
                continue
            valid[i] = r["valid"] is True
            if r["valid"] is False:
                bad[i] = r["op"].get("index", -1)
            if details == "invalid":
                # Native verdicts lack config samples; the rare invalid
                # row re-derives its full counterexample on the host.
                results[i] = ({"valid": True} if r["valid"] is True
                              else host_fallback(
                                  model, columnar_to_ops(cols, i)))
                results[i].setdefault("provenance", "host-fallback")
            if sink is not None:
                _sink_verdict(sink, i, r)
    if fused_refine:
        # Exact bad-index/counterexample recovery for rows that failed
        # inside a fused run. Verdict-only callers ride the native
        # batch engine when it exists; details callers take the host
        # engine's full result (the parity shape).
        hs = [columnar_to_ops(cols, i) for i in fused_refine]
        rs = None
        if not details:
            try:
                from ..native import check_batch_native
                rs = check_batch_native(model, hs)
            except Exception:
                rs = None
        if rs is None:
            rs = [host_fallback(model, h) for h in hs]
        for i, r in zip(fused_refine, rs):
            valid[i] = r["valid"] is True
            if r["valid"] is False:
                bad[i] = r["op"].get("index", -1)
            if details:
                r.setdefault("provenance", "host-fallback")
                results[i] = r
            if sink is not None:
                _sink_verdict(sink, i, r)
    for row, reason in failures:
        r = host_fallback(model, columnar_to_ops(cols, row))
        valid[row] = r["valid"] is True
        if r["valid"] is False:
            bad[row] = r["op"].get("index", -1)
        if details:
            r.setdefault("fallback", reason)
            r.setdefault("provenance", "host-fallback")
            results[row] = r
        if sink is not None:
            _sink_verdict(sink, row, r)
    if details:
        return results
    return valid, bad


def _sink_verdict(sink, row: int, r: dict) -> None:
    """Journal one host-decided row's final verdict through a write
    callable (a check_columnar sink that remaps sub-batch rows, or
    ChunkJournal.record directly) — the ONE result-dict→journal-record
    translation, so the two checkers' journal shapes cannot drift.
    Non-boolean verdicts ("unknown") are not journaled — a resumed run
    re-derives them."""
    if r.get("valid") is True:
        sink([row], [True], [None], ["host-fallback"])
    elif r.get("valid") is False:
        sink([row], [False], [r.get("op", {}).get("index")],
             ["host-fallback"])


def _columnar_chunk_recorder(sch, cols, sink):
    """on_chunk hook journaling device chunk verdicts as they retire
    (check_columnar shape: bad is the caller-level op index, mapped
    through cols.index). Fused-run failures and quarantined rows are
    skipped — they journal when their host-derived verdict lands."""
    def on_chunk(b, lo, hi, v, bad, fr):
        rows, vals, bads, provs = [], [], [], []
        for k in range(hi - lo):
            rp = lo + k
            i = b.indices[rp]
            if i in sch.quarantined:
                continue
            vk = bool(v[k])
            bd = None
            if not vk:
                ev = int(bad[k])
                if b.ev_type[rp, ev] == EV_FUSED:
                    continue
                line = int(b.ev_opidx[rp, ev])
                bd = (int(cols.index[i, line])
                      if cols.index is not None else line)
            rows.append(i)
            vals.append(vk)
            bads.append(bd)
            provs.append(sch.row_provenance.get(i, "device"))
        sink(rows, vals, bads, provs)
    return on_chunk


def check_batch_columnar(model: Model, histories: Sequence[List[Op]], *,
                         max_slots: int = 16, max_states: int = 64,
                         host_fallback=None, details=True,
                         min_device_batch: int = 1,
                         scheduler: bool = True, faults=None,
                         journal=None,
                         scheduler_opts: Optional[dict] = None,
                         partition: object = "auto") -> List[dict]:
    """Check recorded Op-list histories through the columnar fast path:
    one fused conversion walk (history.columnar.ops_to_columnar), one
    vectorized encode, one device dispatch per cost bucket. Falls back
    to the per-history path (``check_batch_tpu``) when the shared
    vocabulary's state space explodes. Per-history result dicts;
    ``details="invalid"`` skips the valid rows' Python decode (see
    check_columnar). KV-valued histories pre-partition into per-key
    sub-histories before conversion (``partition`` — see
    check_batch_tpu; KV values never reach the kind vocabulary)."""
    from ..history.columnar import ops_to_columnar
    from .statespace import StateSpaceExplosion

    if not histories:
        return []
    if partition:
        from .partition import partition_histories, recombine_details
        parts = partition_histories(histories,
                                    force=partition is True)
        if parts is not None:
            subs, sub_hist, sub_key = parts
            inner = check_batch_columnar(
                model, subs, max_slots=max_slots, max_states=max_states,
                host_fallback=host_fallback, details=details,
                min_device_batch=min_device_batch, scheduler=scheduler,
                faults=faults, journal=journal,
                scheduler_opts=scheduler_opts, partition=False)
            return recombine_details(inner, sub_hist, sub_key,
                                     len(histories))
    try:
        cols = ops_to_columnar(model, histories,
                               max_states=min(max_states,
                                              MAX_PACKED_STATES))
    except StateSpaceExplosion:
        return check_batch_tpu(model, histories, max_states=max_states,
                               max_slots=max_slots,
                               host_fallback=host_fallback,
                               min_device_batch=min_device_batch,
                               scheduler=scheduler, faults=faults,
                               journal=journal,
                               scheduler_opts=scheduler_opts)
    assert details in (True, "invalid"), details   # contract: List[dict]
    return check_columnar(model, cols, max_slots=max_slots, details=details,
                          host_fallback=host_fallback,
                          min_device_batch=min_device_batch,
                          scheduler=scheduler, faults=faults,
                          journal=journal, scheduler_opts=scheduler_opts)


def check_synth(model: Model, spec, *, synth: str = "device",
                return_meta: bool = False, **kw):
    """Generate-and-check a deterministic synthetic batch
    (ops.synth_device.SynthSpec) — the campaign/fuzz workhorse: the
    histories are born in the columnar layout on the chosen backend
    (``synth="device"`` jitted JAX; ``"numpy"`` the bit-identical host
    twin; ``"host"`` the legacy lockstep generators, byte-compatible
    with earlier rounds), then ride the full check_columnar pipeline —
    P-compositional partition via the batch's key column, streaming
    scheduler, fault ladder, and ChunkJournal resume (key journals on
    store.spec_digest(spec): the spec NAMES the batch, so a resumable
    campaign never materializes histories just to fingerprint them).
    Only the columnar families check here ("cas"/"wide"); "la" lowers
    to dependency graphs (checkers.cycle) instead. Returns
    check_columnar's shapes, plus the SynthMeta when
    ``return_meta=True``."""
    from .synth_device import synthesize
    assert spec.family in ("cas", "wide"), spec.family
    # The legacy host generators return Op lists for the wide family;
    # only cas is columnar on every backend.
    assert synth != "host" or spec.family == "cas", \
        "host-mode check_synth supports the cas family"
    cols, meta = synthesize(spec, synth, key_meta=False)
    out = check_columnar(model, cols, **kw)
    return (out, meta) if return_meta else out
