"""Dense-frontier WGL linearizability kernel.

The WGL configuration set (see jepsen_tpu.checkers.linearizable for the
algorithm spec; the reference delegates the same search to Knossos at
jepsen/src/jepsen/checker.clj:82-107) is represented densely as a boolean
frontier

    F[s, m] = 1  iff  config (state s, linearized-pending-set m) reachable

with ``m`` ranging over all 2^W subsets of the W pending-op slots. Events
(lowered by jepsen_tpu.ops.encode) drive a ``lax.scan``:

  * INVOKE slot k — record op kind k in the device slot table.
  * every event — close F under application of pending ops: for each
    occupied slot i, (s, m without i) → (target[s], m | i). One
    application is a static reshape splitting mask-bit i plus a V×V
    one-hot "transition matmul" on the state axis; closure iterates to
    fixpoint via ``lax.while_loop`` (monotone OR, so ≤ live-slot
    iterations; re-running converged lanes under vmap is idempotent).
  * OK slot — keep exactly the configs whose mask holds the slot's bit
    and clear it (a dynamic gather along the mask axis — no per-slot
    branching), freeing the slot. An empty survivor set means the
    completed op cannot be linearized: the history is invalid and the
    event index is recorded (it maps back to the offending op for
    Knossos-parity counterexample reporting).

Shapes are fully static: [V, 2^W] per history, vmapped over the batch and
shardable over the device mesh on the batch axis (jepsen_tpu.ops.mesh).
The mask axis provides long 128-lane vectors for the VPU and the
transition matmuls batch onto the MXU. Cost scales with V * 2^W * events,
so callers bucket histories by (V, W) cost class before batching.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from ..history.ops import Op
from ..models.core import Model
from .encode import (EV_INVOKE, EV_OK, EncodedBatch, EncodeFailure,
                     batch_encode, encode_history)

INT32_MAX = np.int32(2**31 - 1)


def _apply_slot(F: jnp.ndarray, i: int, tgt_i: jnp.ndarray,
                V: int, M: int) -> jnp.ndarray:
    """Close F one step under the op in slot ``i``: every config without
    bit i spawns (target-state, mask | bit i). ``tgt_i`` is the op's [V]
    transition vector (-1 where inconsistent; all -1 for empty slots)."""
    hi, lo = M >> (i + 1), 1 << i
    Fr = F.reshape(V, hi, 2, lo)
    src = Fr[:, :, 0, :].reshape(V, hi * lo)
    onehot = tgt_i[:, None] == jnp.arange(V, dtype=jnp.int32)[None, :]
    G = jnp.matmul(onehot.astype(jnp.bfloat16).T,
                   src.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) > 0
    out1 = Fr[:, :, 1:, :] | G.reshape(V, hi, 1, lo)
    return jnp.concatenate([Fr[:, :, :1, :], out1], axis=2).reshape(V, M)


def _complete_slot(F: jnp.ndarray, slot: jnp.ndarray, M: int) -> jnp.ndarray:
    """OK-completion of the op in (dynamic) slot: keep configs whose mask
    has the slot bit set, with the bit cleared."""
    idx = jnp.arange(M, dtype=jnp.int32)
    bit = jnp.int32(1) << slot
    survivors = jnp.take(F, idx | bit, axis=1)
    return jnp.where((idx & bit) == 0, survivors, False)


def make_kernel(V: int, W: int):
    """Build the single-history checker for static bounds (V, W).

    Returns ``check(ev_type, ev_slot, ev_trans, target) -> (valid, bad)``
    where ``bad`` is the event index of the first impossible completion
    (INT32_MAX when valid). vmap/shard over a leading batch axis.
    """
    M = 1 << W

    def closure(F, slot_trans, target):
        tgt = target[slot_trans]  # [W, V]; empty slots gather the
                                  # all-invalid sentinel row.

        def body(carry):
            F0, _ = carry
            Fn = F0
            for i in range(W):
                Fn = _apply_slot(Fn, i, tgt[i], V, M)
            return Fn, (Fn != F0).any()

        F, _ = lax.while_loop(lambda c: c[1], body, (F, jnp.bool_(True)))
        return F

    def check(ev_type, ev_slot, ev_trans, target):
        sentinel = jnp.int32(target.shape[0] - 1)

        def step(carry, ev):
            F, slot_trans, valid, bad = carry
            typ, slot, trans, idx = ev
            is_invoke = typ == EV_INVOKE
            is_ok = typ == EV_OK
            st1 = jnp.where(is_invoke,
                            slot_trans.at[slot].set(trans), slot_trans)
            Fc = closure(F, st1, target)
            F_ok = _complete_slot(Fc, slot, M)
            empty = is_ok & ~F_ok.any()
            F2 = jnp.where(is_ok, F_ok, Fc)
            st2 = jnp.where(is_ok, st1.at[slot].set(sentinel), st1)
            valid2 = valid & ~empty
            bad2 = jnp.minimum(bad, jnp.where(empty, idx, INT32_MAX))
            return (F2, st2, valid2, bad2), None

        N = ev_type.shape[0]
        F0 = jnp.zeros((V, M), jnp.bool_).at[0, 0].set(True)
        st0 = jnp.full((W,), sentinel, jnp.int32)
        idx = jnp.arange(N, dtype=jnp.int32)
        carry = (F0, st0, jnp.bool_(True), jnp.int32(INT32_MAX))
        (F, st, valid, bad), _ = lax.scan(
            step, carry, (ev_type, ev_slot, ev_trans, idx))
        return valid, bad

    return check


# One compiled batch kernel per static (V, W); jit caches per event-shape.
_BATCH_KERNELS: Dict[Tuple[int, int], object] = {}


def batch_kernel(V: int, W: int):
    key = (V, W)
    k = _BATCH_KERNELS.get(key)
    if k is None:
        k = jax.jit(jax.vmap(make_kernel(V, W), in_axes=(0, 0, 0, 0)))
        _BATCH_KERNELS[key] = k
    return k


def run_encoded_batch(batch: EncodedBatch) -> Tuple[np.ndarray, np.ndarray]:
    """Device-check an encoded batch. Returns (valid [B] bool, bad [B])."""
    if batch.batch == 0:
        return np.zeros((0,), bool), np.zeros((0,), np.int32)
    kern = batch_kernel(batch.V, batch.W)
    valid, bad = kern(batch.ev_type, batch.ev_slot,
                      batch.ev_trans, batch.target)
    return np.asarray(valid), np.asarray(bad)


def _result_for(row: int, batch: EncodedBatch, valid: np.ndarray,
                bad: np.ndarray, prepared: List[Op]) -> dict:
    if bool(valid[row]):
        return {"valid": True}
    ev = int(bad[row])
    op_index = int(batch.ev_opidx[row, ev])
    op = next((o for o in prepared if o.index == op_index), None)
    return {"valid": False,
            "op": op.to_dict() if op is not None else {"index": op_index}}


def check_batch_tpu(model: Model, histories: Sequence[List[Op]], *,
                    max_states: int = 64, max_slots: int = 24,
                    host_fallback=None) -> List[dict]:
    """Check many raw histories on device; per-history result dicts.

    Histories the encoder cannot bound (state-space explosion, pending
    window overflow) are delegated to ``host_fallback(model, history)``
    (default: the exact host engine).
    """
    from ..checkers.linearizable import prepare_history, wgl_check
    from ..history.core import index as index_history
    host_fallback = host_fallback or wgl_check

    for h in histories:
        if any(op.index is None for op in h):
            index_history(h)
    prepared = [prepare_history(h) for h in histories]
    batch = batch_encode(model, prepared,
                         max_states=max_states, max_slots=max_slots)
    valid, bad = run_encoded_batch(batch)

    results: List[Optional[dict]] = [None] * len(histories)
    for row, i in enumerate(batch.indices):
        results[i] = _result_for(row, batch, valid, bad, prepared[i])
    for i, reason in batch.failures:
        r = host_fallback(model, histories[i])
        r.setdefault("fallback", reason)
        results[i] = r
    return results


def check_one_tpu(model: Model, history: List[Op], **kw) -> dict:
    """Single-history device check (the Checker-protocol TPU backend)."""
    return check_batch_tpu(model, [history], **kw)[0]
