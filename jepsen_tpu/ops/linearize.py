"""Dense-frontier WGL linearizability kernel.

The WGL configuration set (see jepsen_tpu.checkers.linearizable for the
algorithm spec; the reference delegates the same search to Knossos at
jepsen/src/jepsen/checker.clj:82-107) is represented densely as a boolean
frontier

    F[s, m] = 1  iff  config (state s, linearized-pending-set m) reachable

with ``m`` ranging over all 2^W subsets of the W pending-op slots. The
host encoder (jepsen_tpu.ops.encode) reduces the history to ok-completion
events, each carrying a precomputed snapshot of the pending-slot table;
a ``lax.scan`` drives one event per step:

  * close F under application of pending ops: for each occupied slot i,
    (s, m w/o i) → (target[s], m | i). One application is a static
    reshape splitting mask-bit i plus a V×V one-hot "transition matmul"
    on the state axis; closure iterates to fixpoint via
    ``lax.while_loop`` (monotone OR, ≤ live-slots iterations;
    re-running converged lanes under vmap is idempotent);
  * keep exactly the configs whose mask holds the completing slot's bit,
    clear it (a dynamic gather along the mask axis — no per-slot
    branching). An empty survivor set means the completed op cannot be
    linearized: the history is invalid and the event index is recorded
    (it maps back to the offending op for Knossos-parity counterexample
    reporting).

Shapes are fully static: [V, 2^W] per history, vmapped over the batch and
shardable over the device mesh on the batch axis (jepsen_tpu.parallel).
The mask axis provides long 128-lane vectors for the VPU and the
transition matmuls batch onto the MXU. Cost scales with V * 2^W * events,
so callers bucket histories by (V, W) cost class before batching.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from ..history.ops import Op
from ..models.core import Model
from .encode import (EV_OK, EncodedBatch, EncodeFailure,
                     batch_encode, bucket_encode, encode_history)

INT32_MAX = np.int32(2**31 - 1)


def _apply_slot(F: jnp.ndarray, i: int, tgt_i: jnp.ndarray,
                V: int, M: int) -> jnp.ndarray:
    """Close F one step under the op in slot ``i``: every config without
    bit i spawns (target-state, mask | bit i). ``tgt_i`` is the op's [V]
    transition vector (-1 where inconsistent; all -1 for empty slots)."""
    hi, lo = M >> (i + 1), 1 << i
    Fr = F.reshape(V, hi, 2, lo)
    src = Fr[:, :, 0, :].reshape(V, hi * lo)
    onehot = tgt_i[:, None] == jnp.arange(V, dtype=jnp.int32)[None, :]
    G = jnp.matmul(onehot.astype(jnp.bfloat16).T,
                   src.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) > 0
    out1 = Fr[:, :, 1:, :] | G.reshape(V, hi, 1, lo)
    return jnp.concatenate([Fr[:, :, :1, :], out1], axis=2).reshape(V, M)


def _complete_slot(F: jnp.ndarray, slot: jnp.ndarray, M: int) -> jnp.ndarray:
    """OK-completion of the op in (dynamic) slot: keep configs whose mask
    has the slot bit set, with the bit cleared."""
    idx = jnp.arange(M, dtype=jnp.int32)
    bit = jnp.int32(1) << slot
    survivors = jnp.take(F, idx | bit, axis=1)
    return jnp.where((idx & bit) == 0, survivors, False)


def make_kernel(V: int, W: int):
    """Build the single-history checker for static bounds (V, W).

    Returns ``check(ev_type, ev_slot, ev_slots, target) -> (valid, bad)``
    where ``bad`` is the event index of the first impossible completion
    (INT32_MAX when valid). vmap/shard over a leading batch axis.
    """
    M = 1 << W

    def closure(F, slots_row, target):
        tgt = target[slots_row]  # [W, V]; empty slots gather the
                                 # all-invalid sentinel row.

        def body(carry):
            F0, _ = carry
            Fn = F0
            for i in range(W):
                Fn = _apply_slot(Fn, i, tgt[i], V, M)
            return Fn, (Fn != F0).any()

        F, _ = lax.while_loop(lambda c: c[1], body, (F, jnp.bool_(True)))
        return F

    def check(ev_type, ev_slot, ev_slots, target):
        def step(carry, ev):
            F, valid, bad = carry
            typ, slot, slots_row, idx = ev
            is_ok = typ == EV_OK
            Fc = closure(F, slots_row, target)
            F_ok = _complete_slot(Fc, slot, M)
            empty = is_ok & ~F_ok.any()
            F2 = jnp.where(is_ok, F_ok, F)
            return (F2, valid & ~empty,
                    jnp.minimum(bad, jnp.where(empty, idx, INT32_MAX))), None

        N = ev_type.shape[0]
        F0 = jnp.zeros((V, M), jnp.bool_).at[0, 0].set(True)
        carry = (F0, jnp.bool_(True), jnp.int32(INT32_MAX))
        (F, valid, bad), _ = lax.scan(
            step, carry, (ev_type, ev_slot, ev_slots,
                          jnp.arange(N, dtype=jnp.int32)))
        return valid, bad

    return check


# One compiled batch kernel per static (V, W); jit caches per event-shape.
_BATCH_KERNELS: Dict[Tuple[int, int], object] = {}


def batch_kernel(V: int, W: int):
    key = (V, W)
    k = _BATCH_KERNELS.get(key)
    if k is None:
        k = jax.jit(jax.vmap(make_kernel(V, W), in_axes=(0, 0, 0, 0)))
        _BATCH_KERNELS[key] = k
    return k


# Frontier-elements budget per device dispatch: B * V * 2^W bools. Keeps
# the scan carry (plus XLA's temporaries) well inside one chip's HBM even
# for info-heavy windows (W=16 → 0.5 MB/history).
MAX_FRONTIER_ELEMENTS = 1 << 27


def run_encoded_batch(batch: EncodedBatch) -> Tuple[np.ndarray, np.ndarray]:
    """Device-check an encoded batch. Returns (valid [B] bool, bad [B]).
    Large batches are chunked to bound device memory."""
    if batch.batch == 0:
        return np.zeros((0,), bool), np.zeros((0,), np.int32)
    kern = batch_kernel(batch.V, batch.W)
    per_hist = batch.V << batch.W
    chunk = max(1, MAX_FRONTIER_ELEMENTS // per_hist)
    valids, bads = [], []
    for lo in range(0, batch.batch, chunk):
        hi = min(lo + chunk, batch.batch)
        valid, bad = kern(batch.ev_type[lo:hi], batch.ev_slot[lo:hi],
                          batch.ev_slots[lo:hi], batch.target[lo:hi])
        valids.append(np.asarray(valid))
        bads.append(np.asarray(bad))
    return np.concatenate(valids), np.concatenate(bads)


def _result_for(row: int, batch: EncodedBatch, valid: np.ndarray,
                bad: np.ndarray, prepared: List[Op]) -> dict:
    if bool(valid[row]):
        return {"valid": True}
    ev = int(bad[row])
    op_index = int(batch.ev_opidx[row, ev])
    op = next((o for o in prepared if o.index == op_index), None)
    return {"valid": False,
            "op": op.to_dict() if op is not None else {"index": op_index}}


def check_batch_tpu(model: Model, histories: Sequence[List[Op]], *,
                    max_states: int = 64, max_slots: int = 16,
                    host_fallback=None) -> List[dict]:
    """Check many raw histories on device; per-history result dicts.

    Histories the encoder cannot bound (state-space explosion, pending
    window overflow) are delegated to ``host_fallback(model, history)``
    (default: the exact host engine).
    """
    from ..checkers.linearizable import prepare_history, wgl_check
    from ..history.core import index as index_history
    host_fallback = host_fallback or wgl_check

    for h in histories:
        if any(op.index is None for op in h):
            index_history(h)
    prepared = [prepare_history(h) for h in histories]
    buckets = bucket_encode(model, prepared,
                            max_states=max_states, max_slots=max_slots)

    results: List[Optional[dict]] = [None] * len(histories)
    for batch in buckets:
        valid, bad = run_encoded_batch(batch)
        for row, i in enumerate(batch.indices):
            results[i] = _result_for(row, batch, valid, bad, prepared[i])
        for i, reason in batch.failures:
            r = host_fallback(model, histories[i])
            r.setdefault("fallback", reason)
            results[i] = r
    return results


def check_one_tpu(model: Model, history: List[Op], **kw) -> dict:
    """Single-history device check (the Checker-protocol TPU backend)."""
    return check_batch_tpu(model, [history], **kw)[0]
