"""Vmapped O(n) invariant checkers — the batch/device twins of
jepsen_tpu.checkers.simple (reference semantics:
jepsen/src/jepsen/checker.clj:109-374).

Where the host checkers fold one history with Python sets/Counters, these
lower a *batch* of histories to [B, N] line tensors plus a shared value
vocabulary, then decide every history in one XLA dispatch:

  * set / total-queue / unique-ids are order-free multiset accounting —
    masked scatter-adds over the value domain ([B, V] count vectors),
    pure VPU work with no scan at all;
  * counter and (unordered) queue are order-dependent — a vmapped
    ``lax.scan`` over the line axis carries the running bounds /
    multiset per history.

Device kernels return count vectors / per-read bounds, and the host
decodes them into EXACTLY the dicts the host checkers produce (interval
strings, Counter dicts, fractions), so the two backends are
interchangeable behind the Checker protocol and parity-testable
field-for-field.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..history.ops import Op
from ..utils.core import fraction, integer_interval_set_str

# Line type codes (shared with history.columnar).
PAD = -1
T_INVOKE, T_OK, T_FAIL, T_INFO = 0, 1, 2, 3
_TCODE = {"invoke": T_INVOKE, "ok": T_OK, "fail": T_FAIL, "info": T_INFO}

NONE_SENTINEL = np.int32(-2**31)  # "no value" in int32 value columns

# One jitted kernel is cached per vocabulary/shape key. Bucketing keys
# to powers of two caps distinct compilations at ~31 per family, and the
# bound below keeps a long-lived checker process from accumulating
# compiled kernels without limit (same rationale as DISPATCH_LOG).
_KERNEL_CACHE_LIMIT = 32


def _pow2(n: int) -> int:
    """Smallest power of two >= n (>= 1)."""
    return 1 << max(n - 1, 0).bit_length()


def _cached_kernel(cache: Dict, key, build, limit: int = 0):
    """Bounded per-family kernel cache with LRU eviction: a hit
    reinserts the entry at the MRU end (dicts preserve insertion
    order), and overflow evicts only the single least-recently-used
    kernel — a long-lived process cycling through limit+1 shapes keeps
    every warm compile but one, where wholesale clearing would recompile
    the lot. Shared by the fold kernels and the dependency-graph
    closure kernels (ops.graph)."""
    limit = limit or _KERNEL_CACHE_LIMIT
    k = cache.pop(key, None)
    if k is None:
        if len(cache) >= limit:
            cache.pop(next(iter(cache)))
        k = build()
    cache[key] = k
    return k


@dataclass
class FoldBatch:
    """A batch of histories lowered for the fold kernels.

    typ/f/val/proc — int32 [B, N] (PAD-padded); ``val`` holds dense
    vocabulary ids (``vocab`` maps them back) unless the encoder was
    asked for raw integer values (counter arithmetic). ``extra`` carries
    per-family side inputs (e.g. the set checker's final-read bitmap).
    """

    typ: np.ndarray
    f: np.ndarray
    val: np.ndarray
    proc: np.ndarray
    vocab: List
    extra: dict

    @property
    def batch(self) -> int:
        return int(self.typ.shape[0])


def _encode(histories: Sequence[Sequence[Op]], f_codes: Dict[str, int], *,
            raw_values: bool = False,
            vocab: Optional[dict] = None) -> FoldBatch:
    """Lower Op lists to line tensors. Ops whose ``f`` is not in
    ``f_codes`` are skipped (nemesis ops, reads handled via ``extra``).
    ``raw_values``: keep integer values verbatim (None -> sentinel)
    instead of interning into the shared vocabulary."""
    vocab_idx: dict = {}
    vocab_list: List = []
    rows = []
    for h in histories:
        lines = []
        for op in h:
            fc = f_codes.get(op.f)
            if fc is None or not isinstance(op.process, int):
                continue
            v = op.value
            if raw_values:
                vi = NONE_SENTINEL if v is None else int(v)
            else:
                if isinstance(v, list):
                    v = tuple(v)
                vi = vocab_idx.get(v)
                if vi is None:
                    vi = vocab_idx[v] = len(vocab_list)
                    vocab_list.append(v)
            lines.append((_TCODE[op.type], fc, vi, op.process))
        rows.append(lines)
    B = len(rows)
    N = max((len(r) for r in rows), default=0)
    typ = np.full((B, max(N, 1)), PAD, np.int32)
    f = np.zeros((B, max(N, 1)), np.int32)
    val = np.full((B, max(N, 1)), NONE_SENTINEL, np.int32)
    proc = np.zeros((B, max(N, 1)), np.int32)
    for r, lines in enumerate(rows):
        for j, (t, fc, vi, p) in enumerate(lines):
            typ[r, j] = t
            f[r, j] = fc
            val[r, j] = vi
            proc[r, j] = p
    return FoldBatch(typ=typ, f=f, val=val, proc=proc, vocab=vocab_list,
                     extra={})


def _counts(typ, f, val, t_code, f_code, V):
    """[V] int32 counts of value occurrences on (type, f) lines."""
    mask = (typ == t_code) & (f == f_code) & (val >= 0)
    return jnp.zeros((V,), jnp.int32).at[
        jnp.clip(val, 0, V - 1)].add(mask.astype(jnp.int32))


# ------------------------------------------------------------------ set

F_ADD, F_READ = 0, 1

_SET_KERNELS: Dict[int, object] = {}


def _set_kernel(V: int):
    def build():
        def one(typ, f, val, final_read):
            att = _counts(typ, f, val, T_INVOKE, F_ADD, V) > 0
            add = _counts(typ, f, val, T_OK, F_ADD, V) > 0
            ok = final_read & att
            unexpected = final_read & ~att
            lost = add & ~final_read
            recovered = ok & ~add
            return att, ok, unexpected, lost, recovered

        return jax.jit(jax.vmap(one))

    return _cached_kernel(_SET_KERNELS, V, build)


def _final_read_bitmap(histories, enc: FoldBatch):
    """Lower each row's last ok :read (a value *list*) to a [B, V]
    bitmap over the batch vocabulary. Never-attempted elements extend
    the decoded domain first so the bitmap allocates once at its final
    pow2 width. Returns (V, final, has_read, finals)."""
    vocab_idx = {v: i for i, v in enumerate(enc.vocab)}
    finals: List[Optional[list]] = []
    for h in histories:
        fr = None
        for op in h:
            if op.is_ok and op.f == "read":
                fr = op.value
        finals.append(fr)
        for v in (fr or ()):
            v = tuple(v) if isinstance(v, list) else v
            if v not in vocab_idx:
                vocab_idx[v] = len(enc.vocab)
                enc.vocab.append(v)
    V = _pow2(max(len(enc.vocab), 1))
    final = np.zeros((enc.batch, V), bool)
    has_read = np.zeros(enc.batch, bool)
    for r, fr in enumerate(finals):
        if fr is None:
            continue
        has_read[r] = True
        for v in fr:
            final[r, vocab_idx[tuple(v) if isinstance(v, list) else v]] = \
                True
    return V, final, has_read, finals


def check_sets_batch(histories: Sequence[Sequence[Op]]) -> List[dict]:
    """Batch twin of checkers.simple.SetChecker — :add ops + a final
    :read of the whole set (checker.clj:131-178); one device dispatch
    for the whole batch."""
    enc = _encode(histories, {"add": F_ADD})
    V, final, has_read, _ = _final_read_bitmap(histories, enc)
    att, ok, unexpected, lost, recovered = (
        np.asarray(a) for a in _set_kernel(V)(enc.typ, enc.f, enc.val,
                                              final))

    def decode(r: int) -> dict:
        if not has_read[r]:
            return {"valid": "unknown", "error": "Set was never read"}
        els = lambda m: {enc.vocab[i] for i in np.nonzero(m[r])[0]}  # noqa
        n_att = int(att[r].sum())
        return {
            "valid": not lost[r].any() and not unexpected[r].any(),
            "ok": integer_interval_set_str(els(ok)),
            "lost": integer_interval_set_str(els(lost)),
            "unexpected": integer_interval_set_str(els(unexpected)),
            "recovered": integer_interval_set_str(els(recovered)),
            "ok-frac": fraction(int(ok[r].sum()), n_att),
            "unexpected-frac": fraction(int(unexpected[r].sum()), n_att),
            "lost-frac": fraction(int(lost[r].sum()), n_att),
            "recovered-frac": fraction(int(recovered[r].sum()), n_att),
        }

    return [decode(r) for r in range(enc.batch)]


# ---------------------------------------------- cockroach-style sets

_CRDB_SET_KERNELS: Dict[int, object] = {}


def _crdb_set_kernel(V: int):
    def build():
        def one(typ, f, val, final_read):
            att = _counts(typ, f, val, T_INVOKE, F_ADD, V) > 0
            add = _counts(typ, f, val, T_OK, F_ADD, V) > 0
            failed = _counts(typ, f, val, T_FAIL, F_ADD, V) > 0
            unsure = _counts(typ, f, val, T_INFO, F_ADD, V) > 0
            ok = final_read & add
            unexpected = final_read & ~att
            revived = final_read & failed
            lost = add & ~final_read
            recovered = final_read & unsure
            return att, failed, ok, unexpected, revived, lost, recovered

        return jax.jit(jax.vmap(one))

    return _cached_kernel(_CRDB_SET_KERNELS, V, build)


def check_crdb_sets_batch(histories: Sequence[Sequence[Op]]) -> List[dict]:
    """The cockroach sets checker (cockroachdb/src/jepsen/cockroach/
    sets.clj:21-101), distinct from the knossos-style set fold: ok means
    read AND definitely added; ``revived`` elements were reported failed
    yet appear in the final read; ``recovered`` were indeterminate adds
    that appear; duplicates in the final read list are violations.
    Valid iff no lost, unexpected, duplicate, or revived elements."""
    from collections import Counter

    from ..history.core import complete
    histories = [complete(list(h)) for h in histories]
    enc = _encode(histories, {"add": F_ADD})
    V, final, has_read, finals = _final_read_bitmap(histories, enc)
    dups = [sorted(v for v, c in Counter(
                tuple(x) if isinstance(x, list) else x
                for x in (fr or ())).items() if c > 1)
            for fr in finals]
    att, failed, ok, unexpected, revived, lost, recovered = (
        np.asarray(a) for a in _crdb_set_kernel(V)(enc.typ, enc.f,
                                                   enc.val, final))

    def decode(r: int) -> dict:
        if not has_read[r]:
            return {"valid": "unknown", "error": "Set was never read"}
        els = lambda m: {enc.vocab[i] for i in np.nonzero(m[r])[0]}  # noqa
        n_att = int(att[r].sum())
        n_fail = int(failed[r].sum())
        return {
            "valid": (not lost[r].any() and not unexpected[r].any()
                      and not dups[r] and not revived[r].any()),
            "duplicates": dups[r],
            "ok": integer_interval_set_str(els(ok)),
            "lost": integer_interval_set_str(els(lost)),
            "unexpected": integer_interval_set_str(els(unexpected)),
            "recovered": integer_interval_set_str(els(recovered)),
            "revived": integer_interval_set_str(els(revived)),
            "ok-frac": fraction(int(ok[r].sum()), n_att),
            "revived-frac": fraction(int(revived[r].sum()), n_fail),
            "unexpected-frac": fraction(int(unexpected[r].sum()), n_att),
            "lost-frac": fraction(int(lost[r].sum()), n_att),
            "recovered-frac": fraction(int(recovered[r].sum()), n_att),
        }

    return [decode(r) for r in range(enc.batch)]


def crdb_set_checker_tpu():
    return BatchFoldChecker(check_crdb_sets_batch)


# ---------------------------------------------------------- total-queue

F_ENQ, F_DEQ = 0, 1

_TQ_KERNELS: Dict[int, object] = {}


def _tq_kernel(V: int):
    def build():
        def one(typ, f, val):
            att = _counts(typ, f, val, T_INVOKE, F_ENQ, V)
            enq = _counts(typ, f, val, T_OK, F_ENQ, V)
            deq = _counts(typ, f, val, T_OK, F_DEQ, V)
            ok = jnp.minimum(deq, att)
            unexpected = jnp.where(att == 0, deq, 0)
            duplicated = jnp.where(att > 0, jnp.maximum(deq - att, 0), 0)
            lost = jnp.maximum(enq - deq, 0)
            recovered = jnp.maximum(ok - enq, 0)
            return att, ok, unexpected, duplicated, lost, recovered

        return jax.jit(jax.vmap(one))

    return _cached_kernel(_TQ_KERNELS, V, build)


def check_total_queues_batch(histories: Sequence[Sequence[Op]]
                             ) -> List[dict]:
    """Batch twin of checkers.simple.TotalQueueChecker — what goes in
    must come out (checker.clj:214-271), drain ops expanded."""
    from ..checkers.simple import expand_queue_drain_ops
    histories = [expand_queue_drain_ops(list(h)) for h in histories]
    enc = _encode(histories, {"enqueue": F_ENQ, "dequeue": F_DEQ})
    V = _pow2(max(len(enc.vocab), 1))
    att, ok, unexpected, duplicated, lost, recovered = (
        np.asarray(a) for a in _tq_kernel(V)(enc.typ, enc.f, enc.val))

    def decode(r: int) -> dict:
        cnt = lambda m: {enc.vocab[i]: int(m[r, i])  # noqa: E731
                         for i in np.nonzero(m[r])[0]}
        n_att = int(att[r].sum())
        return {
            "valid": not lost[r].any() and not unexpected[r].any(),
            "lost": cnt(lost),
            "unexpected": cnt(unexpected),
            "duplicated": cnt(duplicated),
            "recovered": cnt(recovered),
            "ok-frac": fraction(int(ok[r].sum()), n_att),
            "unexpected-frac": fraction(int(unexpected[r].sum()), n_att),
            "duplicated-frac": fraction(int(duplicated[r].sum()), n_att),
            "lost-frac": fraction(int(lost[r].sum()), n_att),
            "recovered-frac": fraction(int(recovered[r].sum()), n_att),
        }

    return [decode(r) for r in range(enc.batch)]


# ----------------------------------------------------------- unique-ids

F_GEN = 0

_IDS_KERNELS: Dict[int, object] = {}


def _ids_kernel(V: int):
    def build():
        def one(typ, f, val):
            acks = _counts(typ, f, val, T_OK, F_GEN, V)
            attempted = ((typ == T_INVOKE) & (f == F_GEN)).sum()
            return acks, attempted

        return jax.jit(jax.vmap(one))

    return _cached_kernel(_IDS_KERNELS, V, build)


def check_unique_ids_batch(histories: Sequence[Sequence[Op]]
                           ) -> List[dict]:
    """Batch twin of checkers.simple.UniqueIdsChecker — acknowledged
    :generate ops return distinct ids (checker.clj:273-318)."""
    enc = _encode(histories, {"generate": F_GEN})
    V = _pow2(max(len(enc.vocab), 1))
    acks, attempted = (np.asarray(a) for a in _ids_kernel(V)(
        enc.typ, enc.f, enc.val))

    def decode(r: int) -> dict:
        n_acks = int(acks[r].sum())
        dup_idx = np.nonzero(acks[r] > 1)[0]
        dups = {enc.vocab[i]: int(acks[r, i]) for i in dup_idx}
        seen = [enc.vocab[i] for i in np.nonzero(acks[r] > 0)[0]]
        rng = [min(seen), max(seen)] if seen else [None, None]
        top = dict(sorted(dups.items(), key=lambda kv: -kv[1])[:48])
        return {
            "valid": not dups,
            "attempted-count": int(attempted[r]),
            "acknowledged-count": n_acks,
            "duplicated-count": len(dups),
            "duplicated": top,
            "range": rng,
        }

    return [decode(r) for r in range(enc.batch)]


# -------------------------------------------------------------- counter

_COUNTER_KERNEL = None


def _counter_kernel():
    global _COUNTER_KERNEL
    if _COUNTER_KERNEL is None:
        def one(typ, f, val, proc, P):
            def step(carry, line):
                lower, upper, p_low, p_val, p_act = carry
                t, fc, v, p = line
                is_inv_read = (t == T_INVOKE) & (fc == F_READ)
                is_ok_read = (t == T_OK) & (fc == F_READ)
                is_inv_add = (t == T_INVOKE) & (fc == F_ADD)
                is_ok_add = (t == T_OK) & (fc == F_ADD)
                emit = is_ok_read & p_act[p]
                out = (p_low[p], p_val[p], upper, emit)
                p_low = p_low.at[p].set(jnp.where(is_inv_read, lower,
                                                  p_low[p]))
                p_val = p_val.at[p].set(jnp.where(is_inv_read, v,
                                                  p_val[p]))
                p_act = p_act.at[p].set(jnp.where(
                    is_inv_read, True, p_act[p] & ~is_ok_read))
                add = jnp.where(v == NONE_SENTINEL, 0, v)
                upper = upper + jnp.where(is_inv_add, add, 0)
                lower = lower + jnp.where(is_ok_add, add, 0)
                return (lower, upper, p_low, p_val, p_act), out

            init = (jnp.int32(0), jnp.int32(0),
                    jnp.zeros((P,), jnp.int32),
                    jnp.full((P,), NONE_SENTINEL, jnp.int32),
                    jnp.zeros((P,), bool))
            _, (lows, vals, ups, emits) = jax.lax.scan(
                step, init, (typ, f, val, proc))
            return lows, vals, ups, emits

        _COUNTER_KERNEL = jax.jit(jax.vmap(one, in_axes=(0, 0, 0, 0, None)),
                                  static_argnums=(4,))
    return _COUNTER_KERNEL


def _counter_overflow_risk(history: Sequence[Op]) -> bool:
    """True when a history's counter arithmetic cannot safely ride the
    int32 device path: a value outside int32 range (which also covers a
    collision with NONE_SENTINEL = -2^31), or running add sums that
    could exceed int32 bounds. jax x64 is off, so the honest fallback is
    the arbitrary-precision host checker, not a downcast int64 column."""
    lim = 2**31 - 1
    total = 0
    for op in history:
        v = op.value
        if v is None or op.f not in ("add", "read"):
            continue
        if not isinstance(v, int) or not (-lim <= v <= lim):
            return True  # non-int (e.g. float) or out of int32 range
        if op.f == "add":
            total += abs(v)
            if total > lim:
                return True
    return False


def check_counters_batch(histories: Sequence[Sequence[Op]]) -> List[dict]:
    """Batch twin of checkers.simple.CounterChecker — each ok read lies
    within [ok adds at invoke, attempted adds at completion]
    (checker.clj:321-374). Order-dependent: a vmapped scan carries the
    running bounds and per-process pending reads. Rows whose values or
    running sums could overflow int32 detour to the host checker."""
    from ..checkers.simple import CounterChecker
    from ..history.core import complete
    histories = [complete(list(h)) for h in histories]
    out: List[Optional[dict]] = [None] * len(histories)
    host = [r for r, h in enumerate(histories)
            if _counter_overflow_risk(h)]
    for r in host:
        out[r] = CounterChecker().check(None, None, histories[r])
    dev = [r for r in range(len(histories)) if out[r] is None]
    if not dev:
        return out
    enc = _encode([histories[r] for r in dev],
                  {"add": F_ADD, "read": F_READ}, raw_values=True)
    # densify processes per row
    proc = np.zeros_like(enc.proc)
    for r in range(enc.batch):
        dense: dict = {}
        live = enc.typ[r] != PAD
        for j in np.nonzero(live)[0]:
            proc[r, j] = dense.setdefault(int(enc.proc[r, j]), len(dense))
    P = _pow2(max(int(proc.max(initial=0)) + 1, 1))
    lows, vals, ups, emits = (np.asarray(a) for a in _counter_kernel()(
        enc.typ, enc.f, enc.val, proc, P))

    def decode(r: int) -> dict:
        em = np.nonzero(emits[r])[0]
        reads = [[int(lows[r, j]),
                  None if vals[r, j] == NONE_SENTINEL else int(vals[r, j]),
                  int(ups[r, j])] for j in em]
        errors = [rd for rd in reads
                  if rd[1] is None or not (rd[0] <= rd[1] <= rd[2])]
        return {"valid": not errors, "reads": reads, "errors": errors}

    for i, r in enumerate(dev):
        out[r] = decode(i)
    return out


# ------------------------------------------------- queue (unordered)

_QUEUE_KERNELS: Dict[int, object] = {}


def _queue_kernel(V: int):
    def build():
        def one(typ, f, val):
            def step(carry, line):
                counts, valid, bad = carry
                t, fc, v, j = line
                v = jnp.clip(v, 0, V - 1)
                is_enq = (t == T_INVOKE) & (fc == F_ENQ)
                is_deq = (t == T_OK) & (fc == F_DEQ)
                counts = counts.at[v].add(jnp.where(is_enq, 1, 0))
                missing = is_deq & (counts[v] == 0)
                counts = counts.at[v].add(jnp.where(is_deq & ~missing,
                                                    -1, 0))
                first = missing & valid
                return (counts, valid & ~missing,
                        jnp.where(first, j, bad)), None

            N = typ.shape[0]
            init = (jnp.zeros((V,), jnp.int32), jnp.bool_(True),
                    jnp.int32(-1))
            (counts, valid, bad), _ = jax.lax.scan(
                step, init, (typ, f, val, jnp.arange(N, dtype=jnp.int32)))
            return valid, bad, counts

        return jax.jit(jax.vmap(one))

    return _cached_kernel(_QUEUE_KERNELS, V, build)


def check_queues_batch(histories: Sequence[Sequence[Op]]) -> List[dict]:
    """Batch twin of checkers.simple.QueueChecker with the unordered
    queue model (checker.clj:109-129): assume every non-failing enqueue
    succeeded, only ok dequeues succeeded; a dequeue of an element not
    in the multiset is the violation."""
    enc = _encode(histories, {"enqueue": F_ENQ, "dequeue": F_DEQ})
    V = _pow2(max(len(enc.vocab), 1))
    valid, bad, counts = (np.asarray(a) for a in _queue_kernel(V)(
        enc.typ, enc.f, enc.val))

    def decode(r: int) -> dict:
        if valid[r]:
            final = {enc.vocab[i]: int(counts[r, i])
                     for i in np.nonzero(counts[r])[0]}
            return {"valid": True, "final-queue": final}
        j = int(bad[r])
        v = enc.vocab[enc.val[r, j]] if enc.val[r, j] >= 0 else None
        return {"valid": False,
                "error": f"can't dequeue {v!r}"}

    return [decode(r) for r in range(enc.batch)]


# ------------------------------------------------------ queue (FIFO)

_FIFO_KERNELS: Dict[int, object] = {}


def _fifo_kernel(Nmax: int):
    def build():
        def one(typ, f, val):
            def step(carry, line):
                buf, head, tail, valid, bad, bad_head = carry
                t, fc, v, j = line
                is_enq = (t == T_INVOKE) & (fc == F_ENQ)
                is_deq = (t == T_OK) & (fc == F_DEQ)
                buf = buf.at[jnp.clip(tail, 0, Nmax - 1)].set(
                    jnp.where(is_enq, v, buf[jnp.clip(tail, 0, Nmax - 1)]))
                tail = tail + jnp.where(is_enq, 1, 0)
                empty = head >= tail
                wrong = is_deq & (empty | (buf[jnp.clip(head, 0, Nmax - 1)]
                                           != v))
                first = wrong & valid
                head = head + jnp.where(is_deq & ~wrong, 1, 0)
                return (buf, head, tail, valid & ~wrong,
                        jnp.where(first, j, bad),
                        jnp.where(first, head, bad_head)), None

            N = typ.shape[0]
            init = (jnp.zeros((Nmax,), jnp.int32), jnp.int32(0),
                    jnp.int32(0), jnp.bool_(True), jnp.int32(-1),
                    jnp.int32(-1))
            (buf, head, tail, valid, bad, bad_head), _ = jax.lax.scan(
                step, init, (typ, f, val,
                             jnp.arange(N, dtype=jnp.int32)))
            return valid, bad, bad_head, head, tail

        return jax.jit(jax.vmap(one))

    return _cached_kernel(_FIFO_KERNELS, Nmax, build)


def check_fifo_queues_batch(histories: Sequence[Sequence[Op]]
                            ) -> List[dict]:
    """Strict-order queue fold (the FIFOQueue model's semantics,
    model.clj:87-105, folded like checker.clj:109-129): assume every
    non-failing enqueue succeeded in invocation order; each ok dequeue
    must return the element at the head. The scan carries a ring of
    enqueued values per history."""
    enc = _encode(histories, {"enqueue": F_ENQ, "dequeue": F_DEQ})
    Nmax = _pow2(max(enc.typ.shape[1], 1))
    valid, bad, bad_head, head, tail = (
        np.asarray(a) for a in _fifo_kernel(Nmax)(enc.typ, enc.f,
                                                  enc.val))
    from ..models.core import FIFOQueue

    def _value(vi: int):
        # Sequence payloads round-trip the codec as lists; decode the
        # interned tuple form back so parity with the host holds.
        v = enc.vocab[vi]
        return list(v) if isinstance(v, tuple) else v

    def decode(r: int) -> dict:
        if valid[r]:
            # Remaining queue = enqueued values (invoke order) [head:tail].
            enq = [_value(vi) for t, fc, vi in
                   zip(enc.typ[r], enc.f[r], enc.val[r])
                   if t == T_INVOKE and fc == F_ENQ and vi >= 0]
            return {"valid": True,
                    "final-queue": FIFOQueue(
                        enq[int(head[r]):int(tail[r])])}
        j = int(bad[r])
        v = _value(enc.val[r, j]) if enc.val[r, j] >= 0 else None
        # Host-parity error text (models.core.FIFOQueue.step); empty
        # iff the head AT THE FAILURE had consumed every prior enqueue.
        n_enq_before = int(((enc.typ[r, :j] == T_INVOKE)
                            & (enc.f[r, :j] == F_ENQ)).sum())
        if int(bad_head[r]) >= n_enq_before:
            return {"valid": False,
                    "error": f"can't dequeue {v!r} from empty queue"}
        return {"valid": False, "error": f"can't dequeue {v!r}"}

    return [decode(r) for r in range(enc.batch)]

class BatchFoldChecker:
    """Checker-protocol adapter over a batch fold (single histories ride
    a batch of one; real scale comes from the *_batch functions /
    independent key batching)."""

    def __init__(self, fold):
        self.fold = fold

    def check(self, test, model, history, opts=None) -> dict:
        return self.fold([history])[0]


def set_checker_tpu():
    return BatchFoldChecker(check_sets_batch)


def total_queue_checker_tpu():
    return BatchFoldChecker(check_total_queues_batch)


def unique_ids_checker_tpu():
    return BatchFoldChecker(check_unique_ids_batch)


def counter_checker_tpu():
    return BatchFoldChecker(check_counters_batch)


def queue_checker_tpu():
    return BatchFoldChecker(check_queues_batch)


def fifo_queue_checker_tpu():
    return BatchFoldChecker(check_fifo_queues_batch)
